"""DynamicBatcher — deadline-bounded request coalescing.

Reference counterpart: none — MXNet 1.x served one `module.predict` call
per client batch and left coalescing to external model servers (MMS).
Folding it into the framework is what the compiled-bucket design wants:
the most efficient batch is *exactly a bucket*, so the batcher's job is to
grow a batch toward the largest ready bucket while the oldest request's
latency budget allows, then pad the remainder (the occupancy metric tracks
how much padding traffic costs).

Mechanics:

- ``submit(*arrays)`` enqueues one single-example request (no batch dim)
  and returns a :class:`ServeFuture`; the bounded queue applies
  backpressure — when full, ``submit`` raises :class:`QueueFullError`
  (or blocks up to ``block_secs`` when configured).
- a worker thread drains the queue: it flushes when (a) the batch reaches
  the largest batch bucket / ``max_batch``, or (b) the OLDEST queued
  request has waited ``max_delay_ms`` — the max-latency deadline.
- a flush stacks requests along a new batch axis, padding each example's
  bucketed axes (e.g. variable sequence lengths) up to the batch maximum;
  :meth:`CompiledModel.predict` then pads batch/seq up to the bucket and
  slices both back off, and each request's rows route to its future.

Env knobs (read at construction): ``MXTPU_SERVE_DEADLINE_MS`` (default
5 ms), ``MXTPU_SERVE_QUEUE_LIMIT`` (default 1024), ``MXTPU_SERVE_MAX_BATCH``
(default 0 = the table's largest batch bucket).
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as onp

from ..base import MXNetError
from ..lockcheck import make_lock
from .. import profiler
from ..telemetry import events as _tele
from ..telemetry import trace as _trace
from .compiled import CompiledModel, _as_numpy
from .metrics import ServeMetrics

__all__ = ["DynamicBatcher", "ServeFuture", "QueueFullError",
           "stack_examples", "make_registry_batcher"]


def make_registry_batcher(registry, name: str, **batcher_kw
                          ) -> "DynamicBatcher":
    """One started per-model batcher whose thunk resolves through
    ``registry`` at flush time (so a version swap redirects the very next
    batch) — the shared creation path of ``Server.batcher`` and
    ``Replica._batcher``. An unknown model raises at construction (the
    ctor resolves the thunk once for the model signature)."""
    return DynamicBatcher(lambda: registry.get(name),
                          metrics=ServeMetrics(model=name),
                          **batcher_kw).start()


def stack_examples(model: CompiledModel,
                   examples_per_request: Sequence[Sequence[onp.ndarray]]
                   ) -> List[onp.ndarray]:
    """Stack per-request example arrays (no batch dim) along a new batch
    axis, padding each request's bucketed non-batch axes (e.g. variable
    sequence lengths) to the batch maximum with the model's pad values.
    Shared by the batcher flush and the offline bench."""
    stacked = []
    for i in range(model._n_in):
        spec = model._input_axes[i]
        pv = model._pad_values[i]
        dtype = model._in_avals[i][1]
        examples = [onp.asarray(req[i]) for req in examples_per_request]
        # per-input non-batch bucketed axes, in EXAMPLE coordinates (the
        # request lacks the batch dim, so model axis k > batch axis maps
        # to example axis k-1)
        batch_axis = min(spec) if spec else 0
        var_axes = [a - (1 if a > batch_axis else 0)
                    for a in spec if a != batch_axis]
        if var_axes:
            maxes = {a: max(e.shape[a] for e in examples) for a in var_axes}
            padded = []
            for e in examples:
                widths = [(0, maxes.get(ax, e.shape[ax]) - e.shape[ax])
                          for ax in range(e.ndim)]
                padded.append(onp.pad(e, widths, mode="constant",
                                      constant_values=pv))
            examples = padded
        stacked.append(onp.stack(examples).astype(dtype, copy=False))
    return stacked


class QueueFullError(MXNetError):
    """The bounded request queue is full — backpressure; retry later or
    raise ``MXTPU_SERVE_QUEUE_LIMIT``."""


class ServeFuture:
    """Result handle for one submitted request."""

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def set_result(self, result) -> None:
        self._result = result
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the result/exception lands (or ``timeout``);
        returns whether it did — the non-raising poll the router's
        hedged wait loop uses."""
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("request still queued/in flight")
        if self._exc is not None:
            raise self._exc
        return self._result


#: process-wide serving-request correlation ids (telemetry events carry
#: them from admit through reply)
_REQUEST_IDS = itertools.count(1)


class _Request:
    __slots__ = ("arrays", "future", "t_enqueue", "rid", "span")

    def __init__(self, arrays):
        self.arrays = arrays
        self.future = ServeFuture()
        self.t_enqueue = time.perf_counter()
        self.rid = f"r{next(_REQUEST_IDS)}"
        #: open distributed-trace span covering queue→reply (set at
        #: admit time, finished at reply/error/abandon; None = untraced)
        self.span = None


class DynamicBatcher:
    """Coalesce single requests into bucket-sized batches for ``model``.

    ``model`` may be a :class:`CompiledModel` or a zero-arg callable
    returning one (the registry passes ``lambda: registry.get(name)`` so a
    version swap redirects the very next batch).

    Requests are single examples WITHOUT the batch dim: for a model whose
    input 0 is ``(batch, seq)``, submit a ``(seq,)`` array. Bucketed
    non-batch axes (``seq``) may differ per request; the flush pads them
    to the batch maximum.
    """

    def __init__(self, model, max_batch: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 queue_limit: Optional[int] = None,
                 block_secs: float = 0.0,
                 metrics: Optional[ServeMetrics] = None):
        self._model_thunk: Callable[[], CompiledModel] = (
            model if callable(model) and not isinstance(model, CompiledModel)
            else (lambda: model))
        from ..util import getenv
        m = self._model_thunk()
        self._batch_axis_name = m._primary_axis
        largest = m._table.sizes(self._batch_axis_name)[-1]
        if max_batch is None:
            max_batch = int(getenv("MXTPU_SERVE_MAX_BATCH"))
        # 0 = "the table's largest bucket" on both the env and param paths
        self.max_batch = min(int(max_batch) or largest, largest)
        self.max_delay_ms = float(
            getenv("MXTPU_SERVE_DEADLINE_MS")
            if max_delay_ms is None else max_delay_ms)
        self.queue_limit = int(
            getenv("MXTPU_SERVE_QUEUE_LIMIT")
            if queue_limit is None else queue_limit)
        self.block_secs = float(block_secs)
        self.metrics = metrics or ServeMetrics()
        self._queue: deque = deque()
        self._lock = make_lock("DynamicBatcher._lock")
        self._wake = threading.Event()
        self._stop = False
        self._closed = False
        self._worker: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "DynamicBatcher":
        if self._worker is None or not self._worker.is_alive():
            self._stop = False
            self._closed = False
            self._worker = threading.Thread(target=self._run,
                                            name="mx-serve-batcher",
                                            daemon=True)
            self._worker.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 10.0) -> None:
        """Stop the worker; ``drain=True`` serves what is queued first.
        Anything still queued afterwards — including requests submitted to
        a never-started batcher — fails with "batcher stopped" rather than
        leaving its future unresolved, and later submits are rejected
        immediately (a future enqueued onto a dead worker would never
        resolve). The drain deadline runs on the monotonic clock (a
        wall-clock step must not wedge — or instantly expire — shutdown),
        and the outcome publishes as one ``serve.drain`` event with the
        drained/abandoned split."""
        t0 = time.monotonic()
        served_before = self.metrics.requests
        self._closed = True  # reject new submits from this point on
        if self._worker is not None:
            if drain:
                while self.depth() and time.monotonic() - t0 < timeout:
                    time.sleep(0.005)
            self._stop = True
            self._wake.set()
            self._worker.join(timeout)
        with self._lock:  # closed above ⇒ nothing can enqueue after this
            leftovers = list(self._queue)
            self._queue.clear()
        for req in leftovers:
            req.future.set_exception(MXNetError("batcher stopped"))
            if req.span is not None:
                req.span.finish(outcome="abandoned")
        _tele.emit("serve.drain",
                   severity="warning" if leftovers else "info",
                   model=self.metrics.model, drain=bool(drain),
                   drained=self.metrics.requests - served_before,
                   abandoned=len(leftovers),
                   wall_ms=round((time.monotonic() - t0) * 1e3, 3))

    def worker_alive(self) -> bool:
        """True while the flush worker thread is running — the liveness
        bit a replica heartbeat reports."""
        w = self._worker
        return w is not None and w.is_alive()

    def retry_after_s(self) -> float:
        """Backoff hint for rejected/timed-out requests: roughly the time
        for the current queue to drain at one deadline-flush per batch."""
        batches = max(1, (self.depth() + self.max_batch - 1)
                      // self.max_batch)
        return round(max(0.05, batches * self.max_delay_ms / 1e3), 3)

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- client side ----------------------------------------------------
    def submit(self, *arrays) -> ServeFuture:
        """Enqueue one single-example request; returns its future.
        Malformed requests (wrong input count/rank) are rejected HERE so
        they cannot poison the innocent requests they would be co-batched
        with. Raises :class:`QueueFullError` when the bounded queue is
        full (after blocking up to ``block_secs`` when configured)."""
        model = self._model_thunk()
        if len(arrays) != model._n_in:
            raise MXNetError(
                f"request has {len(arrays)} inputs, model "
                f"takes {model._n_in}")
        req = _Request([_as_numpy(a) for a in arrays])
        for i, (a, (shape, _d)) in enumerate(
                zip(req.arrays, model._in_avals)):
            if a.ndim != len(shape) - 1:
                raise MXNetError(
                    f"request example has rank {a.ndim}; expected rank "
                    f"{len(shape) - 1} (the model input is {shape} with "
                    "the batch dim supplied by the batcher)")
            # non-bucketed example dims must match the compiled signature
            # exactly; bucketed ones are checked against the table so an
            # oversized request is rejected here, not in a shared flush
            spec = model._input_axes[i]
            batch_axis = min(spec) if spec else 0
            ex_names = {ax - (1 if ax > batch_axis else 0): name
                        for ax, name in spec.items() if ax != batch_axis}
            ex_shape = tuple(s for k, s in enumerate(shape)
                             if k != batch_axis)
            for ex_ax, size in enumerate(a.shape):
                name = ex_names.get(ex_ax)
                if name is None:
                    if size != ex_shape[ex_ax]:
                        raise MXNetError(
                            f"request input {i} has size {size} on axis "
                            f"{ex_ax}; the compiled model expects "
                            f"{ex_shape[ex_ax]} (only bucketed axes may "
                            "vary per request)")
                else:
                    model._table.bucket(name, size)  # raises on overflow
        # the request's span covers queue→reply; it parents under the
        # submitter's context (a router attempt, a wire-hop span), and
        # the worker thread resumes it at flush time — the cross-thread
        # half of the one-rooted-tree contract. It must be attached
        # BEFORE the locked append: the moment the worker can see req it
        # may flush it, and a span assigned after the fact would never
        # be resumed or finished.
        if _trace.current() is not None:
            req.span = _trace.start_span("serve.request", kind="server",
                                         request=req.rid,
                                         model=self.metrics.model)
        deadline = time.time() + self.block_secs
        while True:
            with self._lock:
                if self._closed:
                    if req.span is not None:
                        req.span.finish(error="batcher_stopped")
                    raise MXNetError("batcher stopped; submit rejected")
                if len(self._queue) < self.queue_limit:
                    self._queue.append(req)
                    self.metrics.record_depth(len(self._queue))
                    break
            if time.time() >= deadline:
                self.metrics.record_rejection()
                _tele.emit("serve.reject", severity="warning",
                           request_id=req.rid, model=self.metrics.model,
                           queue_limit=self.queue_limit)
                if req.span is not None:
                    req.span.finish(outcome="rejected")
                raise QueueFullError(
                    f"serve queue is full ({self.queue_limit} requests); "
                    "backpressure — retry with backoff or raise "
                    "MXTPU_SERVE_QUEUE_LIMIT")
            time.sleep(0.0005)
        with _trace.use(req.span.ctx if req.span is not None else None):
            _tele.emit("serve.admit", request_id=req.rid,
                       model=self.metrics.model, depth=self.depth())
        self._wake.set()
        return req.future

    # -- worker side ----------------------------------------------------
    def _run(self) -> None:
        while not self._stop:
            batch = self._gather()
            if batch:
                self._flush(batch)
                continue
            with self._lock:
                if self._queue:
                    remaining = (self.max_delay_ms / 1e3
                                 - (time.perf_counter()
                                    - self._queue[0].t_enqueue))
                else:
                    remaining = None  # idle: sleep until a submit wakes us
            self._wake.wait(timeout=max(remaining, 0.0005)
                            if remaining is not None else None)
            self._wake.clear()

    def _gather(self) -> List[_Request]:
        """Take a batch when one is ready: a full bucket immediately, or
        whatever is queued once the oldest request's deadline expires."""
        with self._lock:
            n = len(self._queue)
            if n == 0:
                return []
            oldest_wait_ms = (time.perf_counter()
                              - self._queue[0].t_enqueue) * 1e3
            if n < self.max_batch and oldest_wait_ms < self.max_delay_ms:
                return []
            take = min(n, self.max_batch)
            batch = [self._queue.popleft() for _ in range(take)]
            self.metrics.record_depth(len(self._queue))
            return batch

    def _flush(self, batch: List[_Request]) -> None:
        t0 = time.perf_counter()
        rids = [req.rid for req in batch]
        # the worker thread resumes the FIRST traced request's span for
        # the shared execution: the batch/pad/compute/unpad profiler
        # scopes become that request's subtree (its co-batched peers
        # record the shared flush by reference in their span attrs — a
        # span has one parent, a batch has many requests)
        lead = next((r for r in batch if r.span is not None), None)
        with _trace.use(lead.span.ctx if lead is not None else None):
            _tele.emit("serve.batch", model=self.metrics.model,
                       size=len(batch), request_ids=rids)
            try:
                # thunk inside the try: a failed registry resolve (e.g.
                # the model was unloaded) must fail THESE futures, not
                # kill the worker thread and hang every later submit
                model = self._model_thunk()
                with profiler.Scope("serve.batch"):
                    stacked = stack_examples(
                        model, [req.arrays for req in batch])
                    outs = model.predict(*stacked)
                self._scatter(batch, outs, model)
            except BaseException as e:  # noqa: BLE001 — routed to futures
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)
                    if req.span is not None:
                        req.span.finish(error=type(e).__name__)
                # failed batches must NOT count as served traffic
                self.metrics.record_failed_batch(len(batch))
                _tele.emit("serve.execute", severity="error",
                           model=self.metrics.model, size=len(batch),
                           request_ids=rids,
                           error=f"{type(e).__name__}: {e}")
                return
            dt_ms = (time.perf_counter() - t0) * 1e3
            bucket = model._table.bucket(self._batch_axis_name, len(batch))
            self.metrics.record_batch(len(batch), bucket, dt_ms)
            _tele.emit("serve.execute", model=self.metrics.model,
                       size=len(batch), bucket=bucket,
                       wall_ms=round(dt_ms, 3),
                       occupancy=round(len(batch) / bucket, 4) if bucket
                       else None)
        for req in batch:
            lat_ms = (time.perf_counter() - req.t_enqueue) * 1e3
            with _trace.use(req.span.ctx if req.span is not None else None):
                # latency observes under the request's context so a
                # sampled request pins its trace id as the histogram's
                # OpenMetrics exemplar — the p99-spike→trace link
                self.metrics.record_request(lat_ms)
                _tele.emit("serve.reply", request_id=req.rid,
                           model=self.metrics.model,
                           latency_ms=round(lat_ms, 3))
            if req.span is not None:
                attrs = {"latency_ms": round(lat_ms, 3),
                         "batch_size": len(batch)}
                if lead is not None and req is not lead:
                    attrs["exec_span"] = lead.span.ctx.span_id
                req.span.finish(**attrs)

    def _scatter(self, batch: List[_Request], outs, model: CompiledModel
                 ) -> None:
        """Route row ``i`` of every output to request ``i``; per-request
        variable axes are sliced to that request's true size."""
        multi = isinstance(outs, tuple)
        flat = list(outs) if multi else [outs]
        out_axes = model._output_axes
        if out_axes is None:
            out_axes = [{0: model._primary_axis}] * len(flat)
        arrs = [o.asnumpy() for o in flat]
        for i, req in enumerate(batch):
            picks = []
            for o, spec in zip(arrs, out_axes):
                row = o[i]
                # slice request-local variable axes (e.g. this request's
                # true seq length) — mapped via the request's OWN inputs
                for axis, name in spec.items():
                    if axis == 0:
                        continue
                    true = self._request_size(req, model, name)
                    if true is not None and axis - 1 < row.ndim \
                            and row.shape[axis - 1] > true:
                        sl = [slice(None)] * row.ndim
                        sl[axis - 1] = slice(0, true)
                        row = row[tuple(sl)]
                picks.append(row)
            req.future.set_result(tuple(picks) if multi else picks[0])

    @staticmethod
    def _request_size(req: _Request, model: CompiledModel,
                      name: str) -> Optional[int]:
        for a, spec in zip(req.arrays, model._input_axes):
            batch_axis = min(spec) if spec else 0
            for axis, nm in spec.items():
                if nm == name and axis != batch_axis:
                    ex_axis = axis - (1 if axis > batch_axis else 0)
                    if ex_axis < a.ndim:
                        return a.shape[ex_axis]
        return None

"""Router — health-checked failover routing over a set of replicas.

Reference counterpart: none in-framework — MMS deployments put a cloud
load balancer in front of N server processes and hoped. Here the routing
tier is framework-native so it can close the loop with the runtime it
fronts: the heartbeat reads real batcher progress, a failover retries the
*exact* queued request (futures fail fast on a killed replica), the
prewarm path is the compile ledger's zero-recompile contract, and the
training→serving weight pipe reuses ``fault.checkpoint``'s CRC-verified
``load_latest``.

Policies, all env-tunable (``MXTPU_SERVE_*``, see docs/env_vars.md):

- **Health**: a heartbeat loop (``MXTPU_SERVE_HEARTBEAT_MS``) probes each
  replica; a crash (chaos ``replica_kill``, dead batcher worker,
  ``LockOrderError`` from the request path) or a stall (queued requests
  with no flush progress for ``MXTPU_SERVE_STALL_S``) marks it unhealthy
  and a restarter thread rebuilds it — prewarming from the
  :class:`~incubator_mxnet_tpu.serve.artifact_cache.ArtifactCache` when
  the loader is wired through one.
- **Failover**: idempotent requests retry on a surviving replica with
  capped exponential backoff (``MXTPU_SERVE_RETRIES`` ×
  ``MXTPU_SERVE_RETRY_BACKOFF_MS``); per-request deadlines bound the
  total wait. One optional **hedged** attempt (``MXTPU_SERVE_HEDGE_MS``)
  races a duplicate on a second replica when the first is slow.
- **Admission / shedding**: per-tenant inflight caps
  (``MXTPU_SERVE_TENANT_INFLIGHT``) and a queue-depth overload threshold
  (``MXTPU_SERVE_SHED_DEPTH``) reject with :class:`ShedError` carrying
  ``retry_after`` — explicit load shedding instead of unbounded queueing,
  layered ON TOP of the per-replica ``DynamicBatcher`` backpressure.
- **Weight pipe**: :meth:`Router.sync_weights_once` pulls the newest
  **verified** checkpoint (CRC via ``fault.checkpoint.load_latest``),
  staging-checks it (every float array finite, names resolvable), and
  pushes it to every healthy replica via ``refresh_params`` — zero
  recompiles; a checkpoint that fails verification or staging is
  reported and never swapped in.

Every decision publishes telemetry: ``router.health`` (transitions),
``router.failover``, ``router.shed``, ``router.hedge``,
``router.weight_sync``, plus ``mxtpu_router_*`` counters and the
``mxtpu_serve_replicas_healthy`` gauge.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ..base import MXNetError
from ..lockcheck import LockOrderError, make_lock
from ..telemetry import events as _tele_events
from ..telemetry import trace as _trace
from .batcher import QueueFullError, ServeFuture
from .replica import Replica, ReplicaUnavailable

__all__ = ["Router", "ReplicaSet", "ShedError", "DeadlineExceeded",
           "TokenRateBudget"]


class TokenRateBudget:
    """Per-tenant tokens/sec QoS — the decode-era extension of the
    request-count inflight cap.

    A classic token bucket per tenant: ``rate`` tokens/sec sustained,
    ``burst`` depth (default one second's budget). :meth:`try_take` is
    consulted with a request's *estimated* token cost BEFORE it queues —
    shed-before-breach: a tenant over budget is refused at admission
    (cheap, with ``retry_after``) instead of after its generation has
    held decode batch rows. ``rate`` 0/unset = unlimited (every take
    succeeds). Thread-safe; refill is lazy on the monotonic clock.
    """

    def __init__(self, tokens_per_s: Optional[float] = None,
                 burst: Optional[float] = None):
        from ..util import getenv
        self.rate = float(getenv("MXTPU_SERVE_TENANT_TOKENS_PER_S")
                          if tokens_per_s is None else tokens_per_s)
        b = float(getenv("MXTPU_SERVE_TENANT_TOKEN_BURST")
                  if burst is None else burst)
        self.burst = b if b > 0 else max(self.rate, 1.0)
        self._lock = make_lock("TokenRateBudget._lock")
        self._level: Dict[str, float] = {}
        self._mark: Dict[str, float] = {}

    def enabled(self) -> bool:
        return self.rate > 0

    def try_take(self, tenant: str, tokens: float) -> bool:
        """Debit ``tokens`` from ``tenant``'s bucket if it fits; False =
        over budget (shed the request, do not queue it)."""
        if not self.enabled() or tokens <= 0:
            return True
        now = time.monotonic()
        with self._lock:
            level = self._level.get(tenant, self.burst)
            mark = self._mark.get(tenant, now)
            level = min(self.burst, level + (now - mark) * self.rate)
            if tokens > level:
                self._level[tenant] = level
                self._mark[tenant] = now
                return False
            self._level[tenant] = level - tokens
            self._mark[tenant] = now
            return True

    def headroom(self, tenant: str) -> float:
        """Current bucket level (tokens) — monitoring only."""
        if not self.enabled():
            return float("inf")
        now = time.monotonic()
        with self._lock:
            level = self._level.get(tenant, self.burst)
            mark = self._mark.get(tenant, now)
            return min(self.burst, level + (now - mark) * self.rate)

    def snapshot(self) -> Dict:
        with self._lock:
            return {"tokens_per_s": self.rate, "burst": self.burst,
                    "tenants": {t: round(v, 3)
                                for t, v in self._level.items()}}


class ShedError(MXNetError):
    """Request explicitly rejected by admission control / overload
    shedding / placement exhaustion. ``retry_after`` (seconds) is the
    client's backoff hint — the structured alternative to queueing
    unboundedly or dropping silently."""

    def __init__(self, msg: str, retry_after: float, reason: str = "shed"):
        super().__init__(f"{msg} (retry_after={retry_after:.3f}s)")
        self.retry_after = retry_after
        self.reason = reason


class DeadlineExceeded(MXNetError):
    """The per-request deadline expired before any replica produced a
    result. Carries ``retry_after`` like :class:`ShedError` so clients
    handle both rejection shapes uniformly."""

    def __init__(self, msg: str, retry_after: float):
        super().__init__(f"{msg} (retry_after={retry_after:.3f}s)")
        self.retry_after = retry_after
        self.reason = "deadline"


class ReplicaSet:
    """Fixed set of uniquely-named replicas with least-loaded pick."""

    def __init__(self, replicas: Sequence[Replica]):
        if not replicas:
            raise MXNetError("ReplicaSet needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise MXNetError(f"replica names must be unique, got {names}")
        self._replicas: Tuple[Replica, ...] = tuple(replicas)
        self._rr = itertools.count()

    def __iter__(self):
        return iter(self._replicas)

    def __len__(self):
        return len(self._replicas)

    def get(self, name: str) -> Replica:
        for r in self._replicas:
            if r.name == name:
                return r
        raise MXNetError(f"no replica {name!r} (have "
                         f"{[r.name for r in self._replicas]})")

    def healthy(self) -> List[Replica]:
        return [r for r in self._replicas if r.healthy()]

    def pick(self, exclude: Sequence[str] = ()) -> Optional[Replica]:
        """Healthy replica with the shallowest queue; ties rotate
        round-robin so equal-depth replicas share the load."""
        cands = [r for r in self._replicas
                 if r.healthy() and r.name not in exclude]
        if not cands:
            return None
        rot = next(self._rr)
        return min(((r.queue_depth(), (i + rot) % len(cands), r)
                    for i, r in enumerate(cands)),
                   key=lambda t: (t[0], t[1]))[2]

    def states(self) -> Dict[str, str]:
        return {r.name: r.state for r in self._replicas}


class Router:
    """Front door of the HA tier: admission → placement → deadline-bound
    wait → failover/hedge, plus the health loop and the weight pipe."""

    def __init__(self, replicas, *,
                 heartbeat_ms: Optional[float] = None,
                 stall_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff_ms: Optional[float] = None,
                 hedge_ms: Optional[float] = None,
                 shed_depth: Optional[int] = None,
                 tenant_inflight: Optional[int] = None,
                 tenant_tokens_per_s: Optional[float] = None,
                 tenant_token_burst: Optional[float] = None,
                 request_timeout_s: Optional[float] = None,
                 restart_backoff_s: float = 0.5):
        from ..util import getenv
        self.replicas = (replicas if isinstance(replicas, ReplicaSet)
                         else ReplicaSet(replicas))
        self.heartbeat_ms = float(getenv("MXTPU_SERVE_HEARTBEAT_MS")
                                  if heartbeat_ms is None else heartbeat_ms)
        self.stall_s = float(getenv("MXTPU_SERVE_STALL_S")
                             if stall_s is None else stall_s)
        self.retries = int(getenv("MXTPU_SERVE_RETRIES")
                           if retries is None else retries)
        self.backoff_ms = float(getenv("MXTPU_SERVE_RETRY_BACKOFF_MS")
                                if backoff_ms is None else backoff_ms)
        self.hedge_ms = float(getenv("MXTPU_SERVE_HEDGE_MS")
                              if hedge_ms is None else hedge_ms)
        self.shed_depth = int(getenv("MXTPU_SERVE_SHED_DEPTH")
                              if shed_depth is None else shed_depth)
        self.tenant_inflight = int(
            getenv("MXTPU_SERVE_TENANT_INFLIGHT")
            if tenant_inflight is None else tenant_inflight)
        self.token_budget = TokenRateBudget(tenant_tokens_per_s,
                                            tenant_token_burst)
        self.request_timeout_s = float(
            getenv("MXTPU_SERVE_REQUEST_TIMEOUT_S")
            if request_timeout_s is None else request_timeout_s)
        self.restart_backoff_s = float(restart_backoff_s)
        self._lock = make_lock("Router._lock")
        self._stop_evt = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self._sync_threads: Dict[str, threading.Thread] = {}
        self._restarting: set = set()
        self._restart_threads: Dict[str, threading.Thread] = {}
        self._inflight: Dict[str, int] = {}
        #: (model, ckpt_root) -> {"step", "fleet": {replica: restarts}}
        self._synced_steps: Dict[Tuple[str, str], Dict] = {}
        #: health-thread-private stall accounting {name: {batches, since}}
        self._progress: Dict[str, Dict] = {}
        self.health_errors = 0
        self.stats: Dict[str, int] = {
            "accepted": 0, "completed": 0, "shed": 0, "failed": 0,
            "deadline_exceeded": 0, "retries": 0, "failovers": 0,
            "hedges": 0, "hedge_wins": 0, "restarts": 0,
            "weight_syncs": 0}

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "Router":
        for rep in self.replicas:
            state = rep.state
            if state == "new":
                rep.start()
            elif state == "stopped":
                # a stopped replica's registry still holds its versions;
                # rebooting it is the restart path (fresh registry, the
                # loader re-runs against the artifact cache)
                rep.restart()
        if self._health_thread is None or not self._health_thread.is_alive():
            self._stop_evt.clear()
            self._health_thread = threading.Thread(
                target=self._health_loop, name="mx-serve-router-health",
                daemon=True)
            self._health_thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        # join the HEALTH thread first: only it spawns restarters, and
        # _schedule_restart no-ops once the stop event is set — so after
        # this join the restarter set can only shrink
        if self._health_thread is not None and self._health_thread.is_alive():
            self._health_thread.join(timeout=30.0)
        self._health_thread = None
        with self._lock:
            syncers = list(self._sync_threads.values())
            self._sync_threads.clear()
            restarters = list(self._restart_threads.values())
            self._restart_threads.clear()
        # restarter threads are joined BEFORE stopping the replicas so a
        # restart in flight cannot flip a member back to healthy under a
        # stopped tier (or race module teardown in tests)
        for t in syncers + restarters:
            if t.is_alive():
                t.join(timeout=30.0)
        for rep in self.replicas:
            rep.stop()

    # -- telemetry helpers ----------------------------------------------
    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.stats[key] += n

    @staticmethod
    def _counter(name: str, help_: str, **labels):
        from ..telemetry import metrics as _tmetrics
        return _tmetrics.counter(name, help_, **labels)

    def _emit(self, kind: str, severity: str = "info", **fields) -> None:
        from ..telemetry import events as _tele
        _tele.emit(kind, severity=severity, **fields)

    def retry_after_s(self) -> float:
        """Client backoff hint: scales with the shallowest healthy queue
        (roughly the wait for it to drain); 1s when nothing is healthy
        (a restart is in flight)."""
        healthy = self.replicas.healthy()
        if not healthy:
            return 1.0
        depth = min(r.queue_depth() for r in healthy)
        return round(min(5.0, 0.05 * (1 + depth)), 3)

    # -- admission ------------------------------------------------------
    def _shed(self, reason: str, msg: str, model: str,
              tenant: Optional[str]) -> ShedError:
        err = ShedError(msg, retry_after=self.retry_after_s(), reason=reason)
        self._bump("shed")
        self._counter("mxtpu_router_sheds_total",
                      "Requests explicitly shed by the router",
                      reason=reason).inc()
        self._emit("router.shed", severity="warning", model=model,
                   tenant=tenant, reason=reason,
                   retry_after=err.retry_after)
        return err

    def _deadline(self, msg: str) -> DeadlineExceeded:
        """Accounted constructor: an accepted request that times out must
        show up in the stats like its ShedError sibling, not read as
        permanently in flight."""
        self._bump("deadline_exceeded")
        self._counter("mxtpu_router_deadline_exceeded_total",
                      "Accepted requests that hit their deadline").inc()
        return DeadlineExceeded(msg, self.retry_after_s())

    def set_overload_policy(self, hedge_ms: Optional[float] = None,
                            shed_depth: Optional[int] = None) -> Dict:
        """Hot-swap the overload knobs on a live router — both are read
        per request (``_admit`` / ``_await_result``), so the change
        applies to the next admission with no restart and no inflight
        disruption. The flight director's serve-side remediation; the
        ``router.policy`` event makes every swap auditable even without
        the director's decision ring. Returns the previous values (the
        revert handle)."""
        prev = {"hedge_ms": self.hedge_ms, "shed_depth": self.shed_depth}
        if hedge_ms is not None:
            self.hedge_ms = float(hedge_ms)
        if shed_depth is not None:
            self.shed_depth = int(shed_depth)
        _tele_events.emit("router.policy", severity="info",
                          hedge_ms=self.hedge_ms,
                          shed_depth=self.shed_depth,
                          prev_hedge_ms=prev["hedge_ms"],
                          prev_shed_depth=prev["shed_depth"])
        return prev

    def _admit(self, model: str, tenant: Optional[str],
               est_tokens: int = 0) -> None:
        healthy = self.replicas.healthy()
        if not healthy:
            raise self._shed("no_healthy_replica",
                             "no healthy replica to accept the request",
                             model, tenant)
        if self.shed_depth and all(r.queue_depth() >= self.shed_depth
                                   for r in healthy):
            raise self._shed(
                "overloaded",
                f"every healthy replica is at/over the shed depth "
                f"({self.shed_depth})", model, tenant)
        key = tenant or "default"
        # tokens/sec QoS before the inflight seat: an over-budget tenant
        # is refused while the request is still cheap (nothing queued,
        # no decode rows held) — shed-before-breach
        if est_tokens and not self.token_budget.try_take(key, est_tokens):
            raise self._shed(
                "tenant_tokens",
                f"tenant {key!r} is over its tokens/sec budget "
                f"({self.token_budget.rate}/s, est {est_tokens} tokens)",
                model, tenant)
        if self.tenant_inflight:
            with self._lock:
                if self._inflight.get(key, 0) >= self.tenant_inflight:
                    over = True
                else:
                    self._inflight[key] = self._inflight.get(key, 0) + 1
                    over = False
            if over:
                raise self._shed(
                    "tenant_limit",
                    f"tenant {key!r} is at its inflight cap "
                    f"({self.tenant_inflight})", model, tenant)
        self._bump("accepted")

    def _release(self, tenant: Optional[str]) -> None:
        if self.tenant_inflight:
            key = tenant or "default"
            with self._lock:
                self._inflight[key] = max(0, self._inflight.get(key, 0) - 1)

    # -- request path ---------------------------------------------------
    def call(self, model: str, *arrays, timeout_s: Optional[float] = None,
             tenant: Optional[str] = None, idempotent: bool = True,
             est_tokens: int = 0):
        """Route one single-example request; returns the model output(s).

        Raises :class:`ShedError` (admission/overload/placement, with
        ``retry_after``), :class:`DeadlineExceeded` (per-request deadline,
        with ``retry_after``), or the request's own validation error.
        Every infrastructure failure in between is retried on a surviving
        replica when ``idempotent`` (the default) — an accepted request
        is never silently dropped. ``est_tokens`` (decode front ends pass
        the request's ``max_new_tokens``) is debited against the tenant's
        :class:`TokenRateBudget` at admission.
        """
        return self.call_detailed(model, *arrays, timeout_s=timeout_s,
                                  tenant=tenant, idempotent=idempotent,
                                  est_tokens=est_tokens)[0]

    def call_detailed(self, model: str, *arrays,
                      timeout_s: Optional[float] = None,
                      tenant: Optional[str] = None,
                      idempotent: bool = True,
                      est_tokens: int = 0) -> Tuple[object, Dict]:
        """:meth:`call` plus a per-request info dict — ``{replica,
        failovers, retries, hedged, latency_ms, trace_id}`` — so benches
        can split failover-path tail latency from the happy path.

        The whole call is one ``router.request`` trace span (a new trace
        when the caller carries none — e.g. each bench client request —
        or a child of the caller's, e.g. the TCP front end's wire span);
        every placement attempt, failover retry, and hedged duplicate is
        a ``router.attempt`` child, so a hedged request renders as
        sibling spans under one parent and a failover chain shows each
        replica tried. A router-level request id binds the admit/shed/
        failover/hedge events on this thread to the same story.
        """
        t0 = time.perf_counter()
        timeout_s = (self.request_timeout_s if timeout_s is None
                     else float(timeout_s))
        t_deadline = time.monotonic() + timeout_s
        info: Dict = {"replica": None, "failovers": 0, "retries": 0,
                      "hedged": False}
        self._counter("mxtpu_router_requests_total",
                      "Requests arriving at the router (pre-admission) — "
                      "the SLO burn-rate denominator").inc()
        with _trace.span("router.request", kind="server", model=model,
                         tenant=tenant) as sp, \
                _tele_events.request_scope(f"rq-{sp.ctx.span_id[-8:]}"):
            # low 8 hex of the span id: ids are base+counter per thread,
            # so the HIGH bits are constant thread-wide and would fold
            # every request on a thread into one correlation scope
            info["trace_id"] = sp.ctx.trace_id
            # head sampling: an unsampled trace propagates ids but
            # records no spans — consumers (the bench stitching gate)
            # must not expect a tree for it
            info["trace_sampled"] = sp.ctx.sampled
            self._admit(model, tenant, est_tokens=est_tokens)
            try:
                val = self._call_admitted(model, arrays, t_deadline,
                                          tenant, idempotent, info)
            finally:
                self._release(tenant)
        info["latency_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        return val, info

    def _call_admitted(self, model: str, arrays, t_deadline: float,
                       tenant: Optional[str], idempotent: bool,
                       info: Dict):
        exclude: set = set()
        attempt = 0
        last_err: Optional[BaseException] = None
        while True:
            now = time.monotonic()
            if now >= t_deadline:
                raise self._deadline(
                    f"request deadline expired before completion "
                    f"(last error: {last_err})")
            rep = self.replicas.pick(exclude)
            if rep is None:
                # nowhere to place RIGHT NOW: a restart may rejoin, so
                # back off and re-open excluded replicas — up to the
                # retry cap, then shed explicitly
                if attempt >= self.retries:
                    raise self._shed(
                        "placement_exhausted",
                        f"no replica completed the request after "
                        f"{attempt} retries (last error: {last_err})",
                        model, tenant)
                attempt += 1
                self._bump("retries")
                info["retries"] += 1
                self._backoff(attempt, t_deadline)
                exclude.clear()
                continue
            # one attempt = one child span; the replica's batcher span
            # parents under it because submit runs with it active
            att = _trace.start_span("router.attempt", kind="client",
                                    replica=rep.name, n=attempt)
            try:
                with _trace.use(att.ctx):
                    fut = rep.submit(model, *arrays)
            except QueueFullError as e:
                att.finish(outcome="queue_full")
                last_err = e
                exclude.add(rep.name)
                continue
            except ReplicaUnavailable as e:
                att.finish(outcome="unavailable")
                last_err = e
                self._note_failover(rep, model, e)
                info["failovers"] += 1
                exclude.add(rep.name)
                continue
            except BaseException as e:  # noqa: BLE001 — span hygiene
                # the request's own error (e.g. shape/bucket validation
                # rejected at submit): it surfaces to the caller
                # unchanged, but the attempt span must still close so
                # the trace shows which replica rejected it
                att.finish(outcome=type(e).__name__)
                raise
            try:
                return self._await_result(rep, fut, att, model, arrays,
                                          exclude, t_deadline, info,
                                          idempotent)
            except _InfraFailure as e:
                last_err = e.cause
                self._note_failover(rep, model, e.cause)
                info["failovers"] += 1
                if isinstance(e.cause, LockOrderError):
                    # a lock-order inversion poisons the whole replica,
                    # not just this request
                    rep.kill(reason=f"lock-order: {e.cause}")
                if not idempotent:
                    self._bump("failed")
                    self._counter(
                        "mxtpu_router_failed_total",
                        "Requests terminally failed at the router "
                        "(non-idempotent infra failure — no retry "
                        "allowed)").inc()
                    raise e.cause
                exclude.add(rep.name)
                if attempt < self.retries:
                    attempt += 1
                    self._bump("retries")
                    info["retries"] += 1
                    self._backoff(attempt, t_deadline)
                    continue
                raise self._shed(
                    "retries_exhausted",
                    f"request failed on {attempt + 1} replica(s); "
                    f"last error: {e.cause}", model, tenant)

    def _backoff(self, attempt: int, t_deadline: float) -> None:
        """Capped exponential backoff, never sleeping past the request
        deadline."""
        delay = min(self.backoff_ms * (2 ** (attempt - 1)), 200.0) / 1e3
        delay = min(delay, max(0.0, t_deadline - time.monotonic()))
        if delay > 0:
            time.sleep(delay)

    @staticmethod
    def _is_infra(exc: BaseException) -> bool:
        """Classify a placed request's failure: infrastructure failures
        (crashed/stopped replica, chaos kill, lock-order poison, plumbing
        I/O) are retryable on a surviving replica; anything else is the
        request's own error and must surface to the caller unchanged —
        retrying a deterministically bad request would fail every replica
        and mislead the client into backing off forever."""
        from ..fault.inject import ChaosCrash
        if isinstance(exc, (ReplicaUnavailable, ChaosCrash, LockOrderError,
                            TimeoutError, ConnectionError, OSError)):
            return True
        return isinstance(exc, MXNetError) and "batcher stopped" in str(exc)

    def _await_result(self, rep: Replica, fut: ServeFuture, att, model: str,
                      arrays, exclude: set, t_deadline: float,
                      info: Dict, idempotent: bool):
        """Wait for ``fut`` under the request deadline, optionally racing
        ONE hedged duplicate on a second replica after ``hedge_ms`` —
        only for idempotent requests (a hedge IS a duplicate execution).
        ``att`` is the primary attempt's trace span; the hedge opens a
        sibling span, and every racer's span is finished with its outcome
        (won/lost/error/deadline) — ``finish`` is idempotent, so the
        ``finally`` sweep closes whatever an exception path left open."""
        hedge_at = (time.monotonic() + self.hedge_ms / 1e3
                    if self.hedge_ms > 0 and idempotent else None)
        racers: List[Tuple[Replica, ServeFuture, object]] = [(rep, fut, att)]
        spans = [att]                  # every attempt span ever opened
        hedged = False
        try:
            while True:
                now = time.monotonic()
                if now >= t_deadline:
                    for sp in spans:
                        sp.finish(outcome="deadline")
                    raise self._deadline(
                        f"replica {rep.name!r} produced no result within "
                        "the request deadline")
                if not hedged and hedge_at is not None and now >= hedge_at:
                    hedged = True
                    h = self.replicas.pick(exclude | {rep.name})
                    if h is not None:
                        # the hedge is a SIBLING attempt: current context
                        # here is the router.request span, so both
                        # attempts hang under one parent
                        hatt = _trace.start_span(
                            "router.attempt", kind="client",
                            replica=h.name, hedge=True)
                        # on the sweep list BEFORE submit: if submit
                        # raises past the handler below, the finally
                        # still closes the span
                        spans.append(hatt)
                        try:
                            with _trace.use(hatt.ctx):
                                hfut = h.submit(model, *arrays)
                            racers.append((h, hfut, hatt))
                            info["hedged"] = True
                            self._bump("hedges")
                            self._counter("mxtpu_router_hedges_total",
                                          "Hedged duplicate attempts").inc()
                            self._emit("router.hedge", model=model,
                                       primary=rep.name, hedge=h.name,
                                       after_ms=self.hedge_ms)
                        except MXNetError:
                            hatt.finish(outcome="hedge_submit_failed")
                            # hedging is best-effort by definition
                done = [(r, f, a) for r, f, a in racers if f.done()]
                for r, f, a in done:
                    try:
                        val = f.result(timeout=0)
                    except BaseException as e:  # noqa: BLE001 — classified
                        a.finish(outcome=type(e).__name__)
                        if not self._is_infra(e):
                            raise  # the request's own error — not retryable
                        racers = [t for t in racers if t[1] is not f]
                        if not racers:
                            raise _InfraFailure(e)
                        continue
                    a.finish(outcome="ok", won=True)
                    for _r, _f, other in racers:
                        if other is not a:
                            other.finish(outcome="lost")
                    if f is not fut:
                        self._bump("hedge_wins")
                    info["replica"] = r.name
                    self._bump("completed")
                    return val
                # block on the oldest outstanding racer up to the next
                # event (hedge arm time, request deadline), not spinning
                horizon = t_deadline
                if hedge_at is not None and not hedged:
                    horizon = min(horizon, hedge_at)
                elif len(racers) > 1:
                    horizon = min(horizon, now + 0.005)
                racers[0][1].wait(max(0.0, horizon - time.monotonic()))
        finally:
            for sp in spans:
                sp.finish(outcome="abandoned")

    def _note_failover(self, rep: Replica, model: str,
                       err: BaseException) -> None:
        self._bump("failovers")
        self._counter("mxtpu_router_failovers_total",
                      "Requests failed over to another replica",
                      replica=rep.name).inc()
        self._emit("router.failover", severity="warning", model=model,
                   replica=rep.name,
                   error=f"{type(err).__name__}: {err}"[:200])

    # -- health loop ----------------------------------------------------
    def _health_loop(self) -> None:
        interval = self.heartbeat_ms / 1e3
        while not self._stop_evt.wait(interval):
            try:
                self.health_check_once()
            except Exception:  # noqa: BLE001 — the loop must outlive bugs
                with self._lock:
                    self.health_errors += 1

    def health_check_once(self) -> Dict[str, str]:
        """One heartbeat sweep (the loop body, callable from tests):
        stall-checks healthy replicas, schedules restarts for crashed/
        unhealthy ones, refreshes the healthy gauge. Returns the state
        map."""
        from ..telemetry import metrics as _tmetrics
        n_healthy = 0
        for rep in self.replicas:
            hb = rep.heartbeat()
            state = hb["state"]
            if state == "healthy":
                n_healthy += 1
                self._check_stall(rep, hb)
            elif state in ("crashed", "unhealthy"):
                self._schedule_restart(rep)
        _tmetrics.gauge("mxtpu_serve_replicas_healthy",
                        "Replicas currently serving").set(n_healthy)
        return self.replicas.states()

    def _check_stall(self, rep: Replica, hb: Dict) -> None:
        """Deadline-missed detection: queued requests with zero flush
        progress for ``stall_s`` means the replica is wedged (hung
        compile, deadlocked worker) — kill it so the restart path and the
        request retries take over. ``_progress`` is touched only by the
        health thread."""
        prev = self._progress.get(rep.name)
        if prev is None or hb["batches"] != prev["batches"] \
                or hb["depth"] == 0:
            self._progress[rep.name] = {"batches": hb["batches"],
                                        "since": hb["ts"]}
            return
        if hb["ts"] - prev["since"] >= self.stall_s:
            self._progress.pop(rep.name, None)
            rep.kill(reason=f"stalled: {hb['depth']} queued, no flush "
                            f"for {self.stall_s:.1f}s")

    def _schedule_restart(self, rep: Replica) -> None:
        if self._stop_evt.is_set():
            return  # a stopping tier must not spawn new restarters
        with self._lock:
            if rep.name in self._restarting:
                return
            self._restarting.add(rep.name)
            t = threading.Thread(target=self._restart_replica, args=(rep,),
                                 name=f"mx-serve-restart-{rep.name}",
                                 daemon=True)
            self._restart_threads[rep.name] = t
        t.start()

    def _restart_replica(self, rep: Replica) -> None:
        try:
            rep.restart()
            self._bump("restarts")
            self._counter("mxtpu_serve_replica_restarts_total",
                          "Replica restarts by the router",
                          replica=rep.name).inc()
        except Exception:  # noqa: BLE001 — replica already marked
            # unhealthy; pace the retry so a permanently broken loader
            # cannot hot-loop the restarter
            self._stop_evt.wait(self.restart_backoff_s)
        finally:
            with self._lock:
                self._restarting.discard(rep.name)
                self._restart_threads.pop(rep.name, None)

    # -- training→serving weight pipe -----------------------------------
    def sync_weights_once(self, model: str, ckpt_root: str) -> Dict:
        """Pull the newest **verified** checkpoint under ``ckpt_root``
        and push it to every healthy replica with zero recompiles.

        Never swaps in bad weights: ``load_latest`` already walks past
        CRC-corrupt checkpoints, and the staging check here rejects
        non-finite float arrays and checkpoints whose names match no
        parameter. Returns an outcome dict (also published as a
        ``router.weight_sync`` event)."""
        from ..fault import checkpoint as fault_checkpoint
        from .registry import map_checkpoint_arrays
        try:
            arrays, meta, step = fault_checkpoint.load_latest(ckpt_root)
        except fault_checkpoint.CheckpointError as e:
            out = {"outcome": "no_checkpoint", "error": str(e)[:200]}
            self._emit("router.weight_sync", severity="warning",
                       model=model, **out)
            return out
        # "unchanged" must mean unchanged FLEET, not just an unchanged
        # step: a replica that failed the last push or restarted since
        # (its rebuild prewarms from the artifact cache's original
        # weights) needs the step re-pushed or it serves stale weights
        # until training produces a new checkpoint
        fleet = {r.name: r.restarts for r in self.replicas}
        with self._lock:
            prev = self._synced_steps.get((model, ckpt_root))
            if prev is not None and prev["step"] == step \
                    and prev["fleet"] == fleet:
                return {"outcome": "unchanged", "step": step}
        weights = map_checkpoint_arrays(arrays, meta)
        bad = sorted(k for k, v in weights.items()
                     if v.dtype.kind == "f" and not onp.isfinite(v).all())
        if bad:
            out = {"outcome": "rejected", "step": step,
                   "reason": "non_finite",
                   "arrays": bad[:4]}
            self._emit("router.weight_sync", severity="error", model=model,
                       **out)
            return out
        applied, failed, skipped = [], [], []
        for rep in self.replicas:
            if not rep.healthy():
                skipped.append(rep.name)
                continue
            try:
                rep.push_weights(model, weights)
                applied.append(rep.name)
            except MXNetError as e:
                failed.append({"replica": rep.name,
                               "error": str(e)[:200]})
        if applied:
            with self._lock:
                # record the fleet shape only when EVERY replica took the
                # push — a partial fleet keeps re-syncing each cadence
                # until it converges
                if not failed and not skipped:
                    self._synced_steps[(model, ckpt_root)] = {
                        "step": step, "fleet": fleet}
                else:
                    self._synced_steps.pop((model, ckpt_root), None)
                self.stats["weight_syncs"] += 1
        out = {"outcome": "applied" if applied else "rejected",
               "step": step, "replicas": applied, "failed": failed}
        self._emit("router.weight_sync",
                   severity="info" if applied else "error",
                   model=model, **out)
        return out

    def start_weight_sync(self, model: str, ckpt_root: str,
                          interval_s: float) -> None:
        """Background cadence for :meth:`sync_weights_once` (one thread
        per model; stops with the router)."""
        def loop():
            while not self._stop_evt.wait(interval_s):
                try:
                    self.sync_weights_once(model, ckpt_root)
                except Exception as e:  # noqa: BLE001 — cadence survives
                    self._emit("router.weight_sync", severity="error",
                               model=model, outcome="error",
                               error=f"{type(e).__name__}: {e}"[:200])

        with self._lock:
            have = self._sync_threads.get(model)
            if have is not None and have.is_alive():
                return
            t = threading.Thread(target=loop,
                                 name=f"mx-serve-weight-sync-{model}",
                                 daemon=True)
            self._sync_threads[model] = t
        t.start()

    # -- reporting ------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            stats = dict(self.stats)
            inflight = dict(self._inflight)
        return {"replicas": self.replicas.states(),
                "stats": stats, "tenants_inflight": inflight,
                "token_budget": self.token_budget.snapshot(),
                "policy": {"retries": self.retries,
                           "backoff_ms": self.backoff_ms,
                           "hedge_ms": self.hedge_ms,
                           "shed_depth": self.shed_depth,
                           "tenant_inflight": self.tenant_inflight,
                           "tenant_tokens_per_s": self.token_budget.rate,
                           "heartbeat_ms": self.heartbeat_ms,
                           "stall_s": self.stall_s,
                           "request_timeout_s": self.request_timeout_s}}


class _InfraFailure(Exception):
    """Internal: a placed request failed for infrastructure reasons
    (crashed replica, stopped batcher, lock-order poison) — retryable
    when the request is idempotent."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause

"""Per-request serving observability.

Reference counterpart: the reference profiled *operator* time; a serving
runtime needs *request* truth — tail latency, queue pressure, padding
waste, and (the jit-specific one) recompiles. One :class:`ServeMetrics`
instance aggregates all four families, thread-safe, and renders them as a
JSON-ready dict (``snapshot()``) the bench harness dumps next to its
throughput numbers:

- **latency**: p50/p95/p99/mean over a bounded reservoir, via the shared
  :class:`~incubator_mxnet_tpu.telemetry.metrics.Histogram` (ONE
  reservoir implementation — ``metric.Percentile`` delegates to the same
  class, so training and serving summaries cannot drift);
- **queue**: live + high-water depth, rejected (backpressure) count;
- **batching**: batches flushed, mean/last occupancy (real rows ÷ bucket
  rows — padding waste), batch compute latency;
- **compile**: the wrapped :class:`CompiledModel` counters — post-warmup
  compiles MUST stay 0 in steady state.

Every recording ALSO feeds the process-wide ``mx.telemetry`` registry
(``mxtpu_serve_*`` series labeled by model), so the Prometheus scrape the
serve Server answers carries serving traffic without extra bookkeeping.
The instance-local histograms/ints remain the *window* view ``reset()``
clears; the registry series stay monotonic (Prometheus semantics).

Per-stage wall-time (pad / compute / unpad / batch) rides separately on
``mx.profiler`` spans (``profiler.dumps()``), keeping this module free of
any device API.
"""
from __future__ import annotations

import json
from typing import Dict

from ..lockcheck import make_lock
from ..telemetry import metrics as tmetrics
from ..telemetry.metrics import Histogram

__all__ = ["ServeMetrics"]


def _j(v, ndigits: int = 3):
    """JSON-safe number: NaN/inf (empty metrics) become null — the wire
    protocol must stay strict-JSON parseable on the very first scrape."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    if f != f or f in (float("inf"), float("-inf")):
        return None
    return round(f, ndigits)


class ServeMetrics:
    """Thread-safe aggregate serving counters for one model/batcher."""

    def __init__(self, reservoir: int = 8192, model: str = "default"):
        self._lock = make_lock("ServeMetrics._lock")
        self.model = model
        self._latency = Histogram(name="latency_ms", q=(50, 95, 99),
                                  reservoir=reservoir)
        self._batch_ms = Histogram(name="batch_ms", q=(50, 95, 99),
                                   reservoir=reservoir)
        # process-wide registry series (shared across instances with the
        # same model label; monotonic — never reset by this instance)
        self._g = {
            "requests": tmetrics.counter(
                "mxtpu_serve_requests_total",
                "Requests served (completed batches)", model=model),
            "rejected": tmetrics.counter(
                "mxtpu_serve_rejected_total",
                "Requests rejected by queue backpressure", model=model),
            "failed": tmetrics.counter(
                "mxtpu_serve_failed_total",
                "Requests failed inside an erroring batch", model=model),
            "batches": tmetrics.counter(
                "mxtpu_serve_batches_total", "Batches flushed",
                model=model),
            "depth": tmetrics.gauge(
                "mxtpu_serve_queue_depth", "Live request-queue depth",
                model=model),
            "latency": tmetrics.histogram(
                "mxtpu_serve_latency_ms",
                "End-to-end request latency (ms)", model=model),
        }
        self.requests = 0
        self.rejected = 0
        self.failed = 0
        self.failed_batches = 0
        self.batches = 0
        self.rows = 0
        self.bucket_rows = 0
        self.depth = 0
        self.max_depth = 0
        self.last_occupancy = float("nan")

    # -- recording ------------------------------------------------------
    def record_request(self, latency_ms: float) -> None:
        with self._lock:
            self.requests += 1
            self._latency.observe(latency_ms)
        self._g["requests"].inc()
        self._g["latency"].observe(latency_ms)

    def record_rejection(self) -> None:
        with self._lock:
            self.rejected += 1
        self._g["rejected"].inc()

    def record_failed_batch(self, size: int) -> None:
        """A flush that errored: its requests got exceptions, not results
        — they must not inflate the served-traffic numbers."""
        with self._lock:
            self.failed += size
            self.failed_batches += 1
        self._g["failed"].inc(size)

    def record_depth(self, depth: int) -> None:
        with self._lock:
            self.depth = depth
            self.max_depth = max(self.max_depth, depth)
        self._g["depth"].set(depth)

    def record_batch(self, size: int, bucket: int, dt_ms: float) -> None:
        with self._lock:
            self.batches += 1
            self.rows += size
            self.bucket_rows += bucket
            self.last_occupancy = size / bucket if bucket else float("nan")
            self._batch_ms.observe(dt_ms)
        self._g["batches"].inc()

    # -- reporting ------------------------------------------------------
    @staticmethod
    def _pcts(hist: Histogram) -> Dict:
        s = hist.summary()
        out = {f"{hist.name}_p{q:g}": _j(s[f"p{q:g}"]) for q in hist.q}
        out[f"{hist.name}_mean"] = _j(s["mean"])
        return out

    def snapshot(self, model=None) -> Dict:
        """JSON-ready dict of everything recorded; pass the served
        :class:`CompiledModel` to inline its compile-cache counters."""
        with self._lock:
            snap = {
                "requests": self.requests,
                "rejected": self.rejected,
                "failed": self.failed,
                "failed_batches": self.failed_batches,
                "queue_depth": self.depth,
                "queue_max_depth": self.max_depth,
                "batches": self.batches,
                "batch_occupancy": _j(self.rows / self.bucket_rows, 4)
                if self.bucket_rows else None,
                "latency": self._pcts(self._latency),
                "batch_latency": self._pcts(self._batch_ms),
            }
        if model is not None:
            snap["compile_cache"] = model.cache_info()
        return snap

    def dumps(self, model=None) -> str:
        return json.dumps(self.snapshot(model), indent=1, sort_keys=True)

    def reset(self) -> None:
        """Reset this instance's window (registry series stay monotonic)."""
        with self._lock:
            self._latency.reset()
            self._batch_ms.reset()
            self.requests = self.rejected = self.batches = 0
            self.failed = self.failed_batches = 0
            self.rows = self.bucket_rows = 0
            self.depth = self.max_depth = 0
            self.last_occupancy = float("nan")

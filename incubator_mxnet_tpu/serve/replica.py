"""Replica — one independent serving worker in the HA tier.

Reference counterpart: MMS scaled by running N model-server *processes*
behind a fronting load balancer; the framework itself had no replica
concept. Here a :class:`Replica` is the in-process unit of failure the
:class:`~incubator_mxnet_tpu.serve.router.Router` spreads traffic over:
it owns a **private** :class:`ModelRegistry`, one
:class:`DynamicBatcher` per model, and therefore its own
:class:`CompiledModel` executables — nothing is shared with its peers,
so a crash, a wedged batcher, or a poisoned lock order in one replica
cannot take the tier down.

Lifecycle state machine (transitions publish ``router.health`` events)::

    new ──start()──▶ loading ──▶ healthy ◀──────────────┐
                        │           │ kill()/worker died │
                        ▼           ▼                    │
                    unhealthy ◀─ crashed ──restart()──▶ restarting
                        │                                │ (loader +
                        ▼                                │  prewarm)
                     stopped ◀──stop()── draining ◀──────┘

- ``kill()`` simulates process death (the ``replica_kill`` chaos site
  raises it from the request path): pending futures FAIL FAST so the
  router can retry them on a surviving replica — zero lost accepted
  requests is the router's contract, failing fast is this class's half.
- ``restart()`` rebuilds from scratch — a fresh registry, fresh
  batchers — exactly what a respawned process would do; with an
  :class:`~incubator_mxnet_tpu.serve.artifact_cache.ArtifactCache`
  attached to the loader, the rebuild prewarms from verified StableHLO
  artifacts (no Python-model retrace) and the compile ledger proves the
  restore added zero post-warmup compiles.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence

from ..base import MXNetError
from ..fault import inject
from ..fault.inject import ChaosCrash
from ..lockcheck import make_lock
from .artifact_cache import ArtifactCache
from .batcher import DynamicBatcher, QueueFullError, ServeFuture
from .buckets import BucketTable
from .registry import ModelRegistry, ModelVersion

__all__ = ["Replica", "ReplicaUnavailable", "ReplicaCrashed"]

#: legal lifecycle states (see the module docstring's state machine)
STATES = ("new", "loading", "healthy", "unhealthy", "draining",
          "restarting", "crashed", "stopped")


class ReplicaUnavailable(MXNetError):
    """The replica cannot take this request right now (not healthy,
    mid-restart, or its batcher closed underneath the submit) — an
    infrastructure failure the router may retry elsewhere."""


class ReplicaCrashed(ReplicaUnavailable):
    """The replica died taking this request (chaos ``replica_kill`` or a
    real worker death) — failover territory."""


class Replica:
    """One serving worker: private registry + batchers, health surface,
    crash/restart lifecycle.

    ``loader`` is a callable ``(replica) -> None`` that loads every model
    this replica serves (via :meth:`load`); it runs on :meth:`start` AND
    on every :meth:`restart`, so it must be idempotent from a fresh
    registry — which it is for free when it goes through the artifact
    cache.
    """

    def __init__(self, name: str, loader: Callable[["Replica"], None],
                 max_delay_ms: Optional[float] = None,
                 queue_limit: Optional[int] = None,
                 load_deadline_s: Optional[float] = None):
        self.name = name
        self._loader = loader
        #: staging deadline handed to every registry.load this replica's
        #: loader performs — a HUNG loader during an unattended router
        #: restart aborts (replica lands unhealthy, retried next
        #: heartbeat) instead of wedging the restarter thread forever
        self.load_deadline_s = load_deadline_s
        self._batcher_kw = dict(max_delay_ms=max_delay_ms,
                                queue_limit=queue_limit)
        self._lock = make_lock("Replica._lock")
        self.registry = ModelRegistry()
        self._batchers: Dict[str, DynamicBatcher] = {}
        self._state = "new"
        self._reason = ""
        self.restarts = 0
        self.kills = 0

    # -- state ----------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def healthy(self) -> bool:
        return self.state == "healthy"

    def _transition(self, to: str, reason: str = "") -> None:
        assert to in STATES, to
        with self._lock:
            frm = self._state
            self._state = to
            self._reason = reason
        self._emit_transition(frm, to, reason)

    def _emit_transition(self, frm: str, to: str, reason: str) -> None:
        from ..telemetry import events as _tele
        _tele.emit("router.health",
                   severity=("warning" if to in ("crashed", "unhealthy")
                             else "info"),
                   replica=self.name, **{"from": frm, "to": to},
                   reason=reason)

    # -- loading --------------------------------------------------------
    def start(self) -> "Replica":
        """Run the loader (first boot from ``new``). From ``stopped``
        this routes through :meth:`restart` — the old registry still
        holds its versions, so only a fresh rebuild can re-run the
        loader."""
        if self.state == "stopped":
            return self.restart()
        self._transition("loading")
        try:
            self._loader(self)
        except BaseException as e:
            self._transition("unhealthy", f"load failed: {e}")
            raise
        self._transition("healthy")
        return self

    def load(self, name: str, *, table: BucketTable,
             input_axes: Sequence[Dict[int, str]],
             factory: Optional[Callable] = None,
             artifacts: Optional[str] = None,
             cache: Optional[ArtifactCache] = None,
             version: int = 1,
             input_names: Optional[Sequence[str]] = None,
             output_axes: Optional[Sequence[Dict[int, str]]] = None,
             pad_values=0, analyze: bool = True,
             warmup: bool = True) -> ModelVersion:
        """Load one model into this replica's registry — through the
        artifact cache when one is attached.

        With ``cache`` + ``factory``: a verified cache hit loads the
        StableHLO artifact directly (**no Python-model retrace** — the
        prewarm path a restart takes); a miss or corrupt entry builds
        from ``factory()`` (which must return a hybridized block with one
        forward recorded), repairs the cache with :meth:`ArtifactCache
        .put`, and then loads from the freshly written artifact, so every
        boot serves the exact bytes a restart will.
        """
        if cache is not None and factory is not None:
            names = list(input_names or ["data"])
            got = cache.get(name, version, table, input_axes)
            if got is None:
                block = factory()
                prefix = cache.put(name, version, block, table, input_axes,
                                   input_names=names)
            else:
                prefix, manifest = got
                names = list(manifest.get("input_names", names))
            return self.registry.load(
                name, table=table, input_axes=input_axes, artifacts=prefix,
                version=version, input_names=names, output_axes=output_axes,
                pad_values=pad_values, analyze=analyze, warmup=warmup,
                deadline_s=self.load_deadline_s)
        return self.registry.load(
            name, table=table, input_axes=input_axes, factory=factory,
            artifacts=artifacts, version=version, input_names=input_names,
            output_axes=output_axes, pad_values=pad_values,
            analyze=analyze, warmup=warmup,
            deadline_s=self.load_deadline_s)

    # -- request path ---------------------------------------------------
    def _batcher(self, name: str) -> DynamicBatcher:
        from .batcher import make_registry_batcher
        with self._lock:
            # state re-checked under the SAME lock that kill()/restart()
            # clear _batchers under: a submit racing a kill must not
            # resurrect a fresh batcher on a crashed replica
            if self._state != "healthy":
                raise ReplicaUnavailable(
                    f"replica {self.name!r} is {self._state}"
                    + (f" ({self._reason})" if self._reason else ""))
            b = self._batchers.get(name)
            if b is None:
                b = make_registry_batcher(self.registry, name,
                                          **self._batcher_kw)
                self._batchers[name] = b
        return b

    def submit(self, model: str, *arrays) -> ServeFuture:
        """Enqueue one single-example request on this replica.

        Chaos probes run first: an armed/seeded ``replica_kill`` kills
        THIS replica (pending futures fail fast) and surfaces as
        :class:`ReplicaCrashed`; ``slow_replica`` injects latency. State
        and batcher failures surface as :class:`ReplicaUnavailable`;
        anything else is the request's own fault and is not retryable.
        """
        try:
            # dump=False: kill() below writes the (richer) post-mortem
            # bundle for this death — a second one here would both halve
            # the MXTPU_FLIGHT_MAX budget and fsync on the router's
            # request thread before failover can start
            inject.crash("replica_kill", dump=False)
            if inject.should("replica_kill"):
                raise ChaosCrash("replica_kill")
        except ChaosCrash as e:
            self.kill(reason="chaos: replica_kill")
            raise ReplicaCrashed(
                f"replica {self.name!r} killed mid-request") from e
        inject.maybe_delay("slow_replica")
        try:
            # _batcher() enforces state=="healthy" under the replica lock
            return self._batcher(model).submit(*arrays)
        except (QueueFullError, ReplicaUnavailable):
            raise
        except MXNetError as e:
            # a kill/restart racing this submit closes the batcher or
            # empties the registry under us — that is replica
            # unavailability, not a malformed request
            if not self.healthy() or "batcher stopped" in str(e):
                raise ReplicaUnavailable(
                    f"replica {self.name!r} became unavailable "
                    f"mid-submit: {e}") from e
            raise

    def push_weights(self, model: str, weights: Dict) -> int:
        """Swap the active version's weights in place — the router's
        training→serving pipe. Shapes must match the compiled graphs, so
        this is ``refresh_params``: **zero recompiles**, assertable on
        the compile ledger. Returns how many parameters were updated."""
        from .registry import apply_weights
        cm = self.registry.get(model)
        applied = apply_weights(cm._block, weights)
        if not applied:
            raise MXNetError(
                f"weight push onto replica {self.name!r} matched 0 of "
                f"{model!r}'s parameters — name-scope mismatch?")
        cm.refresh_params()
        return applied

    # -- health surface -------------------------------------------------
    def queue_depth(self) -> int:
        with self._lock:
            batchers = list(self._batchers.values())
        return sum(b.depth() for b in batchers)

    def heartbeat(self) -> Dict:
        """One health probe: state, aggregate queue depth, flush progress
        (total batches), and worker-thread liveness. A healthy replica
        whose batcher worker died is reported (and marked) crashed —
        deadline/stall judgement is the router's, from progress deltas."""
        with self._lock:
            state = self._state
            batchers = list(self._batchers.values())
        depth = sum(b.depth() for b in batchers)
        batches = sum(b.metrics.batches + b.metrics.failed_batches
                      for b in batchers)
        alive = all(b.worker_alive() for b in batchers)
        if state == "healthy" and batchers and not alive:
            self.kill(reason="batcher worker died")
            state = self.state
        return {"replica": self.name, "state": state, "depth": depth,
                "batches": batches, "workers_alive": alive,
                "ts": time.monotonic()}

    # -- lifecycle ------------------------------------------------------
    def kill(self, reason: str = "") -> None:
        """Simulated process death: serving stops NOW, queued/in-flight
        futures fail fast (the router retries them elsewhere), state
        becomes ``crashed`` for the health loop to restart."""
        with self._lock:
            # only a serving(ish) replica can crash: a kill racing a
            # deliberate drain/restart/stop must not resurrect it via
            # the health loop's crashed→restart path
            if self._state not in ("healthy", "loading", "unhealthy"):
                return
            frm = self._state
            self._state = "crashed"  # guard + flip atomically: two
            self._reason = reason    # racing kills must count once
            batchers = list(self._batchers.values())
            self._batchers.clear()
            self.kills += 1
        self._emit_transition(frm, "crashed", reason)
        # fail the parked futures FIRST — the router's failover clock is
        # ticking, and a post-mortem fsync must not sit between a dead
        # replica and the retry that rescues its requests
        for b in batchers:
            b.stop(drain=False, timeout=0.5)
        # the kill evidence (what was queued, which locks were held, the
        # last health probes) lives in process rings that a real crash
        # would erase — bundle it while the state is still warm; the
        # rings are append-only so the stop above only ADDS the
        # drain/abandon tail to the story the bundle tells
        from ..telemetry import flight as _flight
        _flight.dump("replica_kill", replica=self.name, reason=reason,
                     prior_state=frm)

    def restart(self) -> "Replica":
        """Full rebuild — fresh registry, fresh batchers, loader re-run
        (prewarming from the artifact cache when attached) — then rejoin
        as healthy. The router calls this from its restarter thread."""
        with self._lock:
            if self._state == "restarting":
                return self
            frm = self._state
            self._state = "restarting"  # guard + flip atomically
            stale = list(self._batchers.values())
            self._batchers.clear()
            self.registry = ModelRegistry()
            self.restarts += 1
        self._emit_transition(frm, "restarting", "")
        for b in stale:
            b.stop(drain=False, timeout=0.5)
        try:
            self._loader(self)
        except BaseException as e:
            self._transition("unhealthy", f"restart load failed: {e}")
            raise
        self._transition("healthy", "restarted")
        return self

    def drain(self, timeout: float = 10.0) -> None:
        """Graceful: serve what is queued, then stop the batchers."""
        with self._lock:
            frm = self._state
            self._state = "draining"  # flip INSIDE the lock that clears
            batchers = list(self._batchers.values())  # _batchers, or a
            self._batchers.clear()  # racing submit resurrects a batcher
        self._emit_transition(frm, "draining", "")
        for b in batchers:
            b.stop(drain=True, timeout=timeout)

    def stop(self, timeout: float = 10.0) -> None:
        self.drain(timeout=timeout)
        self._transition("stopped")

    def snapshot(self) -> Dict:
        with self._lock:
            state = self._state
            batchers = dict(self._batchers)
        return {"replica": self.name, "state": state,
                "restarts": self.restarts, "kills": self.kills,
                "queue_depth": sum(b.depth() for b in batchers.values()),
                "models": {n: b.metrics.snapshot() for n, b in
                           sorted(batchers.items())}}

    def __repr__(self):
        return f"Replica({self.name!r}, {self.state})"

"""DecodeEngine — the prefill/decode split over a paged KV-cache.

Autoregressive generation on a jit-cache runtime has exactly two graphs
worth compiling (PyGraph's capture-once/replay-cheaply argument):

- **prefill**: encode the prompt and precompute the per-layer
  cross-attention K/V — prompt lengths are ragged, so this is a
  :class:`~..compiled.CompiledModel` bucketed over ``(batch, src)``;
- **decode**: one fixed-shape single-token step
  (:func:`~...models.nmt.nmt_paged_step`) that reads/writes cache pages
  in-place (the pool arrays are donated), AOT-lowered ONCE at
  ``warmup()`` — generation length never appears in any shape, so
  ragged generation lengths cannot recompile anything, by construction.

The KV pool's size is not a tunable: ``capacity_report()`` traces the
decode graph at two pool sizes, reads the fixed and per-page peak live
bytes off the PR 12 liveness model (``analysis.hlo.cost.peak_live_bytes``,
donation-aware), and prices the static "sequences that fit in
``MXTPU_HBM_BUDGET``" number; the runtime :class:`~.blocks.BlockPool` is
built from the same numbers, so the static capacity and the actual
admission limit cannot drift apart. ``check_budget()`` re-runs the
MX709-family memory gate over the real (capacity-sized) graphs.

Env knobs: ``MXTPU_DECODE_MAX_BATCH``, ``MXTPU_DECODE_BLOCK_SIZE``,
``MXTPU_DECODE_MAX_TOKENS`` (see docs/env_vars.md).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ...base import MXNetError
from ...lockcheck import make_rlock
from ...util import getenv, hbm_budget_bytes
from ...telemetry import compile_log
from ..buckets import BucketTable
from ..compiled import CompiledModel
from .blocks import (BlockPool, blocks_per_sequence, block_bytes,
                     price_capacity)

__all__ = ["DecodeEngine", "PrefillEntry", "DECODE_SITE"]

#: compile-ledger site of the AOT decode step (prefill buckets ride the
#: regular ``serve.compiled`` site)
DECODE_SITE = "serve.decode"


class PrefillEntry:
    """HybridBlock entry the prefill CompiledModel wraps: encoder forward
    plus every decoder layer's cross-attention K/V projection, packed
    into one ``(B, Ls, num_layers * 2 * units)`` tensor (a single output
    keeps the bucket-padding slice trivial)."""

    def __new__(cls, model):
        from ...gluon.block import HybridBlock

        class _Entry(HybridBlock):
            def __init__(self, m, **kw):
                super().__init__(**kw)
                self._m = m      # Block.__setattr__ registers the child

            def hybrid_forward(self, F, src, src_valid_length):
                m = self._m
                B, L = src.shape[0], src.shape[1]
                mask = m._src_mask(F, src_valid_length, B, L)
                mem = m.encoder(m.src_embed(src), mask)
                kvs = [layer.cross_attn.kv_proj(mem)
                       for layer in m.decoder.layers]
                return F.concat(*kvs, dim=2) if len(kvs) > 1 else kvs[0]

        return _Entry(model, prefix="prefill_")


class DecodeEngine:
    """Paged-KV-cache generation engine for one :class:`NMTModel` replica.

    ``prompt_table`` must declare ``batch`` and ``src`` axes; decode-side
    shapes are fixed by ``max_batch`` (concurrent rows), ``block_size``
    (tokens per cache page) and ``max_target_len`` (generation cap =
    pages per sequence × block_size). ``warmup()`` AOT-compiles every
    prefill bucket plus the single decode executable; after it,
    ``telemetry.compile_log.assert_zero_post_warmup()`` is an invariant
    across arbitrarily ragged prompt/generation lengths.
    """

    def __init__(self, model, prompt_table: BucketTable, *,
                 max_batch: Optional[int] = None,
                 block_size: Optional[int] = None,
                 max_target_len: Optional[int] = None,
                 hbm_budget: Optional[int] = None,
                 bos_id: int = 1, eos_id: int = 2):
        import jax

        if not {"batch", "src"} <= set(prompt_table.axes):
            raise MXNetError("DecodeEngine prompt_table needs 'batch' and "
                             f"'src' axes, got {sorted(prompt_table.axes)}")
        self._model = model
        self._table = prompt_table
        self.max_batch = int(max_batch or getenv("MXTPU_DECODE_MAX_BATCH"))
        self.block_size = int(block_size
                              or getenv("MXTPU_DECODE_BLOCK_SIZE"))
        self.max_target_len = int(max_target_len
                                  or getenv("MXTPU_DECODE_MAX_TOKENS"))
        self.bos_id, self.eos_id = int(bos_id), int(eos_id)
        if self.max_target_len > model.decoder._max_length:
            raise MXNetError(
                f"max_target_len {self.max_target_len} exceeds the "
                f"model's position table ({model.decoder._max_length})")
        self._budget = hbm_budget if hbm_budget is not None \
            else hbm_budget_bytes()
        self._lock = make_rlock("DecodeEngine._lock")

        from ...models.nmt import incremental_decode_params
        self._extract_params = lambda: incremental_decode_params(model)
        try:
            params = self._extract_params()
        except Exception:
            # a freshly-initialize()d gluon model defers parameter
            # creation to its first forward — run one tiny full pass so
            # the decoder-side params exist before extraction
            from ... import autograd
            from ...ndarray import array as _force_nd
            lo_s0 = int(prompt_table.axes["src"][0])
            src0 = _force_nd(onp.full((1, lo_s0), self.bos_id), dtype="int32")
            tgt0 = _force_nd(onp.full((1, 1), self.bos_id), dtype="int32")
            with autograd.predict_mode():
                model(src0, tgt0)
            params = self._extract_params()
        self._treedef = jax.tree_util.tree_structure(params)
        self._param_leaves = jax.tree_util.tree_leaves(params)
        self.num_layers = len(params["layers"])
        self.units = int(params["embed"].shape[1])
        self.vocab = int(params["proj_w"].shape[0])
        self.num_heads = model.decoder.layers[0].self_attn._num_heads
        self.max_src = int(prompt_table.axes["src"][1])
        self._dtype = params["embed"].dtype

        # -- prefill: bucketed CompiledModel over (batch, src) -------------
        from ...ndarray import array as _nd_array
        lo_b = prompt_table.axes["batch"][0]
        lo_s = prompt_table.axes["src"][0]
        # NDArray example args: the warm-up call must take the block's
        # eager (ndarray-F) path, not the symbolic compose path
        ex_src = _nd_array(onp.zeros((lo_b, lo_s)), dtype="int32")
        ex_vl = _nd_array(onp.full((lo_b,), float(lo_s)), dtype="float32")
        self.prefill = CompiledModel(
            PrefillEntry(model), prompt_table,
            input_axes=[{0: "batch", 1: "src"}, {0: "batch"}],
            example_args=(ex_src, ex_vl), donate=False)

        # -- decode: one flat fixed-shape step, AOT-compiled at warmup -----
        self._flat_step = self._make_flat_step()
        # the donating jit is the TPU-semantics graph: capacity pricing and
        # the MX709 gate read its donation-aware liveness
        self._jit_step = jax.jit(self._flat_step, donate_argnums=(0, 1))
        self._exe = None

        # -- capacity: priced off the liveness model, pool sized from it ---
        self.capacity = self.capacity_report()
        nb = self.capacity["num_blocks"]
        bps = self.capacity["blocks_per_seq"]
        self.pool = BlockPool(nb, self.block_size, bps,
                              max_sequences=self.capacity["max_sequences"])
        self._warmed = False
        self.steps = 0

        import jax.numpy as jnp
        B, NL, U = self.max_batch, self.num_layers, self.units
        self._pool_k = jnp.zeros((nb, NL, self.block_size, U), self._dtype)
        self._pool_v = jnp.zeros_like(self._pool_k)
        self._cross = jnp.zeros((NL, B, self.max_src, 2 * U), self._dtype)
        self._tables = onp.zeros((B, bps), "int32")
        self._valid = onp.zeros((B,), "float32")

    # -- graph construction ------------------------------------------------

    def _make_flat_step(self):
        import jax
        import jax.numpy as jnp
        from ...models.nmt import nmt_paged_step

        H, bs, max_src, treedef = (self.num_heads, self.block_size,
                                   self.max_src, self._treedef)

        def flat_step(pool_k, pool_v, tables, positions, tokens, cross_kv,
                      valid, *param_leaves):
            params = jax.tree_util.tree_unflatten(treedef,
                                                  list(param_leaves))
            mem_mask = jnp.arange(max_src)[None, :] < valid[:, None]
            return nmt_paged_step(params, H, bs, pool_k, pool_v, tables,
                                  positions, tokens, cross_kv, mem_mask)

        return flat_step

    def _step_avals(self, num_blocks: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        nb = num_blocks if num_blocks is not None \
            else self.capacity["num_blocks"]
        B, NL, U = self.max_batch, self.num_layers, self.units
        bps = blocks_per_sequence(self.max_target_len, self.block_size)
        sds = lambda s, d: jax.ShapeDtypeStruct(s, jnp.dtype(d))
        pool = sds((nb, NL, self.block_size, U), self._dtype)
        return (pool, pool, sds((B, bps), "int32"), sds((B,), "int32"),
                sds((B,), "int32"), sds((NL, B, self.max_src, 2 * U),
                                        self._dtype),
                sds((B,), "float32"),
                *[sds(tuple(l.shape), l.dtype) for l in self._param_leaves])

    def _traced_step(self, num_blocks: int):
        """One TracedGraph of the decode step at ``num_blocks`` pool pages
        — the liveness-model view capacity pricing reads."""
        from ...analysis.hlo.trace import trace_entry
        res = trace_entry(self._jit_step,
                          sample_args=[tuple(self._step_avals(num_blocks))])
        g = res.graphs[0]
        g.entry = "DecodeEngine.step"
        g.expected = True
        n_state, n_in = 2, 5
        g.roles = (["state"] * n_state + ["input"] * n_in
                   + ["param"] * len(self._param_leaves))
        return g

    # -- capacity ----------------------------------------------------------

    def capacity_report(self) -> Dict[str, int]:
        """Price the static capacity: trace the decode graph at two pool
        sizes, read fixed vs per-page peak live bytes off the liveness
        scan, divide into ``MXTPU_HBM_BUDGET``. Deterministic — the
        serve_bench gate asserts this equals the runtime pool's
        admission limit."""
        from ...analysis.hlo.cost import peak_live_bytes
        bps = blocks_per_sequence(self.max_target_len, self.block_size)
        if self._budget is None:
            rep = price_capacity(hbm_budget=None, fixed_bytes=0,
                                 per_block_bytes=1,
                                 max_target_len=self.max_target_len,
                                 block_size=self.block_size,
                                 max_batch=self.max_batch)
        else:
            p2 = peak_live_bytes(self._traced_step(2))
            p3 = peak_live_bytes(self._traced_step(3))
            per_block = max(1, p3 - p2)
            analytic = block_bytes(self.num_layers, self.units,
                                   self.block_size,
                                   onp.dtype(self._dtype).itemsize)
            per_block = max(per_block, analytic)
            fixed = max(0, p2 - 2 * per_block)
            rep = price_capacity(hbm_budget=self._budget, fixed_bytes=fixed,
                                 per_block_bytes=per_block,
                                 max_target_len=self.max_target_len,
                                 block_size=self.block_size,
                                 max_batch=self.max_batch)
            rep["fixed_bytes"] = fixed
            rep["per_block_bytes"] = per_block
            rep["hbm_budget"] = int(self._budget)
        if rep["max_sequences"] < 1:
            raise MXNetError(
                "MXTPU_HBM_BUDGET too small for even one decode sequence: "
                f"{rep} — shrink the model, block_size, or max_target_len")
        return rep

    def trace(self, max_graphs: int = 8):
        """TraceResult over BOTH graph families (every prefill bucket plus
        the capacity-sized decode step) — what ``analysis.hlo.verify``
        dispatches to, giving the MX706/MX709 passes decode coverage."""
        from ...analysis.hlo.trace import trace_entry
        res = trace_entry(self.prefill, max_graphs=max_graphs)
        res.graphs.append(self._traced_step(self.capacity["num_blocks"]))
        return res

    def check_budget(self):
        """MX709-family gate over the real (capacity-sized) graphs."""
        from ...analysis import hlo as _hlo
        return _hlo.verify_trace(self.trace(),
                                 hbm_budget_bytes=self._budget)

    # -- lifecycle ---------------------------------------------------------

    def warmup(self) -> dict:
        """AOT-compile every prefill bucket and the decode executable.
        After this, zero post-warmup compiles is an invariant."""
        t0 = time.monotonic()
        pre = self.prefill.warmup()
        # holding the engine lock across the AOT compile is the warmup
        # CONTRACT (same as CompiledModel.warmup): run_step callers block
        # until the executable exists instead of racing a half-installed one
        with self._lock:  # mxlint: disable=MX803
            if self._exe is None:
                import jax
                t1 = time.monotonic()
                # donation is a TPU-backend capability; CPU (tests) runs
                # the same graph without it — same contract as
                # CompiledModel's donate="auto"
                jit = self._jit_step if jax.default_backend() != "cpu" \
                    else jax.jit(self._flat_step)
                self._exe = jit.lower(*self._step_avals()).compile()
                compile_log.note(
                    DECODE_SITE,
                    (("pool", tuple(self._pool_k.shape)),
                     ("batch", self.max_batch)),
                    wall_ms=(time.monotonic() - t1) * 1e3, warmup=True)
            compile_log.mark_warmed(DECODE_SITE)
            self._warmed = True
        return {"prefill": pre, "decode_compiled": 1,
                "capacity": dict(self.capacity),
                "seconds": time.monotonic() - t0}

    def refresh_params(self) -> None:
        """Re-extract decoder params after a weight sync (same shapes —
        the AOT executable is reused, no recompile)."""
        import jax
        with self._lock:
            self._param_leaves = jax.tree_util.tree_leaves(
                self._extract_params())
        self.prefill.refresh_params()

    # -- serving operations (called by DecodeBatcher at token boundaries) --

    def prefill_request(self, src_tokens, valid_len: Optional[int] = None
                        ) -> Tuple[onp.ndarray, int]:
        """Run the bucketed prefill for ONE prompt; returns the packed
        cross-KV row ``(NL, max_src, 2U)`` (padded to max_src) and the
        prompt's valid length."""
        src = onp.asarray(src_tokens, "int32").reshape(1, -1)
        lp = int(valid_len if valid_len is not None else src.shape[1])
        out = self.prefill.predict(src, onp.asarray([float(lp)], "float32"))
        packed = onp.asarray(getattr(out, "_data", out))[0]   # (Ls, NL*2U)
        NL, U = self.num_layers, self.units
        row = onp.zeros((NL, self.max_src, 2 * U), packed.dtype)
        ls = min(packed.shape[0], self.max_src)
        row[:, :ls] = packed[:ls].reshape(ls, NL, 2 * U).transpose(1, 0, 2)
        return row, lp

    def bind_row(self, row: int, cross_row: onp.ndarray,
                 valid_len: int) -> None:
        """Install an admitted sequence's cross-KV into batch row ``row``
        (an eager in-place-style update, not a recompile)."""
        import jax.numpy as jnp
        with self._lock:
            self._cross = self._cross.at[:, row].set(
                jnp.asarray(cross_row, self._dtype))
            self._valid[row] = float(valid_len)

    def clear_row(self, row: int) -> None:
        with self._lock:
            self._tables[row] = 0
            self._valid[row] = 0.0

    def set_row_table(self, row: int, table: Sequence[int]) -> None:
        with self._lock:
            self._tables[row] = 0
            self._tables[row, :len(table)] = onp.asarray(table, "int32")

    def run_step(self, positions: onp.ndarray, tokens: onp.ndarray
                 ) -> onp.ndarray:
        """One fixed-shape decode step over the whole batch; returns
        logits ``(max_batch, vocab)``. Rows not bound to a sequence must
        point at the scratch page (table row 0) — their logits are
        garbage and ignored by the batcher."""
        import jax.numpy as jnp
        # the un-warmed first step pays the compile under the lock by the
        # same warmup contract — steady-state steps never compile
        with self._lock:  # mxlint: disable=MX803
            if self._exe is None:
                self.warmup()
            logits, self._pool_k, self._pool_v = self._exe(
                self._pool_k, self._pool_v,
                jnp.asarray(self._tables), jnp.asarray(positions, "int32"),
                jnp.asarray(tokens, "int32"), self._cross,
                jnp.asarray(self._valid), *self._param_leaves)
            self.steps += 1
        return onp.asarray(logits)

    def reset_cache(self) -> None:
        """Drop all cache contents (e.g. after a chaos replica death) —
        pages are zeroed host-side state only; no recompile."""
        import jax.numpy as jnp
        with self._lock:
            self._pool_k = jnp.zeros_like(self._pool_k)
            self._pool_v = jnp.zeros_like(self._pool_v)
            self._tables[:] = 0
            self._valid[:] = 0.0

    def stats(self) -> dict:
        return {"prefill": dict(self.prefill.stats),
                "decode_steps": self.steps,
                "capacity": dict(self.capacity),
                "pool": self.pool.snapshot(),
                "warmed": self._warmed}

"""Paged KV-cache block pool — fixed-size cache pages per replica.

The decode step's self-attention cache is one preallocated pool of
fixed-size pages (``(num_blocks, layers, block_size, units)`` K and V
arrays owned by :class:`~.engine.DecodeEngine`); this module is the
*allocator* over that pool. Sequences never own contiguous cache rows —
they own a **block table** (a row of physical page ids), so a new request
can join the running batch whenever enough free pages exist anywhere in
the pool: uniform pages make fragmentation structurally impossible, the
same argument as OS paging (and vLLM's PagedAttention).

Allocation happens at *token boundaries*: a sequence takes its first page
at admission and one more each time generation crosses a
``block_size`` boundary; retiring a sequence returns every page to the
free list. Admission is seat-based — :meth:`BlockPool.admission_limit` is
the static "how many concurrent sequences fit" number priced by
:func:`price_capacity` from ``MXTPU_HBM_BUDGET`` via the liveness model
(see ``engine.py``) — so an admitted sequence can never hit honest
mid-generation exhaustion. :class:`CacheExhausted` is therefore loud by
construction: it only fires on an over-admission bug or the seeded
``decode_block_exhaustion`` chaos knob (``fault.inject``).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

from ...base import MXNetError
from ...lockcheck import make_lock
from ...telemetry import metrics as tmetrics

__all__ = ["BlockPool", "CacheExhausted", "blocks_per_sequence",
           "block_bytes", "price_capacity"]


class CacheExhausted(MXNetError):
    """The block pool cannot satisfy an allocation — the request must be
    shed/requeued (never silently truncated). Seat-based admission makes
    this unreachable for admitted sequences outside of chaos injection
    or an allocator bug."""


def blocks_per_sequence(max_target_len: int, block_size: int) -> int:
    """Pages a worst-case (``max_target_len``) sequence needs."""
    return max(1, math.ceil(int(max_target_len) / int(block_size)))


def block_bytes(num_layers: int, units: int, block_size: int,
                dtype_bytes: int = 4) -> int:
    """HBM bytes of ONE cache page across all layers, K and V."""
    return 2 * int(num_layers) * int(block_size) * int(units) * dtype_bytes


def price_capacity(*, hbm_budget: Optional[int], fixed_bytes: int,
                   per_block_bytes: int, max_target_len: int,
                   block_size: int, max_batch: int) -> Dict[str, int]:
    """The static capacity number: how many concurrent sequences fit.

    ``fixed_bytes`` is the decode graph's pool-independent peak live
    bytes (params + activations + cross-KV at ``max_batch`` rows) and
    ``per_block_bytes`` the marginal liveness cost of one more pool page
    — both measured off the traced decode graph by
    :meth:`~.engine.DecodeEngine.capacity_report` (the PR 12 liveness
    model), not hand-derived, so the number moves when the graph does.
    Returns ``{"max_sequences", "num_blocks", "blocks_per_seq"}``; a
    ``None``/unset budget prices no constraint (capacity = max_batch).
    Deterministic: same inputs → same numbers.
    """
    bps = blocks_per_sequence(max_target_len, block_size)
    if hbm_budget is None:
        seqs = int(max_batch)
    else:
        free = int(hbm_budget) - int(fixed_bytes)
        seqs = max(0, free // max(1, int(per_block_bytes) * bps))
        seqs = min(seqs, int(max_batch))
    # +1: page id 0 is the engine's scratch page (inactive batch rows
    # park their writes there)
    return {"max_sequences": seqs, "num_blocks": seqs * bps + 1,
            "blocks_per_seq": bps}


class BlockPool:
    """Allocator over ``num_blocks`` uniform cache pages.

    Page id 0 is reserved as the scratch page and never handed out.
    ``alloc_sequence`` admits a sequence (seat + first page),
    ``append_token`` advances it one token (allocating a page at each
    ``block_size`` boundary) and returns the ``(page, slot)`` write
    coordinates, ``free_sequence`` returns everything. Thread-safe; all
    accounting is O(1) per token.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 blocks_per_seq: int, max_sequences: Optional[int] = None):
        if num_blocks < 2:
            raise MXNetError(f"BlockPool needs >= 2 blocks (one is the "
                             f"scratch page), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.blocks_per_seq = int(blocks_per_seq)
        self._max_seqs = ((self.num_blocks - 1) // self.blocks_per_seq
                          if max_sequences is None else int(max_sequences))
        self._lock = make_lock("BlockPool._lock")
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._tables: Dict[str, List[int]] = {}
        self._lengths: Dict[str, int] = {}
        self.peak_in_use = 0
        self._g_free = tmetrics.gauge("mxtpu_decode_blocks_free",
                                      "free KV-cache pages in the pool")
        self._g_seqs = tmetrics.gauge("mxtpu_decode_active_sequences",
                                      "sequences holding cache pages")
        self._g_free.set(len(self._free))
        self._g_seqs.set(0)

    # -- admission ---------------------------------------------------------

    def admission_limit(self) -> int:
        """The pool's actual concurrent-sequence limit — by construction
        equal to the static ``price_capacity`` number the pool was sized
        from (the serve_bench acceptance gate asserts this)."""
        return self._max_seqs

    def can_admit(self) -> bool:
        with self._lock:
            return (len(self._tables) < self._max_seqs
                    and bool(self._free))

    def alloc_sequence(self, seq_id: str) -> List[int]:
        """Admit ``seq_id``: take its seat and first page; returns the
        (live, single-page) block table."""
        self._chaos(seq_id)
        with self._lock:
            if seq_id in self._tables:
                raise MXNetError(f"sequence {seq_id!r} already admitted")
            if len(self._tables) >= self._max_seqs or not self._free:
                raise CacheExhausted(
                    f"block pool full: {len(self._tables)}/{self._max_seqs} "
                    f"sequences, {len(self._free)} free pages — shed or "
                    "requeue the request")
            table = [self._free.pop()]
            self._tables[seq_id] = table
            self._lengths[seq_id] = 0
            self._note_locked()
            return list(table)

    def append_token(self, seq_id: str):
        """Advance ``seq_id`` one token; allocates a fresh page when the
        position crosses a block boundary. Returns
        ``(page_id, slot, table)`` for the token's write coordinates."""
        self._chaos(seq_id)
        with self._lock:
            if seq_id not in self._tables:
                raise MXNetError(f"sequence {seq_id!r} not admitted")
            pos = self._lengths[seq_id]
            table = self._tables[seq_id]
            need = pos // self.block_size
            if need >= len(table):
                if need >= self.blocks_per_seq:
                    raise CacheExhausted(
                        f"sequence {seq_id!r} exceeded its reserved "
                        f"{self.blocks_per_seq} pages (pos {pos})")
                if not self._free:
                    # unreachable for seat-admitted sequences; loud anyway
                    raise CacheExhausted(
                        f"no free page for {seq_id!r} at pos {pos} — "
                        "admission accounting violated")
                table.append(self._free.pop())
            self._lengths[seq_id] = pos + 1
            self._note_locked()
            return table[need], pos % self.block_size, list(table)

    def free_sequence(self, seq_id: str) -> None:
        """Retire ``seq_id`` and return all its pages to the free list
        (token-boundary leave)."""
        with self._lock:
            table = self._tables.pop(seq_id, None)
            self._lengths.pop(seq_id, None)
            if table:
                self._free.extend(table)
            self._note_locked()

    # -- introspection -----------------------------------------------------

    def sequence_table(self, seq_id: str) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    def sequence_length(self, seq_id: str) -> int:
        with self._lock:
            return self._lengths[seq_id]

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def active_sequences(self) -> int:
        with self._lock:
            return len(self._tables)

    def snapshot(self) -> dict:
        with self._lock:
            in_use = (self.num_blocks - 1) - len(self._free)
            return {"num_blocks": self.num_blocks,
                    "block_size": self.block_size,
                    "blocks_per_seq": self.blocks_per_seq,
                    "admission_limit": self._max_seqs,
                    "active_sequences": len(self._tables),
                    "blocks_in_use": in_use,
                    "blocks_free": len(self._free),
                    "peak_blocks_in_use": self.peak_in_use}

    def _note_locked(self) -> None:
        in_use = (self.num_blocks - 1) - len(self._free)
        if in_use > self.peak_in_use:
            self.peak_in_use = in_use
        self._g_free.set(len(self._free))
        self._g_seqs.set(len(self._tables))

    @staticmethod
    def _chaos(seq_id: str) -> None:
        from ...fault import inject
        mk = inject.active()
        if mk is not None and mk.should("decode_block_exhaustion"):
            raise CacheExhausted(
                f"chaos: seeded cache-block exhaustion for {seq_id!r}")

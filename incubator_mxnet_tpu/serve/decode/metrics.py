"""Token-level decode observability — ServeMetrics' streaming sibling.

One-shot inference has one latency; a token stream has three that matter
independently: **TTFT** (time to first token — prefill + queueing),
**ITL** (inter-token latency — the per-step cadence the SLO monitor's
p50/p99 built-ins gate), and end-to-end request latency. Plus the decode
batcher's own health: tokens/sec, step occupancy (active rows ÷ batch
rows), admissions, requeues (cache-pressure sheds), and pool pressure.

Registry series (``mxtpu_decode_*``, labeled by model) feed the
Prometheus scrape and the ``decode-itl`` SLO built-ins
(``telemetry.slo.default_slos``); the instance view is the window
``snapshot()`` the bench dumps.
"""
from __future__ import annotations

import json
from typing import Dict

from ...lockcheck import make_lock
from ...telemetry import metrics as tmetrics
from ...telemetry.metrics import Histogram
from ..metrics import _j

__all__ = ["DecodeMetrics"]


class DecodeMetrics:
    """Thread-safe token/stream counters for one decode batcher."""

    def __init__(self, reservoir: int = 8192, model: str = "default"):
        self._lock = make_lock("DecodeMetrics._lock")
        self.model = model
        self._itl = Histogram(name="itl_ms", q=(50, 99), reservoir=reservoir)
        self._ttft = Histogram(name="ttft_ms", q=(50, 99),
                               reservoir=reservoir)
        self._latency = Histogram(name="latency_ms", q=(50, 95, 99),
                                  reservoir=reservoir)
        self._g = {
            "requests": tmetrics.counter(
                "mxtpu_decode_requests_total",
                "Decode requests completed", model=model),
            "tokens": tmetrics.counter(
                "mxtpu_decode_tokens_total",
                "Tokens generated", model=model),
            "shed": tmetrics.counter(
                "mxtpu_decode_shed_total",
                "Decode requests shed (queue/cache pressure)", model=model),
            "requeued": tmetrics.counter(
                "mxtpu_decode_requeued_total",
                "Admissions bounced back to the queue", model=model),
            "failed": tmetrics.counter(
                "mxtpu_decode_failed_total",
                "Streams failed with an exception", model=model),
            "steps": tmetrics.counter(
                "mxtpu_decode_steps_total",
                "Fixed-shape decode steps executed", model=model),
            "itl": tmetrics.histogram(
                "mxtpu_decode_itl_ms",
                "Inter-token latency (ms)", q=(50, 99), model=model),
            "ttft": tmetrics.histogram(
                "mxtpu_decode_ttft_ms",
                "Time to first token (ms)", q=(50, 99), model=model),
        }
        self.requests = 0
        self.tokens = 0
        self.shed = 0
        self.requeued = 0
        self.failed = 0
        self.steps = 0
        self.step_rows = 0
        self.step_capacity = 0

    # -- recording ------------------------------------------------------
    def record_token(self, itl_ms: float) -> None:
        with self._lock:
            self.tokens += 1
            self._itl.observe(itl_ms)
        self._g["tokens"].inc()
        self._g["itl"].observe(itl_ms)

    def record_first_token(self, ttft_ms: float) -> None:
        with self._lock:
            self._ttft.observe(ttft_ms)
        self._g["ttft"].observe(ttft_ms)

    def record_stream_done(self, latency_ms: float) -> None:
        with self._lock:
            self.requests += 1
            self._latency.observe(latency_ms)
        self._g["requests"].inc()

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1
        self._g["shed"].inc()

    def record_requeue(self) -> None:
        with self._lock:
            self.requeued += 1
        self._g["requeued"].inc()

    def record_failed(self) -> None:
        with self._lock:
            self.failed += 1
        self._g["failed"].inc()

    def record_step(self, active_rows: int, capacity_rows: int) -> None:
        with self._lock:
            self.steps += 1
            self.step_rows += active_rows
            self.step_capacity += capacity_rows
        self._g["steps"].inc()

    # -- reporting ------------------------------------------------------
    def snapshot(self) -> Dict:
        from ..metrics import ServeMetrics
        with self._lock:
            return {
                "requests": self.requests,
                "tokens": self.tokens,
                "shed": self.shed,
                "requeued": self.requeued,
                "failed": self.failed,
                "steps": self.steps,
                "step_occupancy": _j(self.step_rows / self.step_capacity, 4)
                if self.step_capacity else None,
                "itl": ServeMetrics._pcts(self._itl),
                "ttft": ServeMetrics._pcts(self._ttft),
                "latency": ServeMetrics._pcts(self._latency),
            }

    def dumps(self) -> str:
        return json.dumps(self.snapshot(), indent=1, sort_keys=True)

    def reset(self) -> None:
        with self._lock:
            self._itl.reset()
            self._ttft.reset()
            self._latency.reset()
            self.requests = self.tokens = self.shed = 0
            self.requeued = self.failed = self.steps = 0
            self.step_rows = self.step_capacity = 0

"""mx.serve.decode — autoregressive decode serving.

The serving stack above this package is one-shot: a request is one
``predict`` and one reply. Generation breaks both of that stack's core
assumptions — a request's cost is unknown at admission (ragged output
lengths) and its working set grows every token (the KV cache). This
package is the decode-shaped counterpart, three layers deep:

======================  ====================================================
:mod:`.blocks`          paged KV-cache allocator: uniform cache pages,
                        per-sequence block tables, seat-based admission
                        whose capacity is PRICED (not tuned) from
                        ``MXTPU_HBM_BUDGET`` via the liveness model
:mod:`.engine`          the prefill/decode split: bucketed prefill
                        ``CompiledModel`` + ONE AOT fixed-shape decode
                        step (donated in-place cache updates) — zero
                        post-warmup recompiles across ragged lengths, by
                        construction
:mod:`.batcher`         continuous batching: requests join/leave the
                        running batch at token boundaries, streaming
                        tokens through :class:`TokenStream`
======================  ====================================================

``DecodeMetrics`` adds the token-level telemetry (ITL/TTFT histograms
feeding the ``decode-itl`` SLO built-ins); ``analysis.hlo.verify``
dispatches on :class:`DecodeEngine` so the MX706/MX709 lint gates cover
both graph families device-blind.
"""
from .blocks import (BlockPool, CacheExhausted, block_bytes,
                     blocks_per_sequence, price_capacity)
from .engine import DECODE_SITE, DecodeEngine, PrefillEntry
from .batcher import DecodeBatcher, TokenStream
from .metrics import DecodeMetrics

__all__ = [
    "BlockPool", "CacheExhausted", "blocks_per_sequence", "block_bytes",
    "price_capacity",
    "DecodeEngine", "PrefillEntry", "DECODE_SITE",
    "DecodeBatcher", "TokenStream",
    "DecodeMetrics",
]

"""DecodeBatcher — continuous batching at token boundaries.

:class:`~..batcher.DynamicBatcher` coalesces one-shot requests and drains
a whole batch before admitting the next; generation would make that
catastrophic — a 4-token reply would wait for the 64-token straggler it
was co-batched with. The decode batcher instead keeps ONE fixed-shape
decode batch running and lets requests **join and leave between steps**:

- ``submit(prompt)`` enqueues and returns a :class:`TokenStream`
  (a streaming :class:`~..batcher.ServeFuture` sibling — tokens arrive
  as they are generated, ``result()`` waits for the full sequence);
- the worker ("mx-decode-batcher") runs one engine step per token
  boundary; before each step it admits queued requests into free batch
  rows while the block pool has seats (seat-based admission — the priced
  capacity), running their bucketed prefill;
- a sequence leaves the instant it emits EOS or hits its token budget:
  its pages free, its row opens, the next queued request takes it on the
  very next boundary — no drain barrier, which is what keeps step
  occupancy (and therefore tokens/sec) high under ragged lengths.

Chaos contract (``fault.inject``): seeded ``decode_block_exhaustion``
sheds/requeues loudly (``decode.shed``/``decode.requeue`` events, bounded
requeues, then a :class:`~.blocks.CacheExhausted` on the stream);
``decode_replica_death`` fails every in-flight stream with
``ReplicaUnavailable`` after ONE flight-recorder bundle — a stream never
hangs and is never silently truncated.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import List, Optional

import numpy as onp

from ...base import MXNetError
from ...lockcheck import make_lock
from ... import profiler
from ...telemetry import events as _tele
from ...telemetry import trace as _trace
from ...telemetry import goodput as _goodput
from ..batcher import QueueFullError
from .blocks import CacheExhausted
from .engine import DecodeEngine
from .metrics import DecodeMetrics

__all__ = ["DecodeBatcher", "TokenStream"]

_STREAM_IDS = itertools.count(1)


class TokenStream:
    """Streaming result handle: tokens land one by one; the full sequence
    lands at :meth:`result`. API-compatible with
    :class:`~..batcher.ServeFuture` (``done``/``wait``/``result``/
    ``set_exception``) so router/client plumbing treats both alike."""

    def __init__(self):
        self._cond = threading.Condition()
        self._tokens: List[int] = []
        self._read = 0
        self._finished = False
        self._reason: Optional[str] = None
        self._exc: Optional[BaseException] = None

    # -- producer side (batcher worker) ---------------------------------
    def put_token(self, tok: int) -> None:
        with self._cond:
            self._tokens.append(int(tok))
            self._cond.notify_all()

    def finish(self, reason: str = "eos") -> None:
        with self._cond:
            self._finished = True
            self._reason = reason
            self._cond.notify_all()

    def set_exception(self, exc: BaseException) -> None:
        with self._cond:
            self._exc = exc
            self._finished = True
            self._cond.notify_all()

    def set_result(self, tokens) -> None:
        with self._cond:
            self._tokens = [int(t) for t in tokens]
            self._finished = True
            self._reason = "set_result"
            self._cond.notify_all()

    # -- consumer side ---------------------------------------------------
    def next_token(self, timeout: Optional[float] = None) -> Optional[int]:
        """Block for the next unread token; ``None`` = stream finished.
        Raises the stream's exception (a failed stream never hangs)."""
        with self._cond:
            while True:
                if self._exc is not None:
                    raise self._exc
                if self._read < len(self._tokens):
                    self._read += 1
                    return self._tokens[self._read - 1]
                if self._finished:
                    return None
                if not self._cond.wait(timeout):
                    raise TimeoutError("no token within timeout; stream "
                                       "still generating")

    def tokens(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    def done(self) -> bool:
        with self._cond:
            return self._finished

    def finish_reason(self) -> Optional[str]:
        with self._cond:
            return self._reason

    def wait(self, timeout: Optional[float] = None) -> bool:
        with self._cond:
            self._cond.wait_for(lambda: self._finished, timeout)
            return self._finished

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """The full generated sequence (excluding BOS, including EOS when
        emitted)."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._finished, timeout):
                raise TimeoutError("generation still in flight")
            if self._exc is not None:
                raise self._exc
            return list(self._tokens)


class _DecodeRequest:
    __slots__ = ("src", "valid", "max_new", "tenant", "stream", "t_enqueue",
                 "rid", "span", "requeues")

    def __init__(self, src, valid, max_new, tenant):
        self.src = src
        self.valid = valid
        self.max_new = max_new
        self.tenant = tenant
        self.stream = TokenStream()
        self.t_enqueue = time.perf_counter()
        self.rid = f"d{next(_STREAM_IDS)}"
        self.span = None
        self.requeues = 0


class _Active:
    __slots__ = ("req", "row", "last_token", "produced", "t_admit", "t_last")

    def __init__(self, req: _DecodeRequest, row: int, bos: int):
        self.req = req
        self.row = row
        self.last_token = bos
        self.produced = 0
        self.t_admit = time.perf_counter()
        self.t_last = self.t_admit


class DecodeBatcher:
    """Continuous batching over one :class:`~.engine.DecodeEngine`.

    ``submit(prompt_tokens)`` → :class:`TokenStream`. Env knobs:
    ``MXTPU_DECODE_QUEUE_LIMIT``, ``MXTPU_DECODE_MAX_REQUEUES`` (see
    docs/env_vars.md).
    """

    def __init__(self, engine: DecodeEngine,
                 queue_limit: Optional[int] = None,
                 max_requeues: Optional[int] = None,
                 block_secs: float = 0.0,
                 metrics: Optional[DecodeMetrics] = None,
                 qos=None):
        from ...util import getenv
        self.engine = engine
        #: optional router.TokenRateBudget: per-tenant tokens/sec QoS,
        #: consulted BEFORE a request queues (shed-before-breach)
        self.qos = qos
        self.queue_limit = int(getenv("MXTPU_DECODE_QUEUE_LIMIT")
                               if queue_limit is None else queue_limit)
        self.max_requeues = int(getenv("MXTPU_DECODE_MAX_REQUEUES")
                                if max_requeues is None else max_requeues)
        self.block_secs = float(block_secs)
        self.metrics = metrics or DecodeMetrics()
        self._queue: deque = deque()
        self._lock = make_lock("DecodeBatcher._lock")
        self._wake = threading.Event()
        self._active: List[Optional[_Active]] = [None] * engine.max_batch
        #: requests popped from the queue but not yet landed in a batch
        #: row — stop(drain=True) must not mistake this window for idle
        self._inflight_admits = 0
        self._stop = False
        self._closed = False
        self._worker: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "DecodeBatcher":
        if self._worker is None or not self._worker.is_alive():
            self._stop = False
            self._closed = False
            self._worker = threading.Thread(target=self._run,
                                            name="mx-decode-batcher",
                                            daemon=True)
            self._worker.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """``drain=True`` finishes in-flight generation and the queue
        first (bounded by ``timeout`` on the monotonic clock); leftovers
        fail loudly with "batcher stopped". One ``decode.drain`` event
        records the drained/abandoned split."""
        t0 = time.monotonic()
        served_before = self.metrics.requests
        self._closed = True
        if self._worker is not None:
            if drain:
                while ((self.depth() or self.active_sequences()
                        or self._admits_in_flight())
                       and time.monotonic() - t0 < timeout):
                    time.sleep(0.005)
            self._stop = True
            self._wake.set()
            self._worker.join(timeout)
        abandoned = 0
        with self._lock:
            leftovers = list(self._queue)
            self._queue.clear()
        for req in leftovers:
            req.stream.set_exception(MXNetError("batcher stopped"))
            if req.span is not None:
                req.span.finish(outcome="abandoned")
            abandoned += 1
        for act in list(self._active):
            if act is None:
                continue
            self._retire(act, reason="stopped",
                         exc=MXNetError("batcher stopped"))
            abandoned += 1
        _tele.emit("decode.drain",
                   severity="warning" if abandoned else "info",
                   model=self.metrics.model, drain=bool(drain),
                   drained=self.metrics.requests - served_before,
                   abandoned=abandoned,
                   wall_ms=round((time.monotonic() - t0) * 1e3, 3))

    def worker_alive(self) -> bool:
        w = self._worker
        return w is not None and w.is_alive()

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def active_sequences(self) -> int:
        with self._lock:
            return sum(1 for a in self._active if a is not None)

    def _admits_in_flight(self) -> int:
        with self._lock:
            return self._inflight_admits

    def retry_after_s(self) -> float:
        """Backoff hint: roughly one sequence's residence time per queued
        batch-slot wave."""
        waves = max(1, (self.depth() + self.engine.max_batch - 1)
                    // self.engine.max_batch)
        return round(max(0.05, waves * 0.1), 3)

    def stats(self) -> dict:
        return {"metrics": self.metrics.snapshot(),
                "engine": self.engine.stats(),
                "queue_depth": self.depth(),
                "active_sequences": self.active_sequences()}

    # -- client side ----------------------------------------------------
    def submit(self, src_tokens, valid_len: Optional[int] = None,
               max_new_tokens: Optional[int] = None,
               tenant: Optional[str] = None) -> TokenStream:
        """Enqueue one prompt; returns its token stream. Oversized
        prompts are rejected here (bucket-table overflow), a full queue
        raises :class:`~..batcher.QueueFullError` (after blocking up to
        ``block_secs`` when configured)."""
        src = onp.asarray(src_tokens, "int32").reshape(-1)
        self.engine._table.bucket("src", src.shape[0])  # raises on overflow
        max_new = min(int(max_new_tokens or self.engine.max_target_len - 1),
                      self.engine.max_target_len - 1)
        req = _DecodeRequest(src, valid_len, max_new, tenant)
        if self.qos is not None and not self.qos.try_take(
                tenant or "default", max_new):
            self.metrics.record_shed()
            _tele.emit("decode.shed", severity="warning",
                       request_id=req.rid, model=self.metrics.model,
                       tenant=tenant, reason="tenant_tokens",
                       est_tokens=max_new)
            from ..router import ShedError
            raise ShedError(
                f"tenant {tenant or 'default'!r} is over its tokens/sec "
                f"budget ({self.qos.rate}/s, est {max_new} tokens)",
                retry_after=self.retry_after_s(), reason="tenant_tokens")
        if _trace.current() is not None:
            req.span = _trace.start_span("decode.request", kind="server",
                                         request=req.rid,
                                         model=self.metrics.model)
        deadline = time.time() + self.block_secs
        while True:
            with self._lock:
                if self._closed:
                    if req.span is not None:
                        req.span.finish(error="batcher_stopped")
                    raise MXNetError("batcher stopped; submit rejected")
                if len(self._queue) < self.queue_limit:
                    self._queue.append(req)
                    break
            if time.time() >= deadline:
                self.metrics.record_shed()
                _tele.emit("decode.shed", severity="warning",
                           request_id=req.rid, model=self.metrics.model,
                           reason="queue_full",
                           queue_limit=self.queue_limit)
                if req.span is not None:
                    req.span.finish(outcome="rejected")
                raise QueueFullError(
                    f"decode queue is full ({self.queue_limit} requests); "
                    "backpressure — retry with backoff or raise "
                    "MXTPU_DECODE_QUEUE_LIMIT")
            time.sleep(0.0005)
        with _trace.use(req.span.ctx if req.span is not None else None):
            _tele.emit("decode.admit", request_id=req.rid,
                       model=self.metrics.model, depth=self.depth())
        self._wake.set()
        return req.stream

    # -- worker side ----------------------------------------------------
    def _run(self) -> None:
        while not self._stop:
            admitted = self._admit_pending()
            if any(a is not None for a in self._active):
                self._step_once()
                continue
            if not admitted:
                self._wake.wait(timeout=0.05 if self.depth() else None)
                self._wake.clear()

    def _free_row(self) -> Optional[int]:
        for i, a in enumerate(self._active):
            if a is None:
                return i
        return None

    def _admit_pending(self) -> bool:
        """Token-boundary join: move queued requests into free batch rows
        while the block pool has seats. Prefill runs here (bucketed, a
        warm compile-cache hit)."""
        admitted = False
        while True:
            row = self._free_row()
            if row is None or not self.engine.pool.can_admit():
                return admitted
            with self._lock:
                if not self._queue:
                    return admitted
                req = self._queue.popleft()
                self._inflight_admits += 1
            try:
                try:
                    table = self.engine.pool.alloc_sequence(req.rid)
                except CacheExhausted as e:
                    self._bounce(req, e)
                    continue
                try:
                    t0 = time.perf_counter()
                    with profiler.Scope("decode.prefill"):
                        cross_row, lp = self.engine.prefill_request(
                            req.src, req.valid)
                    if _goodput.enabled():
                        _goodput.note_serve(
                            "prefill", tokens=lp,
                            wall_ms=(time.perf_counter() - t0) * 1e3)
                except BaseException as e:  # noqa: BLE001 — to the stream
                    self.engine.pool.free_sequence(req.rid)
                    req.stream.set_exception(e)
                    self.metrics.record_failed()
                    if req.span is not None:
                        req.span.finish(error=type(e).__name__)
                    _tele.emit("decode.execute", severity="error",
                               request_id=req.rid, model=self.metrics.model,
                               stage="prefill",
                               error=f"{type(e).__name__}: {e}")
                    continue
                self.engine.bind_row(row, cross_row, lp)
                self.engine.set_row_table(row, table)
                self._active[row] = _Active(req, row, self.engine.bos_id)
                admitted = True
                with _trace.use(req.span.ctx
                                if req.span is not None else None):
                    _tele.emit("decode.join", request_id=req.rid,
                               model=self.metrics.model, row=row,
                               prompt_len=lp,
                               active=self.active_sequences())
            finally:
                with self._lock:
                    self._inflight_admits -= 1
        return admitted

    def _bounce(self, req: _DecodeRequest, exc: CacheExhausted) -> None:
        """Cache-pressure admission failure: requeue (bounded), then shed
        loudly — never silently drop."""
        req.requeues += 1
        if req.requeues <= self.max_requeues:
            self.metrics.record_requeue()
            _tele.emit("decode.requeue", severity="warning",
                       request_id=req.rid, model=self.metrics.model,
                       attempt=req.requeues, error=str(exc))
            with self._lock:
                self._queue.append(req)
        else:
            self.metrics.record_shed()
            _tele.emit("decode.shed", severity="warning",
                       request_id=req.rid, model=self.metrics.model,
                       reason="cache_exhausted", attempts=req.requeues)
            if req.span is not None:
                req.span.finish(outcome="shed")
            req.stream.set_exception(exc)

    def _step_once(self) -> None:
        """One token boundary: advance every active sequence one token
        through the fixed-shape decode executable."""
        from ...fault import inject
        mk = inject.active()
        if mk is not None and mk.should("decode_replica_death"):
            self._replica_death()
            return
        B = self.engine.max_batch
        positions = onp.zeros((B,), "int32")
        tokens = onp.zeros((B,), "int32")
        stepping: List[_Active] = []
        for act in self._active:
            if act is None:
                continue
            try:
                _page, _slot, table = self.engine.pool.append_token(
                    act.req.rid)
            except CacheExhausted as e:
                # only reachable via chaos (seat-based admission): fail
                # the stream loudly rather than truncate it silently
                self._retire(act, reason="cache_exhausted", exc=e)
                continue
            self.engine.set_row_table(act.row, table)
            positions[act.row] = (
                self.engine.pool.sequence_length(act.req.rid) - 1)
            tokens[act.row] = act.last_token
            stepping.append(act)
        if not stepping:
            return
        t0 = time.perf_counter()
        with profiler.Scope("decode.step"):
            logits = self.engine.run_step(positions, tokens)
        dt_ms = (time.perf_counter() - t0) * 1e3
        self.metrics.record_step(len(stepping), B)
        if _goodput.enabled():
            _goodput.note_serve("decode", tokens=len(stepping),
                                wall_ms=dt_ms)
        now = time.perf_counter()
        for act in stepping:
            nxt = int(onp.argmax(logits[act.row]))
            if act.produced == 0:
                self.metrics.record_first_token(
                    (now - act.req.t_enqueue) * 1e3)
            self.metrics.record_token((now - act.t_last) * 1e3)
            act.t_last = now
            act.produced += 1
            act.last_token = nxt
            act.req.stream.put_token(nxt)
            if nxt == self.engine.eos_id:
                self._retire(act, reason="eos")
            elif act.produced >= act.req.max_new:
                self._retire(act, reason="length")

    def _retire(self, act: _Active, reason: str,
                exc: Optional[BaseException] = None) -> None:
        """Token-boundary leave: free the pages and the batch row; the
        next queued request joins on the following boundary."""
        self.engine.pool.free_sequence(act.req.rid)
        self.engine.clear_row(act.row)
        self._active[act.row] = None
        req = act.req
        lat_ms = (time.perf_counter() - req.t_enqueue) * 1e3
        with _trace.use(req.span.ctx if req.span is not None else None):
            if exc is None:
                self.metrics.record_stream_done(lat_ms)
                req.stream.finish(reason)
                _tele.emit("decode.reply", request_id=req.rid,
                           model=self.metrics.model, reason=reason,
                           tokens=act.produced,
                           latency_ms=round(lat_ms, 3))
            else:
                self.metrics.record_failed()
                req.stream.set_exception(exc)
                _tele.emit("decode.execute", severity="error",
                           request_id=req.rid, model=self.metrics.model,
                           stage="decode", reason=reason,
                           error=f"{type(exc).__name__}: {exc}")
        if req.span is not None:
            if exc is None:
                req.span.finish(latency_ms=round(lat_ms, 3),
                                tokens=act.produced, reason=reason)
            else:
                req.span.finish(error=type(exc).__name__)

    def _replica_death(self) -> None:
        """Chaos mid-generation replica death: ONE flight bundle, every
        in-flight stream fails with ``ReplicaUnavailable`` (the router's
        retry classifier requeues it) — nothing hangs, nothing truncates
        silently."""
        from ...telemetry import flight as _flight
        from ..replica import ReplicaUnavailable
        victims = [a for a in self._active if a is not None]
        _tele.emit("decode.replica_death", severity="error",
                   model=self.metrics.model, in_flight=len(victims),
                   queued=self.depth())
        _flight.dump("decode_replica_death", model=self.metrics.model,
                     in_flight=len(victims), queued=self.depth())
        exc = ReplicaUnavailable(
            "decode replica died mid-generation (chaos); stream aborted — "
            "requeue the request")
        for act in victims:
            self._retire(act, reason="replica_death", exc=exc)
        self.engine.reset_cache()

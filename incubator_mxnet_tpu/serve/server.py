"""Server — in-process and TCP front ends over the registry.

Reference counterpart: MXNet Model Server sat *outside* the framework
(Java frontend, HTTP, process boundary); this front end is deliberately
minimal and in-tree — enough protocol to smoke-test the full
request → queue → batch → compiled-bucket → response path over a real
socket, while production deployments are expected to put their own RPC
layer in front of :meth:`Server.submit`.

Wire protocol: newline-delimited JSON over TCP, one object per request::

    {"model": "lenet", "inputs": [[...nested lists...], ...],
     "dtypes": ["float32"], "version": 2,          # version optional
     "trace": {"trace_id": "...", "span_id": "...", "sampled": true}}
    -> {"ok": true, "outputs": [...], "latency_ms": 1.8,
        "trace_id": "..."}                         # echoed when traced

    {"cmd": "metrics", "model": "lenet"}   -> {"ok": true, "metrics": {...}}
    {"cmd": "models"}                      -> {"ok": true, "models": {...}}
    {"cmd": "prometheus"}  -> {"ok": true, "text": "<metrics scrape>"}
    {"cmd": "telemetry"}   -> {"ok": true, "telemetry": {...snapshot...}}

Generation streams — one reply line per token as it is produced by an
attached :class:`~.decode.DecodeBatcher` (:meth:`Server.attach_decoder`),
then a terminal ``done`` line::

    {"cmd": "generate", "model": "nmt", "tokens": [5, 9, 3],
     "max_new_tokens": 16, "tenant": "t1"}
    -> {"ok": true, "token": 7, "i": 0}
    -> {"ok": true, "token": 2, "i": 1}
    -> {"ok": true, "done": true, "reason": "eos", "tokens": [7, 2],
        "latency_ms": 12.1}

:func:`client_generate` is the matching streaming client (a generator).

The optional ``trace`` field carries W3C-style distributed-trace context
across the wire (``mx.telemetry.trace``): the server resumes the
caller's context and opens one ``serve.wire`` span over the request, so
a traced client renders the TCP hop, the batcher, and the compiled
execution as one rooted tree. :func:`client_call` injects the active
context automatically.

Each model gets one :class:`DynamicBatcher` whose model thunk resolves
through the registry at flush time, so a version swap redirects the very
next batch without restarting the server.

A :class:`Server` normally fronts one :class:`ModelRegistry`; pass
``router=`` instead to put the TCP protocol in front of the HA tier —
predict requests route through :meth:`Router.call_detailed` (failover,
hedging, admission control), with shed/deadline rejections surfacing as
structured ``retry_after`` replies. Router mode serves the active
version only: a ``version``-pinned request is refused with a structured
error rather than silently answered by whatever version is live.
"""
from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Dict, Optional

import numpy as onp

from ..base import MXNetError
from ..lockcheck import make_lock
from ..telemetry import trace as _trace
from .batcher import DynamicBatcher, ServeFuture
from .registry import ModelRegistry

__all__ = ["Server", "client_call", "client_generate"]


class Server:
    """Serve every model in ``registry`` — in-process via :meth:`submit`,
    over TCP via :meth:`start` (``port=0`` picks a free port; read it back
    from ``server.port``). With ``router=`` the predict path routes
    through the HA tier instead of a local batcher."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 host: str = "127.0.0.1",
                 port: int = 0, max_delay_ms: Optional[float] = None,
                 queue_limit: Optional[int] = None, router=None):
        if registry is None and router is None:
            raise MXNetError("Server needs a registry or a router")
        self.registry = registry
        self.router = router
        self.host = host
        self.port = port
        self._batcher_kw = dict(max_delay_ms=max_delay_ms,
                                queue_limit=queue_limit)
        self._batchers: Dict[str, DynamicBatcher] = {}
        self._decoders: Dict[str, object] = {}
        self._lock = make_lock("Server._lock")
        self._tcp: Optional[socketserver.ThreadingTCPServer] = None
        self._tcp_thread: Optional[threading.Thread] = None

    # -- in-process path ------------------------------------------------
    def batcher(self, name: str) -> DynamicBatcher:
        from .batcher import make_registry_batcher
        if self.registry is None:
            # router-backed mode: placement lives in the HA tier; a
            # batcher built over a None registry would fail on first
            # flush AND stay cached under the model name
            raise MXNetError(
                "router-backed Server has no local batchers — submit "
                "through the wire protocol or Router.call instead")
        with self._lock:
            b = self._batchers.get(name)
            if b is None:
                b = make_registry_batcher(self.registry, name,
                                          **self._batcher_kw)
                self._batchers[name] = b
        return b

    def submit(self, name: str, *arrays) -> ServeFuture:
        """Enqueue one single-example request for ``name``'s active
        version; returns the future."""
        return self.batcher(name).submit(*arrays)

    def attach_decoder(self, name: str, batcher) -> None:
        """Expose a started :class:`~.decode.DecodeBatcher` under model
        name ``name`` for the ``generate`` wire command (decoders wrap a
        live model + engine, so they attach explicitly rather than load
        through the registry)."""
        with self._lock:
            self._decoders[name] = batcher

    def decoder(self, name: str):
        with self._lock:
            b = self._decoders.get(name)
        if b is None:
            raise MXNetError(
                f"no decoder attached for model {name!r}; call "
                "Server.attach_decoder(name, DecodeBatcher) first")
        return b

    def metrics(self, name: str) -> dict:
        b = self.batcher(name)
        return b.metrics.snapshot(self.registry.get(name))

    def prometheus(self, openmetrics: bool = False) -> str:
        """The process-wide telemetry scrape: every ``mxtpu_*`` series —
        serving counters/latency by model, training step counters,
        compile ledger, event totals. Default is strict text exposition
        0.0.4 (no exemplar suffixes — anything after the value breaks a
        real Prometheus scrape at that content type);
        ``openmetrics=True`` renders the exemplar-bearing OpenMetrics
        exposition with its mandatory ``# EOF`` terminator."""
        from .. import telemetry
        if openmetrics:
            return telemetry.prometheus_text(exemplars=True) + "# EOF\n"
        return telemetry.prometheus_text(exemplars=False)

    # -- TCP front end --------------------------------------------------
    def start(self) -> "Server":
        if self._tcp is not None:
            return self
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        reply = outer._handle_line(line)
                    except Exception as e:  # noqa: BLE001 — wire boundary
                        reply = {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"}
                        # shed/overload errors carry a client backoff
                        # hint — surface it structurally, not in prose
                        retry_after = getattr(e, "retry_after", None)
                        if retry_after is not None:
                            reply["retry_after"] = retry_after
                        trace_id = getattr(e, "trace_id", None)
                        if trace_id is not None:
                            reply["trace_id"] = trace_id
                    # a generate stream returns an iterator of reply
                    # docs — each is written (and flushed) as the token
                    # is produced, so the client reads a live stream
                    replies = [reply] if isinstance(reply, dict) else reply
                    for doc in replies:
                        self.wfile.write(
                            (json.dumps(doc) + "\n").encode("utf-8"))
                        self.wfile.flush()

        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = TCP((self.host, self.port), Handler)
        self.port = self._tcp.server_address[1]
        self._tcp_thread = threading.Thread(
            target=self._tcp.serve_forever, name="mx-serve-tcp", daemon=True)
        self._tcp_thread.start()
        return self

    def stop(self) -> None:
        if self._tcp is not None:
            self._tcp.shutdown()
            self._tcp.server_close()
            self._tcp = None
        with self._lock:
            batchers = list(self._batchers.values())
            self._batchers.clear()
            # attached decoders are externally owned (they wrap a caller-
            # built engine) — detach without stopping them
            self._decoders.clear()
        for b in batchers:
            b.stop()

    # -- protocol -------------------------------------------------------
    def _handle_line(self, line: bytes) -> dict:
        msg = json.loads(line.decode("utf-8"))
        cmd = msg.get("cmd")
        if cmd == "models":
            if self.registry is None:
                return {"ok": True,
                        "models": {"router": self.router.snapshot()}}
            return {"ok": True, "models": self.registry.models()}
        if cmd == "metrics":
            if self.registry is None:
                return {"ok": True,
                        "metrics": {"router": self.router.snapshot()}}
            return {"ok": True, "metrics": self.metrics(msg["model"])}
        if cmd == "prometheus":
            # text-format scrape over the JSON-lines protocol; a real
            # Prometheus deployment fronts this with its own HTTP shim.
            # Default stays strict 0.0.4; {"format": "openmetrics"}
            # switches to the exemplar-bearing exposition (and the
            # content type a collector needs to parse it)
            if msg.get("format") == "openmetrics":
                return {"ok": True,
                        "content_type": ("application/openmetrics-text; "
                                         "version=1.0.0; charset=utf-8"),
                        "text": self.prometheus(openmetrics=True)}
            return {"ok": True,
                    "content_type": "text/plain; version=0.0.4",
                    "text": self.prometheus()}
        if cmd == "telemetry":
            from .. import telemetry
            return {"ok": True, "telemetry": telemetry.snapshot()}
        if cmd == "generate":
            return self._generate(msg)
        if cmd is not None:
            raise MXNetError(f"unknown cmd {cmd!r}")
        # a predict request: resume the caller's carried trace context
        # (if any) and span the wire hop, so the TCP boundary is one
        # stitched edge in the request's tree instead of a correlation
        # cliff
        ctx = _trace.from_wire(msg.get("trace"))
        with _trace.use(ctx), \
                _trace.span("serve.wire", kind="server",
                            model=msg.get("model")) as wire_sp:
            try:
                reply = self._predict(msg)
            except Exception as e:
                # the error reply the handler builds from this exception
                # is the one an on-call most wants to correlate — pin the
                # wire span's trace id on it so sheds/timeouts keep the
                # "structured errors carry trace_id" contract
                if ctx is not None or _trace.sample_rate() > 0:
                    e.trace_id = wire_sp.ctx.trace_id
                raise
            if ctx is not None or _trace.sample_rate() > 0:
                reply.setdefault("trace_id", wire_sp.ctx.trace_id)
            return reply

    def _generate(self, msg: dict):
        """One generation stream: submit to the attached decoder, return
        a generator of wire replies — one per token as it lands, then a
        terminal ``done`` doc. Submit-time sheds (queue full, tenant
        tokens/sec budget) raise here and surface as the usual structured
        error line with ``retry_after``."""
        from ..util import getenv
        name = msg["model"]
        b = self.decoder(name)
        stream = b.submit(
            onp.asarray(msg["tokens"], "int32"),
            valid_len=msg.get("valid"),
            max_new_tokens=msg.get("max_new_tokens"),
            tenant=msg.get("tenant"))
        timeout_s = float(getenv("MXTPU_SERVE_REQUEST_TIMEOUT_S"))
        t0 = time.perf_counter()

        def _replies():
            i = 0
            try:
                while True:
                    tok = stream.next_token(timeout=timeout_s)
                    if tok is None:
                        break
                    yield {"ok": True, "token": tok, "i": i}
                    i += 1
            except Exception as e:  # noqa: BLE001 — wire boundary
                doc = {"ok": False, "error": f"{type(e).__name__}: {e}",
                       "model": name}
                retry_after = getattr(e, "retry_after", None)
                if retry_after is None:
                    retry_after = b.retry_after_s()
                doc["retry_after"] = retry_after
                yield doc
                return
            yield {"ok": True, "done": True,
                   "reason": stream.finish_reason(),
                   "tokens": stream.tokens(),
                   "latency_ms": round((time.perf_counter() - t0) * 1e3, 3)}

        return _replies()

    def _predict(self, msg: dict) -> dict:
        name = msg["model"]
        version = msg.get("version")
        tenant = msg.get("tenant")
        dtypes = msg.get("dtypes")
        t0 = time.perf_counter()
        if self.registry is None:
            # HA mode: the router owns placement/failover/shedding;
            # Shed/Deadline errors surface through the generic handler
            # with their structured retry_after. Wire floats default to
            # f32 (no model avals to consult here; f64 would silently
            # miss every compiled bucket).
            if version is not None:
                # replicas always serve the synced active version —
                # silently answering a pinned request with a different
                # version would be worse than refusing it
                raise MXNetError(
                    f"version pinning (version={version!r}) is not "
                    "supported by the router-backed tier; replicas "
                    "serve the active version only")
            arrays = []
            for i, payload in enumerate(msg["inputs"]):
                dtype = dtypes[i] if dtypes and i < len(dtypes) else None
                a = onp.asarray(payload, dtype=dtype)
                if dtype is None and a.dtype == onp.float64:
                    a = a.astype(onp.float32)
                arrays.append(a)
            val, info = self.router.call_detailed(name, *arrays,
                                                  tenant=tenant)
            result = val if isinstance(val, tuple) else (val,)
            return {"ok": True,
                    "outputs": [onp.asarray(r).tolist() for r in result],
                    "replica": info["replica"],
                    "latency_ms": round((time.perf_counter() - t0) * 1e3,
                                        3)}
        model = self.registry.get(name, version)
        arrays = []
        for i, payload in enumerate(msg["inputs"]):
            dtype = (dtypes[i] if dtypes and i < len(dtypes)
                     else model._in_avals[i][1])
            arrays.append(onp.asarray(payload, dtype=dtype))
        if version is not None:
            # pinned-version requests bypass the shared batcher (which
            # always serves the active version)
            outs = model.predict(*[a[None] for a in arrays])
            outs = outs if isinstance(outs, tuple) else (outs,)
            result = tuple(o.asnumpy()[0] for o in outs)
        else:
            b = self.batcher(name)
            fut = b.submit(*arrays)
            from ..util import getenv
            timeout_s = float(getenv("MXTPU_SERVE_REQUEST_TIMEOUT_S"))
            try:
                result = fut.result(timeout=timeout_s)
            except TimeoutError:
                # structured, retryable reply — a deadline miss is an
                # operational state, not a stack trace
                return {"ok": False, "error": "deadline_exceeded",
                        "model": name, "timeout_s": timeout_s,
                        "retry_after": b.retry_after_s()}
            if not isinstance(result, tuple):
                result = (result,)
        return {"ok": True,
                "outputs": [r.tolist() for r in result],
                "latency_ms": round((time.perf_counter() - t0) * 1e3, 3)}


def client_call(host: str, port: int, payload: dict,
                timeout: float = 30.0) -> dict:
    """Minimal blocking client for the JSON-lines protocol (used by the
    tests and the bench; real clients keep the socket open). An active
    distributed-trace context is injected as the ``trace`` field (unless
    the payload already carries one), so the server's ``serve.wire`` span
    parents under the caller's tree."""
    if "cmd" not in payload and "trace" not in payload:
        wire_ctx = _trace.to_wire()
        if wire_ctx is not None:
            payload = {**payload, "trace": wire_ctx}
    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.sendall((json.dumps(payload) + "\n").encode("utf-8"))
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = sock.recv(65536)
            if not chunk:
                # surface the transport failure, not a JSON parse error
                # on a truncated buffer
                raise ConnectionError(
                    f"server closed the connection before a complete "
                    f"reply ({len(buf)} bytes received)")
            buf += chunk
    return json.loads(buf.decode("utf-8"))


def client_generate(host: str, port: int, payload: dict,
                    timeout: float = 60.0):
    """Streaming client for the ``generate`` command: a generator over
    the server's reply lines — one ``{"ok": true, "token": t, "i": n}``
    per generated token as it arrives, ending with the terminal ``done``
    doc (or a single structured-error doc). ``payload`` needs ``model``
    and ``tokens``; ``cmd`` is filled in."""
    payload = dict(payload)
    payload.setdefault("cmd", "generate")
    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.sendall((json.dumps(payload) + "\n").encode("utf-8"))
        with sock.makefile("rb") as rf:
            for line in rf:
                doc = json.loads(line.decode("utf-8"))
                yield doc
                if not doc.get("ok") or doc.get("done"):
                    return
    raise ConnectionError("server closed the generate stream before the "
                          "terminal done/error line")

"""Stateful RNG facade over JAX's functional PRNG.

Reference parity: MXNet keeps a per-device stateful PRNG requested by ops via
``ResourceRequest::kRandom`` (``src/resource.cc``) and seeded by
``mx.random.seed`` (``python/mxnet/random.py``). JAX PRNG is functional
(explicit keys); this module holds one key per Context and splits it on every
draw, giving MXNet's stateful surface with JAX's reproducibility.

Hybridized (jitted) code must not hit hidden state — the gluon CachedOp pulls
an explicit key from here *outside* the traced function and feeds it as an
argument (SURVEY §7 "RNG parity").
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import jax

from .context import Context, current_context
from .lockcheck import make_lock

__all__ = ["seed", "next_key", "fork_key", "get_state", "trace_rng",
           "uniform", "normal", "randn", "randint", "exponential", "poisson",
           "gamma", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle"]

_lock = make_lock("random._lock")
_keys: Dict[Context, jax.Array] = {}
_root_seed = 0


class _TraceRNG(threading.local):
    def __init__(self):
        self.stack = []


_TRACE_RNG = _TraceRNG()


class trace_rng:
    """While a HybridBlock cache is traced, ``next_key`` splits from this
    explicit key (a jit argument) instead of the hidden per-device stream, so
    randomness is an input of the compiled executable (SURVEY §7 RNG parity)."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        _TRACE_RNG.stack.append(self)
        return self

    def __exit__(self, *exc):
        _TRACE_RNG.stack.pop()

    def split(self):
        self._key, sub = jax.random.split(self._key)
        return sub


def _impl() -> str:
    """PRNG implementation. TPU default is ``rbg`` (XLA RngBitGenerator):
    bit generation runs at a fraction of threefry's cost, which matters for
    per-step dropout masks over (B, L, hidden) activations — the reference's
    cuDNN dropout uses a device generator of the same character. Override
    with MXTPU_RNG_IMPL=threefry2x32 for strict cross-backend key parity."""
    import os
    env = os.environ.get("MXTPU_RNG_IMPL")
    if env:
        return env
    return "rbg" if jax.default_backend() == "tpu" else "threefry2x32"


def seed(seed_state: int, ctx: str | Context = "all") -> None:
    """Seed the generator(s). ``ctx='all'`` reseeds every context
    (reference: MXRandomSeed / MXRandomSeedContext)."""
    global _root_seed
    with _lock:
        if isinstance(ctx, str) and ctx == "all":
            _root_seed = seed_state
            _keys.clear()
        else:
            ctx = Context(ctx) if not isinstance(ctx, Context) else ctx
            _keys[ctx] = jax.random.key(seed_state, impl=_impl())


def _key_for(ctx: Context) -> jax.Array:
    if ctx not in _keys:
        # Derive a distinct stream per (root seed, device type, device id).
        base = jax.random.key(_root_seed, impl=_impl())
        _keys[ctx] = jax.random.fold_in(
            jax.random.fold_in(base, ctx.device_typeid), ctx.device_id
        )
    return _keys[ctx]


def next_key(ctx: Optional[Context] = None) -> jax.Array:
    """Draw-and-advance: returns a fresh subkey, advancing the context's
    stateful stream."""
    if _TRACE_RNG.stack:
        return _TRACE_RNG.stack[-1].split()
    ctx = ctx or current_context()
    with _lock:
        key = _key_for(ctx)
        new, sub = jax.random.split(key)
        _keys[ctx] = new
    return sub


def fork_key(ctx: Optional[Context] = None, num: int = 1):
    """Split N independent subkeys in one advance (for multi-worker use)."""
    ctx = ctx or current_context()
    with _lock:
        key = _key_for(ctx)
        parts = jax.random.split(key, num + 1)
        _keys[ctx] = parts[0]
    return parts[1:]


def get_state(ctx: Optional[Context] = None) -> jax.Array:
    ctx = ctx or current_context()
    with _lock:
        return _key_for(ctx)


# ---------------------------------------------------------------------------
# module-level sampling API (reference: python/mxnet/random.py delegates to
# the generated sampling ops; ours live in ndarray/random.py)
# ---------------------------------------------------------------------------

def _delegate(name):
    def fn(*args, **kwargs):
        from .ndarray import random as _ndr
        return getattr(_ndr, name)(*args, **kwargs)
    fn.__name__ = name
    fn.__doc__ = f"mx.random.{name}: see mx.nd.random.{name}."
    return fn


uniform = _delegate("uniform")
normal = _delegate("normal")
randn = _delegate("randn")
randint = _delegate("randint")
exponential = _delegate("exponential")
poisson = _delegate("poisson")
gamma = _delegate("gamma")
negative_binomial = _delegate("negative_binomial")
generalized_negative_binomial = _delegate("generalized_negative_binomial")
multinomial = _delegate("multinomial")
shuffle = _delegate("shuffle")

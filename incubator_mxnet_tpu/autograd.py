"""Autograd: imperative tape + reverse-mode differentiation over jax.vjp.

TPU-native counterpart of ``src/imperative/imperative.cc``
(``Imperative::RecordOp`` / ``Imperative::Backward``) and the Python surface
``python/mxnet/autograd.py``. Where the reference records an NNVM graph and
runs the ``Gradient`` pass, we record each dispatched op as a pure JAX
function plus value snapshots, and differentiate node-by-node with
``jax.vjp`` in reverse tape order. XLA still sees whole fused backward
computations on the hybridized (jit) path — this tape only serves eager mode,
exactly like the reference's imperative path.

Semantics notes (divergences documented per SURVEY §7 "hard parts"):
- Input values are snapshotted at record time, so later in-place mutation of
  an input does not corrupt the recorded graph; mutating an array that is
  *itself* required for gradient (i.e. has been recorded) raises, as MXNet
  does.
- ``grad(..., create_graph=True)`` records the backward pass itself (each
  pullback re-linearized from the original inputs at backward time), so the
  returned gradients are differentiable — higher-order eager grads, at the
  cost of one re-linearization per node on that pass.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .base import MXNetError, _as_list

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "mark_variables",
    "backward",
    "grad",
    "get_symbol",
]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape: List["Node"] = []


_STATE = _State()


class Node:
    """One recorded op: a pure function and its I/O bindings."""

    __slots__ = ("fn", "inputs", "input_values", "outputs", "name",
                 "vjp_fn", "multi")

    def __init__(self, fn, inputs, input_values, outputs, name="",
                 vjp_fn=None, multi=False):
        self.fn = fn                    # pure: (*jnp arrays) -> jnp array | tuple
        self.inputs = inputs            # List[NDArray] (for grad routing)
        self.input_values = input_values  # List[jax.Array] snapshot
        self.outputs = outputs          # List[NDArray]
        self.name = name
        #: pullback captured at forward time (residuals = stored
        #: activations); None for ops recorded without one — backward then
        #: falls back to re-linearizing the forward.
        self.vjp_fn = vjp_fn
        self.multi = multi              # did fn return a tuple/list?


# ---------------------------------------------------------------------------
# Recording scopes
# ---------------------------------------------------------------------------

class _RecordingScope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec = recording
        self._train = training
        self._prev: Optional[tuple] = None

    def __enter__(self):
        self._prev = (_STATE.recording, _STATE.training)
        if self._rec is not None:
            _STATE.recording = self._rec
        if self._train is not None:
            _STATE.training = self._train
        return self

    def __exit__(self, *exc):
        _STATE.recording, _STATE.training = self._prev


def record(train_mode: bool = True) -> _RecordingScope:
    """Scope in which executed ops are recorded for backward()."""
    return _RecordingScope(True, train_mode)


def pause(train_mode: bool = False) -> _RecordingScope:
    """Scope in which recording is suspended."""
    return _RecordingScope(False, train_mode)


def train_mode() -> _RecordingScope:
    return _RecordingScope(None, True)


def predict_mode() -> _RecordingScope:
    return _RecordingScope(None, False)


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    prev, _STATE.recording = _STATE.recording, flag
    return prev


def set_training(flag: bool) -> bool:
    prev, _STATE.training = _STATE.training, flag
    return prev


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

def _record_node(fn, inputs, input_values, outputs, name="",
                 vjp_fn=None, multi=False) -> None:
    node = Node(fn, list(inputs), list(input_values), list(outputs), name,
                vjp_fn=vjp_fn, multi=multi)
    _STATE.tape.append(node)
    for arr in node.outputs:
        arr._fresh_grad_node = node  # mark as produced-on-tape


def clear_tape() -> None:
    for node in _STATE.tape:
        for arr in node.outputs:
            arr._fresh_grad_node = None
    _STATE.tape.clear()


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Attach gradient buffers to arrays (MXAutogradMarkVariables parity)."""
    variables = _as_list(variables)
    gradients = _as_list(gradients)
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._grad = g
        var._grad_req = req


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def backward(
    heads,
    head_grads=None,
    retain_graph: bool = False,
    train_mode: bool = True,
) -> None:
    """Run reverse accumulation from ``heads`` into attached ``.grad`` buffers.

    Reference: ``Imperative::Backward`` (src/imperative/imperative.cc).
    """
    heads = _as_list(heads)
    head_grads = _as_list(head_grads) if head_grads is not None else [None] * len(heads)

    grad_map: Dict[int, Any] = {}
    for h, hg in zip(heads, head_grads):
        g = hg._data if hasattr(hg, "_data") else hg
        if g is None:
            g = jnp.ones(h.shape, h._data.dtype)
        grad_map[id(h)] = grad_map.get(id(h), 0) + g

    # The tape is in execution order == a valid topological order.
    for node in reversed(_STATE.tape):
        out_grads = [grad_map.get(id(o)) for o in node.outputs]
        if all(g is None for g in out_grads):
            continue
        if node.vjp_fn is not None:
            vjp_fn = node.vjp_fn
            outs = node.outputs
            multi = node.multi
        else:
            # node recorded without a pullback: re-linearize the forward
            primal_out, vjp_fn = jax.vjp(node.fn, *node.input_values)
            outs = primal_out if isinstance(primal_out, (tuple, list)) \
                else (primal_out,)
            multi = isinstance(primal_out, (tuple, list))
        cotangents = []
        for o, g in zip(outs, out_grads):
            o_data = o._data if hasattr(o, "_data") else o
            if g is None:
                cotangents.append(jnp.zeros(o_data.shape, o_data.dtype))
            else:
                cotangents.append(jnp.asarray(g, o_data.dtype))
        cot = tuple(cotangents) if multi else cotangents[0]
        in_grads = vjp_fn(cot)
        for arr, g in zip(node.inputs, in_grads):
            if g is None or _is_float0(g):
                continue
            prev = grad_map.get(id(arr))
            grad_map[id(arr)] = g if prev is None else prev + g

    # Deposit into attached grad buffers.
    seen = set()
    for node in _STATE.tape:
        for arr in node.inputs + node.outputs:
            if id(arr) in seen:
                continue
            seen.add(id(arr))
            _deposit(arr, grad_map)
    for h in heads:
        if id(h) not in seen:
            _deposit(h, grad_map)

    if not retain_graph:
        clear_tape()


def _deposit(arr, grad_map) -> None:
    req = getattr(arr, "_grad_req", "null")
    if req == "null" or getattr(arr, "_grad", None) is None:
        return
    g = grad_map.get(id(arr))
    if g is None:
        return
    g = jnp.asarray(g, arr._data.dtype)
    if req == "add":
        arr._grad._data = arr._grad._data + g
    else:  # 'write'
        arr._grad._data = g
    arr._grad._version += 1
    # Freshness mark read by Trainer's stale-grad check (reference:
    # Parameter._fresh_grad — only backward sets it, only updates clear it).
    arr._grad._fresh_grad = True


def _grad_create_graph(heads, variables, head_grads, train_mode):
    """Differentiable backward: every pullback application is re-recorded as
    a tape node of the form ``(xs, cotangents) -> input grads`` built from
    ``jax.vjp(node.fn, *xs)`` at BACKWARD time — so the result depends on the
    original inputs (not frozen residuals) and a further backward()/grad()
    differentiates through it. This is the reference's create_graph=True
    (``Imperative::Backward`` with the grad graph recorded); it pays a
    re-linearization per node, unlike the fast path's stored pullbacks."""
    from .ndarray import NDArray

    heads = _as_list(heads)
    head_grads = _as_list(head_grads) if head_grads is not None \
        else [None] * len(heads)

    grad_map: Dict[int, NDArray] = {}
    for h, hg in zip(heads, head_grads):
        if hg is None:
            hg = NDArray(jnp.ones(h.shape, h._data.dtype), ctx=h.context)
        elif not isinstance(hg, NDArray):   # raw numpy/jax seed, as backward()
            hg = NDArray(jnp.asarray(hg, h._data.dtype), ctx=h.context)
        acc = grad_map.get(id(h))
        grad_map[id(h)] = hg if acc is None else acc + hg

    tape_snapshot = list(_STATE.tape)   # new nodes append as we go
    with _RecordingScope(True, train_mode):
        for node in reversed(tape_snapshot):
            out_grads = [grad_map.get(id(o)) for o in node.outputs]
            if all(g is None for g in out_grads):
                continue
            cot_nds = []
            for o, g in zip(node.outputs, out_grads):
                if g is None:
                    g = NDArray(jnp.zeros(o.shape, o._data.dtype),
                                ctx=o.context)
                cot_nds.append(g)
            n_in = len(node.input_values)
            multi = node.multi
            fn = node.fn

            def pb(*vals, _fn=fn, _n=n_in, _multi=multi):
                xs, cots = vals[:_n], vals[_n:]
                _, f_vjp = jax.vjp(_fn, *xs)
                return tuple(f_vjp(tuple(cots) if _multi else cots[0]))

            vals = list(node.input_values) + [c._data for c in cot_nds]
            out, vjp_fn = jax.vjp(pb, *vals)
            outs = [NDArray(o, ctx=inp.context)   # each grad on ITS input's
                    for o, inp in zip(out, node.inputs)]
            _record_node(pb, node.inputs + cot_nds, vals, outs,
                         name=(node.name or "op") + "_backward",
                         vjp_fn=vjp_fn, multi=True)
            for arr, g_nd in zip(node.inputs, outs):
                if _is_float0(g_nd._data):
                    continue
                prev = grad_map.get(id(arr))
                grad_map[id(arr)] = g_nd if prev is None else prev + g_nd

    out = []
    for v in variables:
        g = grad_map.get(id(v))
        if g is None:
            g = NDArray(jnp.zeros(v.shape, v._data.dtype), ctx=v.context)
        out.append(g)
    return out


def grad(
    heads,
    variables,
    head_grads=None,
    retain_graph: Optional[bool] = None,
    create_graph: bool = False,
    train_mode: bool = True,
):
    """Return gradients of heads w.r.t. variables (MXAutogradBackwardEx with
    variable outputs). With ``create_graph=True`` the backward pass itself is
    recorded, so the returned grads are differentiable (reference semantics:
    retain_graph defaults to create_graph)."""
    from .ndarray import NDArray  # circular-safe local import

    if create_graph:
        # reference semantics: retain_graph DEFAULTS to create_graph; an
        # explicit False still wins (the caller is bounding memory and gives
        # up differentiating the result)
        out = _grad_create_graph(_as_list(heads), _as_list(variables),
                                 head_grads, train_mode)
        if retain_graph is False:
            clear_tape()
        return out

    variables = _as_list(variables)
    heads = _as_list(heads)
    saved = [(v, getattr(v, "_grad", None), getattr(v, "_grad_req", "null")) for v in variables]
    out = []
    try:
        for v in variables:
            v._grad = NDArray(jnp.zeros(v.shape, v._data.dtype), ctx=v.context)
            v._grad_req = "write"
        backward(heads, head_grads, retain_graph=True, train_mode=train_mode)
        out = [v._grad for v in variables]
    finally:
        for v, g, req in saved:
            v._grad, v._grad_req = g, req
        if retain_graph is False or retain_graph is None:
            clear_tape()
    return out


def get_symbol(x):
    """Reference parity stub (autograd.get_symbol): the eager tape has no NNVM
    symbol; use HybridBlock.export for graph capture."""
    raise MXNetError("get_symbol is not supported; hybridize() captures graphs")

"""Weight initializers (reference: ``python/mxnet/initializer.py``).

Same registry + ``InitDesc``-by-name dispatch: an Initializer is called with
the parameter name and the array to fill; name patterns route ``*_bias`` to
zeros etc., exactly like the reference's ``Initializer.__call__``.
"""
from __future__ import annotations

import math
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from .base import Registry
from . import random as _rng
from .ndarray.ndarray import NDArray

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "Mixed", "Load", "register", "create"]

_registry: Registry = Registry.get("initializer")
register = _registry.register


def create(init, **kwargs) -> "Initializer":
    if isinstance(init, Initializer):
        return init
    if init is None:
        return Uniform(0.07)
    return _registry.create(init, **kwargs)


class InitDesc(str):
    """Parameter name carrying init attrs (reference parity)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr: NDArray) -> None:
        name = str(name)
        if name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_one(name, arr)
        elif name.endswith("beta"):
            self._init_zero(name, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(name, arr)
        else:
            self._init_default(name, arr)

    init_weight = __call__

    def _init_zero(self, name, arr):
        arr._set_data(jnp.zeros(arr.shape, arr._data.dtype))

    def _init_one(self, name, arr):
        arr._set_data(jnp.ones(arr.shape, arr._data.dtype))

    def _init_bias(self, name, arr):
        self._init_zero(name, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        self._init_weight(name, arr)

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(name, arr)


_registry.alias("zero", "zeros")


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(name, arr)


_registry.alias("one", "ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr._set_data(jnp.full(arr.shape, self.value, arr._data.dtype))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        key = _rng.next_key(arr.context)
        arr._set_data(jax.random.uniform(key, arr.shape, arr._data.dtype,
                                         -self.scale, self.scale))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        key = _rng.next_key(arr.context)
        arr._set_data(jax.random.normal(key, arr.shape, arr._data.dtype) * self.sigma)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, name, arr):
        key = _rng.next_key(arr.context)
        nout = arr.shape[0]
        nin = int(onp.prod(arr.shape[1:]))
        a = jax.random.normal(key, (nout, nin))
        q, r = jnp.linalg.qr(a if nout <= nin else a.T)
        q = q if nout <= nin else q.T
        q = q * jnp.sign(jnp.diagonal(r))[..., None] if q.shape[0] == r.shape[0] else q
        arr._set_data((self.scale * q[:nout, :nin]).reshape(arr.shape).astype(arr._data.dtype))


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = float(onp.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        scale = math.sqrt(self.magnitude / factor)
        key = _rng.next_key(arr.context)
        if self.rnd_type == "uniform":
            arr._set_data(jax.random.uniform(key, shape, arr._data.dtype, -scale, scale))
        else:
            arr._set_data(jax.random.normal(key, shape, arr._data.dtype) * scale)


_registry.alias("xavier", "glorot")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


_registry.alias("msraprelu", "he")


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        shape = arr.shape
        weight = onp.zeros(int(onp.prod(shape)), dtype=onp.float32)
        f = onp.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(jnp.asarray(weight.reshape(shape), arr._data.dtype))


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1.0, others 0 (reference parity)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = jnp.zeros(arr.shape, arr._data.dtype)
        n = arr.shape[0] // 4
        b = b.at[n:2 * n].set(self.forget_bias)
        arr._set_data(b)

    _init_bias = _init_weight
    _init_default = _init_weight


@register
class Mixed(Initializer):
    """Per-name-pattern initializer routing (reference: initializer.Mixed):
    the FIRST regex that matches the parameter name wins."""

    def __init__(self, patterns, initializers):
        super().__init__()
        if len(patterns) != len(initializers):
            raise ValueError("Mixed needs len(patterns) == len(initializers)")
        self.map = [(re.compile(p), create(i) if not isinstance(i, Initializer)
                     else i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise ValueError(
            f"Parameter {name!r} matches no pattern in Mixed; add a catch-all "
            "'.*' entry as the reference requires")


@register
class Load(Initializer):
    """Initialize parameters by name from a saved param dict / .params file.

    Names missing from the file fall back to ``default_init`` (reference:
    initializer.Load — warm-starting from a checkpoint with a different
    head)."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        if isinstance(param, str):
            from .ndarray import load as _load_params
            param = _load_params(param)
        if not isinstance(param, dict):
            raise ValueError(
                "Load needs a name->NDArray dict (or a .params file saved "
                f"from one); got {type(param).__name__} — save with "
                "nd.save(fname, {name: array, ...})")
        self.param = {(k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                       else k): v for k, v in param.items()}
        self.default_init = create(default_init) \
            if default_init is not None else None
        self.verbose = verbose

    def __call__(self, name, arr):
        name = str(name)
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise ValueError(
                    f"Parameter {name!r} has shape {tuple(arr.shape)} but the "
                    f"loaded value has {tuple(src.shape)}")
            arr._set_data(jnp.asarray(
                src.asnumpy() if hasattr(src, "asnumpy") else src,
                arr._data.dtype))
            if self.verbose:
                print(f"Initialized {name} from the loaded file")
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise ValueError(
                f"Parameter {name!r} missing from the loaded file and no "
                "default_init was given")

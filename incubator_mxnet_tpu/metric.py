"""Evaluation metrics (reference: ``python/mxnet/metric.py`` — EvalMetric zoo).

Host-side accumulation over device results; ``update`` accepts NDArray or
numpy. ``get`` triggers the device→host sync point exactly like the
reference's asnumpy-based metrics.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as onp

from .base import Registry, _as_list
from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MCC", "MAE",
           "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
           "Perplexity", "PearsonCorrelation", "Loss", "Percentile",
           "CompositeEvalMetric", "create"]

_registry: Registry = Registry.get("metric")
register = _registry.register


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    return _registry.create(metric, *args, **kwargs)


def _np(x) -> onp.ndarray:
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


class EvalMetric:
    def __init__(self, name: str, output_names=None, label_names=None):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label: dict, pred: dict):
        """Name-keyed update (reference: EvalMetric.update_dict, used by
        Module.update_metric)."""
        if self.output_names is not None:
            preds = [pred[n] for n in self.output_names if n in pred]
        else:
            preds = list(pred.values())
        if self.label_names is not None:
            labels = [label[n] for n in self.label_names if n in label]
        else:
            labels = list(label.values())
        self.update(labels, preds)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        return list(zip(_as_list(name), _as_list(value)))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kw):
        super().__init__(name, **kw)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _np(pred)
            label = _np(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(onp.int64).reshape(-1)
            label = label.astype(onp.int64).reshape(-1)
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kw):
        super().__init__(f"{name}_{top_k}", **kw)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _np(pred)
            label = _np(label).astype(onp.int64).reshape(-1)
            topk = onp.argsort(-pred, axis=-1)[:, : self.top_k]
            self.sum_metric += float((topk == label[:, None]).any(axis=1).sum())
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kw):
        super().__init__(name, **kw)
        self.average = average

    def reset(self):
        super().reset()
        self.tp = self.fp = self.fn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _np(pred)
            label = _np(label).reshape(-1).astype(onp.int64)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.reshape(-1).astype(onp.int64)
            self.tp += float(((pred == 1) & (label == 1)).sum())
            self.fp += float(((pred == 1) & (label == 0)).sum())
            self.fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)

    def get(self):
        prec = self.tp / max(self.tp + self.fp, 1e-12)
        rec = self.tp / max(self.tp + self.fn, 1e-12)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return (self.name, f1 if self.num_inst else float("nan"))


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient for binary classification
    (reference: metric.py MCC)."""

    def __init__(self, name="mcc", **kw):
        super().__init__(name, **kw)

    def reset(self):
        super().reset()
        self.tp = self.fp = self.fn = self.tn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _np(pred)
            label = _np(label).reshape(-1).astype(onp.int64)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.reshape(-1).astype(onp.int64)
            self.tp += float(((pred == 1) & (label == 1)).sum())
            self.fp += float(((pred == 1) & (label == 0)).sum())
            self.fn += float(((pred == 0) & (label == 1)).sum())
            self.tn += float(((pred == 0) & (label == 0)).sum())
            self.num_inst += len(label)

    def get(self):
        num = self.tp * self.tn - self.fp * self.fn
        den = ((self.tp + self.fp) * (self.tp + self.fn)
               * (self.tn + self.fp) * (self.tn + self.fn)) ** 0.5
        val = num / den if den > 0 else 0.0
        return (self.name, val if self.num_inst else float("nan"))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kw):
        super().__init__(name, **kw)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            l, p = _np(label), _np(pred)
            self.sum_metric += float(onp.abs(l - p.reshape(l.shape)).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kw):
        super().__init__(name, **kw)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            l, p = _np(label), _np(pred)
            self.sum_metric += float(((l - p.reshape(l.shape)) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kw):
        super().__init__(name=name, **kw)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(onp.sqrt(self.sum_metric / self.num_inst)))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kw):
        super().__init__(name, **kw)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _np(label).astype(onp.int64).reshape(-1)
            pred = _np(pred).reshape(len(label), -1)
            prob = pred[onp.arange(len(label)), label]
            self.sum_metric += float(-onp.log(prob + self.eps).sum())
            self.num_inst += len(label)


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kw):
        super().__init__(eps=eps, name=name, **kw)


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kw):
        super().__init__(name=name, **kw)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _np(label).astype(onp.int64).reshape(-1)
            pred = _np(pred).reshape(len(label), -1)
            prob = pred[onp.arange(len(label)), label]
            if self.ignore_label is not None:
                keep = label != self.ignore_label
                prob = prob[keep]
            self.sum_metric += float(-onp.log(prob + self.eps).sum())
            self.num_inst += len(prob)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(onp.exp(self.sum_metric / self.num_inst)))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kw):
        super().__init__(name, **kw)

    def reset(self):
        super().reset()
        self._labels: List[onp.ndarray] = []
        self._preds: List[onp.ndarray] = []

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._labels.append(_np(label).reshape(-1))
            self._preds.append(_np(pred).reshape(-1))
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return (self.name, float("nan"))
        l = onp.concatenate(self._labels)
        p = onp.concatenate(self._preds)
        return (self.name, float(onp.corrcoef(l, p)[0, 1]))


@register
class Percentile(EvalMetric):
    """Streaming percentile summary over scalar samples (latency metrics).

    ``update(None, values)`` accumulates samples (NDArray / numpy / floats);
    ``get`` returns ``([name_p50, name_p95, ...], [values...])`` using
    nearest-rank percentiles over a bounded uniform reservoir.
    Deterministically seeded; mean/count are exact regardless of the cap.

    The reservoir/percentile math lives in ONE place —
    :class:`incubator_mxnet_tpu.telemetry.metrics.Histogram` — which this
    metric and the serving runtime (``mx.serve.metrics``) both delegate
    to, so training and serving latency summaries cannot drift apart.
    """

    def __init__(self, q=(50, 95, 99), name="latency", reservoir=8192, **kw):
        self.q = tuple(q)
        self.reservoir = int(reservoir)
        super().__init__(name, **kw)

    def reset(self):
        super().reset()
        from .telemetry.metrics import Histogram
        self._hist = Histogram(name=self.name, q=self.q,
                               reservoir=self.reservoir, seed=0)

    def update(self, labels, preds):
        for pred in _as_list(preds):
            vals = _np(pred).reshape(-1)
            self.sum_metric += float(vals.sum())
            self.num_inst += vals.size
            for v in vals:
                self._hist.observe(float(v))

    def percentile(self, q: float) -> float:
        return self._hist.percentile(q)

    def get(self):
        names = [f"{self.name}_p{q:g}" for q in self.q] + [f"{self.name}_mean"]
        if self.num_inst == 0:
            return (names, [float("nan")] * len(names))
        vals = [self.percentile(q) for q in self.q]
        vals.append(self.sum_metric / self.num_inst)
        return (names, vals)


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kw):
        super().__init__(name, **kw)

    def update(self, _, preds):
        for pred in _as_list(preds):
            p = _np(pred)
            self.sum_metric += float(p.sum())
            self.num_inst += p.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kw):
        super().__init__(f"custom({name})", **kw)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            out = self._feval(_np(label), _np(pred))
            if isinstance(out, tuple):
                s, n = out
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += out
                self.num_inst += 1


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kw):
        super().__init__(name, **kw)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(_as_list(n))
            values.extend(_as_list(v))
        return (names, values)


_registry.alias("accuracy", "acc")
_registry.alias("crossentropy", "ce")
_registry.alias("negativeloglikelihood", "nll_loss")

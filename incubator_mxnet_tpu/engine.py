"""Execution-engine facade.

The reference's dependency engine (``src/engine/threaded_engine_perdevice.cc``,
``ThreadedEnginePerDevice``) schedules async ops with read/write deps on
engine Vars. On TPU that entire role is subsumed by JAX's async dispatch +
XLA's runtime: every op launched on a ``jax.Array`` is already asynchronous,
ordered by data dependence, and overlapped with host code. What remains of the
engine API is therefore a thin facade:

- ``waitall()``          ≙ Engine::WaitForAll — block until all pending device
                            work is complete.
- ``set_bulk_size`` etc. — accepted, no-ops (XLA fuses/bulks internally).
- NaiveEngine mode       ≙ ``jax.disable_jit`` — serialize+eagerize everything
                            for debugging scheduling-dependent failures
                            (SURVEY §5.2: MXNET_ENGINE_TYPE=NaiveEngine).

Env: ``MXNET_ENGINE_TYPE`` ∈ {ThreadedEnginePerDevice (default), NaiveEngine}.
"""
from __future__ import annotations

import contextlib

import jax

from .base import get_env

__all__ = ["waitall", "naive_engine", "engine_type", "bulk", "set_bulk_size"]

_BULK_SIZE = 15


def engine_type() -> str:
    return get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")


def waitall() -> None:
    """Block until all async device work has completed (mx.nd.waitall)."""
    try:
        jax.effects_barrier()
    except Exception:
        pass
    # Synchronize every live device by a tiny blocking transfer.
    for d in jax.devices():
        try:
            jax.device_put(0, d).block_until_ready()
        except Exception:
            pass


@contextlib.contextmanager
def naive_engine():
    """Serialized, un-jitted execution for debugging (NaiveEngine parity)."""
    with jax.disable_jit():
        yield


def set_bulk_size(size: int) -> int:
    """Reference parity (Engine::SetBulkSize): XLA handles bulking; no-op."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, size
    return prev


@contextlib.contextmanager
def bulk(size: int):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)

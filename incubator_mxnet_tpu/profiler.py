"""Profiler facade over jax.profiler/XProf with a hierarchical span recorder.

Host-side scopes record parented wall-time spans; :func:`step_report`
turns the per-step frames into a host-gap attribution report.

Reference parity (SURVEY §5.1): ``python/mxnet/profiler.py`` —
``set_config(filename=...)``, ``set_state('run'|'stop')``, ``pause``/
``resume``, user scopes (``Scope``/``Task``/``Frame``/``Marker``), ``dump()``,
``dumps()``. The C++ profiler's chrome://tracing JSON becomes an XProf/
TensorBoard trace directory; operator-level aggregation comes from the XLA
trace instead of hand-instrumented engine events. NVTX ranges map to
``jax.profiler.TraceAnnotation``.

Beyond the facade, user scopes *record* — hierarchically. Every
``Scope``/``Task`` exit appends a named wall-time span carrying its
**parent** (the enclosing scope on this thread), nesting **depth**, and the
current telemetry **step/request correlation** id; every ``Marker.mark``
appends an instant. All span timestamps come from one monotonic clock
anchored to the wall clock once at import (``perf_counter`` + a fixed
epoch), so nested spans provably nest on the merged chrome-trace timeline
(``mx.telemetry.chrome_trace``) instead of drifting against each other.

Runtime code that already measures its own phase timings (e.g.
``parallel.ShardedTrainer.step``) publishes them with :func:`record_span`
— same ring, same clock, explicit parent. :func:`step_report` then
aggregates per-step frames into the host-gap attribution the whole-step-
capture work (ROADMAP open item 2) is judged by: each step split into
``place`` / ``dispatch`` / ``device_wait`` / ``python`` segments, plus the
derived host-gap (everything the host spends not blocked on the device).

:func:`dumps` aggregates spans into a JSON document (count/total/mean/
min/max/p50/p95/p99 per span name); :func:`dump` writes the merged
chrome-trace JSON atomically to the ``set_config(filename=...)`` path.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque as _deque
from collections import namedtuple
from typing import Dict, List, Optional

import jax

from .lockcheck import make_lock

__all__ = ["set_config", "set_state", "pause", "resume", "dump", "dumps",
           "Scope", "Task", "Frame", "Marker", "scope", "span_records",
           "reset_spans", "recent_spans", "record_span", "step_report",
           "SpanRecord"]

_STATE = {"running": False, "dir": "profile_output", "aggregate": False,
          "started_at": None, "filename": "profile.json"}

# -- host-side span recorder -------------------------------------------------
#: cap per span name so a long-lived server cannot grow without bound; the
#: aggregate counters keep counting past the cap, only raw samples drop
_MAX_SAMPLES_PER_NAME = 8192

_SPAN_LOCK = make_lock("profiler._SPAN_LOCK")
_SPANS: Dict[str, dict] = {}          # name -> {count, total_ms, samples[]}
_MARKERS: List[dict] = []
_MARKERS_DROPPED = [0]                # overflow count past the sample cap

#: one raw span on the shared timeline. ``t_start`` is epoch seconds derived
#: from perf_counter + a fixed anchor, so two spans from one thread compare
#: exactly (a child's [t_start, t_start+dur] interval is contained in its
#: parent's — the property the chrome-trace merge and step_report rely on).
#: ``trace`` is the active distributed-trace correlation at record time —
#: ``(trace_id, span_id)`` or None — so the chrome-trace merge and the
#: otel export can stitch profiler wall-time spans into the request tree
SpanRecord = namedtuple(
    "SpanRecord", ["name", "kind", "t_start", "dur_ms", "parent", "depth",
                   "step", "trace"], defaults=[None])

#: raw span ring for the chrome-trace merge (mx.telemetry.chrome_trace)
#: and step_report — aggregates cannot be placed on a timeline
_RECENT: "_deque[SpanRecord]" = _deque(maxlen=4096)

#: wall-clock anchor for the monotonic span timeline: wall ≈ _EPOCH + perf.
#: ONE reading at import keeps every span on a single comparable clock.
_EPOCH = time.time() - time.perf_counter()

_TLS = threading.local()              # per-thread open-scope stack


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _current_step() -> Optional[int]:
    # lazy import: telemetry.export imports profiler for the trace merge
    from .telemetry.events import current_step
    return current_step()


def _trace():
    # lazy import, same reason as _current_step
    from .telemetry import trace
    return trace


def _trace_ids():
    """(trace_id, span_id) of the active distributed-trace context, or
    None — stamped onto every SpanRecord."""
    ctx = _trace().current()
    return (ctx.trace_id, ctx.span_id) if ctx is not None else None


def _append(rec: SpanRecord) -> None:
    with _SPAN_LOCK:
        ent = _SPANS.get(rec.name)
        if ent is None:
            ent = _SPANS[rec.name] = {
                "kind": rec.kind, "count": 0, "total_ms": 0.0,
                "min_ms": float("inf"), "max_ms": 0.0, "samples": []}
        ent["count"] += 1
        ent["total_ms"] += rec.dur_ms
        ent["min_ms"] = min(ent["min_ms"], rec.dur_ms)
        ent["max_ms"] = max(ent["max_ms"], rec.dur_ms)
        if len(ent["samples"]) < _MAX_SAMPLES_PER_NAME:
            ent["samples"].append(rec.dur_ms)
        _RECENT.append(rec)


def record_span(name: str, dur_ms: float, kind: str = "scope",
                parent: Optional[str] = None, step: Optional[int] = None,
                t0: Optional[float] = None, depth: Optional[int] = None
                ) -> None:
    """Publish one already-measured span into the recorder — the entry
    point for runtime code that times its own phases (``ShardedTrainer``
    publishes ``step.place``/``step.dispatch``/``step.device_wait`` under
    the ``step`` frame this way). ``t0`` is the ``time.perf_counter()``
    reading at the span's start (defaults to now − duration); ``step``
    defaults to the telemetry step scope bound on this thread."""
    if t0 is None:
        t0 = time.perf_counter() - dur_ms / 1e3
    if step is None:
        step = _current_step()
    if depth is None:
        depth = 0 if parent is None else 1
    _append(SpanRecord(name, kind, _EPOCH + t0, dur_ms, parent, depth,
                       step, _trace_ids()))


def recent_spans() -> List[SpanRecord]:
    """Newest-last raw :class:`SpanRecord` rows — the timeline form the
    telemetry chrome-trace export merges with bus events and
    :func:`step_report` aggregates (bounded ring; the aggregates in
    :func:`span_records` keep the full counts)."""
    with _SPAN_LOCK:
        return list(_RECENT)


def reset_spans() -> None:
    """Drop all recorded spans and markers (``dumps(reset=True)`` calls
    this after rendering)."""
    with _SPAN_LOCK:
        _SPANS.clear()
        _MARKERS.clear()
        _RECENT.clear()
        _MARKERS_DROPPED[0] = 0


def span_records() -> Dict[str, dict]:
    """Aggregated span table ``{name: {kind, count, total_ms, mean_ms,
    min_ms, max_ms, p50_ms, p95_ms, p99_ms}}`` — the programmatic form of
    what :func:`dumps` serializes."""
    out: Dict[str, dict] = {}
    with _SPAN_LOCK:
        for name, ent in _SPANS.items():
            samples = sorted(ent["samples"])
            # a name with zero completed spans (markers-only usage, or a
            # started-but-never-stopped Task) would serialize min_ms=inf
            # as the invalid JSON token Infinity — normalize to 0.0 here
            # so every consumer sees strict-JSON-safe numbers
            min_ms = ent["min_ms"] if ent["min_ms"] != float("inf") else 0.0
            row = {"kind": ent["kind"], "count": ent["count"],
                   "total_ms": round(ent["total_ms"], 4),
                   "mean_ms": round(ent["total_ms"] / max(ent["count"], 1), 4),
                   "min_ms": round(min_ms, 4),
                   "max_ms": round(ent["max_ms"], 4)}
            from .util import nearest_rank_percentile
            for q in (50, 95, 99):
                p = nearest_rank_percentile(samples, q)
                row[f"p{q}_ms"] = round(p, 4) if p == p else 0.0
            out[name] = row
    return out


#: step_report segments that are device time, not host time — the host gap
#: is the frame total minus these (PyGraph's "dispatch tax" generalized:
#: on TPU the jitted call returns after enqueue, so dispatch/place/python
#: are all host-side; only an explicit sync blocks on the device)
_DEVICE_SEGMENTS = ("device_wait", "compute", "serve.compute")
#: one-off work that is host time but not *per-step* host tax — a
#: cold-bucket XLA compile inside a predict frame must not read as a
#: steady-state dispatch gap (it gets its own visible segment instead)
_ONEOFF_SEGMENTS = ("serve.compile", "compile")


def step_report(frame: str = "step", emit: bool = False) -> Dict:
    """Host-gap attribution over the recorded per-step frames.

    Aggregates every raw span whose ``kind`` is ``"frame"`` and name is
    ``frame`` (the trainer records one per :meth:`ShardedTrainer.step`;
    ``serve.CompiledModel.predict`` records ``"serve.predict"``), plus the
    spans parented to it. Each frame is split into named segments — the
    direct children (``place`` / ``dispatch`` / ``device_wait`` for the
    trainer; ``serve.pad`` / ``serve.compute`` / ``serve.unpad`` for
    serving) — and the remainder is attributed to ``python`` (host-side
    framework time between instrumented phases), so the whole frame is
    always accounted for. The derived ``host_gap_ms_*`` is the frame time
    minus device-side segments (:data:`_DEVICE_SEGMENTS`) and one-off
    compiles (:data:`_ONEOFF_SEGMENTS` — a cold-bucket compile is real
    host time but not steady-state dispatch tax) — the number ROADMAP
    open item 2 drives toward zero.

    Returns a strict-JSON-safe dict: ``{frame, steps, wall_ms_total,
    wall_ms_mean, segments: {name: {total_ms, mean_ms, count,
    share_pct}}, instrumented_pct, host_gap_ms_total, host_gap_ms_mean,
    memory: {live_bytes, live_arrays, sites}}`` — the ``memory``
    segment is the ``telemetry.memory`` ledger's current residency view
    beside the time attribution.
    ``instrumented_pct`` is the share of frame wall time covered by
    *measured* child spans (the ``python`` remainder excluded) — the
    honest instrumentation-coverage signal; the remainder itself is
    always attributed, so the segment table always sums to the frame.
    ``emit=True`` additionally publishes it as one ``perf.step_report``
    telemetry event. The report covers the raw-span ring window (newest
    ~4096 spans), not the whole process lifetime.
    """
    spans = recent_spans()
    frames = [r for r in spans if r.kind == "frame" and r.name == frame]
    n = len(frames)
    wall_total = sum(r.dur_ms for r in frames)
    segs: Dict[str, dict] = {}
    child_total = 0.0
    pfx = frame + "."
    for r in spans:
        if r.parent != frame:
            continue
        key = r.name[len(pfx):] if r.name.startswith(pfx) else r.name
        ent = segs.setdefault(key, {"total_ms": 0.0, "count": 0})
        ent["total_ms"] += r.dur_ms
        ent["count"] += 1
        child_total += r.dur_ms
    if n:
        # the un-instrumented remainder of each frame is host-side Python
        segs["python"] = {"total_ms": max(wall_total - child_total, 0.0),
                          "count": n}
    non_gap_ms = 0.0                  # device time + one-off compiles
    for key, ent in segs.items():
        if key in _DEVICE_SEGMENTS or key in _ONEOFF_SEGMENTS:
            non_gap_ms += ent["total_ms"]
        total = ent["total_ms"]
        ent["total_ms"] = round(total, 4)
        ent["mean_ms"] = round(total / max(n, 1), 4)
        ent["share_pct"] = (round(100.0 * total / wall_total, 2)
                            if wall_total else 0.0)
    instrumented = min(child_total, wall_total)
    host_gap = max(wall_total - non_gap_ms, 0.0)
    report = {
        "frame": frame,
        "steps": n,
        "wall_ms_total": round(wall_total, 4),
        "wall_ms_mean": round(wall_total / max(n, 1), 4),
        "segments": segs,
        "instrumented_pct": (round(100.0 * instrumented / wall_total, 2)
                             if wall_total else 0.0),
        "host_gap_ms_total": round(host_gap, 4),
        "host_gap_ms_mean": round(host_gap / max(n, 1), 4),
    }
    # current device-memory residency beside the time attribution: the
    # telemetry.memory ledger's light view (live bytes + per-site
    # attribution) — "where did the step's time AND memory go" in one
    # report
    from .telemetry import memory as _memory
    report["memory"] = _memory.segment()
    if emit:
        from .telemetry import events as _tele
        _tele.emit("perf.step_report", **{
            k: v for k, v in report.items() if k != "segments"},
            segments={k: v["total_ms"] for k, v in segs.items()})
    return report


def set_config(filename: str = "profile.json", profile_all: bool = False,
               profile_symbolic: bool = True, profile_imperative: bool = True,
               profile_memory: bool = True, profile_api: bool = True,
               aggregate_stats: bool = False, **kwargs) -> None:
    """Accepts the reference kwargs; ``filename`` is where :func:`dump`
    writes the merged chrome-trace JSON, and the XProf trace directory is
    derived from it (XProf writes a directory, not one JSON file)."""
    base = filename[:-5] if filename.endswith(".json") else filename
    _STATE["dir"] = base + "_xprof"
    _STATE["filename"] = filename
    _STATE["aggregate"] = aggregate_stats


def set_state(state: str = "stop") -> None:
    if state == "run" and not _STATE["running"]:
        os.makedirs(_STATE["dir"], exist_ok=True)
        jax.profiler.start_trace(_STATE["dir"])
        _STATE["running"] = True
        _STATE["started_at"] = time.time()
    elif state == "stop" and _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False


def pause(profile_process: str = "worker") -> None:
    if _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False


def resume(profile_process: str = "worker") -> None:
    if not _STATE["running"]:
        jax.profiler.start_trace(_STATE["dir"])
        _STATE["running"] = True


def dump(finished: bool = True, profile_process: str = "worker") -> str:
    """Flush the profile (reference: MXDumpProfile). Stops an active XProf
    trace (XProf writes on stop) and writes the merged chrome-trace JSON
    — recorded spans as nested complete events plus telemetry bus events
    as instants (``mx.telemetry.chrome_trace``) — to the
    ``set_config(filename=...)`` path. The write is atomic (tmp +
    ``os.replace``, the ``nd.save`` pattern), so a reader never sees a
    truncated trace. Returns the path written."""
    if _STATE["running"]:
        set_state("stop")
    from .telemetry.export import chrome_trace
    path = _STATE["filename"]
    doc = chrome_trace()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(doc)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # never leave a truncated trace
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def dumps(reset: bool = False) -> str:
    """JSON document of every recorded user span and marker, plus a pointer
    at the XProf trace directory (per-op device detail lives in the trace
    viewer). ``reset=True`` clears the recorder after rendering — the
    serving bench uses this to emit per-phase reports."""
    with _SPAN_LOCK:
        markers = list(_MARKERS)
        dropped = _MARKERS_DROPPED[0]
    doc = {"trace_dir": _STATE["dir"],
           "note": "device-level op table: open trace_dir with "
                   "XProf/TensorBoard profile plugin",
           "spans": span_records(),
           "markers": markers,
           "markers_dropped": dropped}
    if reset:
        reset_spans()
    # strict JSON: any residual non-finite value (a pathological dur, a
    # future aggregate) becomes null instead of the Infinity/NaN tokens
    # json would otherwise emit (allow_nan=False enforces it)
    from .telemetry.export import sanitize
    return json.dumps(sanitize(doc), indent=1, sort_keys=True,
                      allow_nan=False)


class Scope:
    """User annotation scope (reference: mx.profiler.Scope; NVTX parity).
    Entering pushes onto the per-thread scope stack; exiting records a
    named wall-time span carrying its parent scope and nesting depth, so
    nested scopes nest — not interleave — on the merged trace timeline."""

    _kind = "scope"

    def __init__(self, name: str = "<unk>", step: Optional[int] = None):
        self._name = name
        self._step = step
        self._ann = jax.profiler.TraceAnnotation(name)
        self._t0: Optional[float] = None
        self._tspan = None       # open trace.span manager, if sampled
        self._tspan_sp = None    # the Span it returned on enter

    def __enter__(self):
        self._t0 = time.perf_counter()
        _stack().append(self)
        # a sampled distributed trace adopts profiler scopes as spans:
        # serve.pad/compute/unpad land UNDER the request's tree instead
        # of beside it — the "one stitched tree" contract. Unsampled or
        # untraced: two thread-local reads, nothing recorded.
        self._tspan = None
        self._tspan_sp = None
        ctx = _trace().current()
        if ctx is not None and ctx.sampled:
            # the public scoped-span manager owns activation AND finish,
            # so the trace module's context-stack invariants live in one
            # place
            self._tspan = _trace().span(self._name, kind=self._kind)
            self._tspan_sp = self._tspan.__enter__()
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        if self._t0 is None:
            return
        trace_ids = None
        if self._tspan is not None:
            self._tspan.__exit__(*(exc if len(exc) == 3
                                   else (None, None, None)))
            trace_ids = (self._tspan_sp.ctx.trace_id,
                         self._tspan_sp.ctx.span_id)
        else:
            trace_ids = _trace_ids()
        st = _stack()
        parent, depth = None, 0
        if self in st:
            i = len(st) - 1 - st[::-1].index(self)   # last occurrence
            parent = st[i - 1]._name if i > 0 else None
            depth = i
            del st[i]
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        step = self._step if self._step is not None else _current_step()
        _append(SpanRecord(self._name, self._kind, _EPOCH + self._t0,
                           dur_ms, parent, depth, step, trace_ids))
        self._t0 = None


def scope(name: str = "<unk>") -> Scope:
    return Scope(name)


class Task(Scope):
    """Named task annotation (reference: profiler.Task)."""

    _kind = "task"

    def __init__(self, name: str = "task", domain=None):
        super().__init__(name)

    def start(self):
        self.__enter__()

    def stop(self):
        self.__exit__(None, None, None)


class Frame(Task):
    """A per-iteration frame (reference: profiler.Frame). Frames are what
    :func:`step_report` aggregates: one ``Frame("step")`` per training
    step (the trainer records it), children attributed as segments."""

    _kind = "frame"


class Marker:
    """Instant event (reference: profiler.Marker.mark). Each ``mark``
    appends a timestamped instant to the recorder (and emits a zero-length
    TraceAnnotation so it shows in the XProf timeline too)."""

    def __init__(self, name: str = "marker", domain=None):
        self._name = name

    def mark(self, scope_name: str = "process") -> None:
        with jax.profiler.TraceAnnotation(f"{self._name}:{scope_name}"):
            pass
        with _SPAN_LOCK:
            if len(_MARKERS) < _MAX_SAMPLES_PER_NAME:
                _MARKERS.append({"name": self._name, "scope": scope_name,
                                 "t": time.time()})
            else:  # bounded like span samples: a long-lived server must
                _MARKERS_DROPPED[0] += 1  # not grow without limit

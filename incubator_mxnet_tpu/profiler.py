"""Profiler facade over jax.profiler / XProf.

Reference parity (SURVEY §5.1): ``python/mxnet/profiler.py`` —
``set_config(filename=...)``, ``set_state('run'|'stop')``, ``pause``/
``resume``, user scopes (``Scope``/``Task``/``Frame``/``Marker``), ``dump()``,
``dumps()``. The C++ profiler's chrome://tracing JSON becomes an XProf/
TensorBoard trace directory; operator-level aggregation comes from the XLA
trace instead of hand-instrumented engine events. NVTX ranges map to
``jax.profiler.TraceAnnotation``.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional

import jax

__all__ = ["set_config", "set_state", "pause", "resume", "dump", "dumps",
           "Scope", "Task", "Frame", "Marker", "scope"]

_STATE = {"running": False, "dir": "profile_output", "aggregate": False,
          "started_at": None}


def set_config(filename: str = "profile.json", profile_all: bool = False,
               profile_symbolic: bool = True, profile_imperative: bool = True,
               profile_memory: bool = True, profile_api: bool = True,
               aggregate_stats: bool = False, **kwargs) -> None:
    """Accepts the reference kwargs; the trace directory is derived from
    ``filename`` (XProf writes a directory, not one JSON file)."""
    base = filename[:-5] if filename.endswith(".json") else filename
    _STATE["dir"] = base + "_xprof"
    _STATE["aggregate"] = aggregate_stats


def set_state(state: str = "stop") -> None:
    if state == "run" and not _STATE["running"]:
        os.makedirs(_STATE["dir"], exist_ok=True)
        jax.profiler.start_trace(_STATE["dir"])
        _STATE["running"] = True
        _STATE["started_at"] = time.time()
    elif state == "stop" and _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False


def pause(profile_process: str = "worker") -> None:
    if _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False


def resume(profile_process: str = "worker") -> None:
    if not _STATE["running"]:
        jax.profiler.start_trace(_STATE["dir"])
        _STATE["running"] = True


def dump(finished: bool = True, profile_process: str = "worker") -> None:
    """Flush the trace (reference: MXDumpProfile). Stops an active trace —
    XProf writes on stop."""
    if _STATE["running"]:
        set_state("stop")


def dumps(reset: bool = False) -> str:
    """Aggregate-stats table parity: points at the XProf directory (the
    per-op table lives in the trace viewer)."""
    return (f"Profile data in {_STATE['dir']!r} "
            f"(open with XProf/TensorBoard profile plugin)")


class Scope:
    """User annotation scope (reference: mx.profiler.Scope; NVTX parity)."""

    def __init__(self, name: str = "<unk>"):
        self._ann = jax.profiler.TraceAnnotation(name)

    def __enter__(self):
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)


def scope(name: str = "<unk>") -> Scope:
    return Scope(name)


class Task(Scope):
    """Named task annotation (reference: profiler.Task)."""

    def __init__(self, name: str = "task", domain=None):
        super().__init__(name)

    def start(self):
        self.__enter__()

    def stop(self):
        self.__exit__(None, None, None)


class Frame(Task):
    pass


class Marker:
    """Instant event (reference: profiler.Marker.mark)."""

    def __init__(self, name: str = "marker", domain=None):
        self._name = name

    def mark(self, scope_name: str = "process") -> None:
        with jax.profiler.TraceAnnotation(f"{self._name}:{scope_name}"):
            pass

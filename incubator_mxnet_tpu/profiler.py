"""Profiler facade over jax.profiler / XProf, plus a host-side span recorder.

Reference parity (SURVEY §5.1): ``python/mxnet/profiler.py`` —
``set_config(filename=...)``, ``set_state('run'|'stop')``, ``pause``/
``resume``, user scopes (``Scope``/``Task``/``Frame``/``Marker``), ``dump()``,
``dumps()``. The C++ profiler's chrome://tracing JSON becomes an XProf/
TensorBoard trace directory; operator-level aggregation comes from the XLA
trace instead of hand-instrumented engine events. NVTX ranges map to
``jax.profiler.TraceAnnotation``.

Beyond the facade, user scopes now *record*: every ``Scope``/``Task`` exit
appends a named wall-time span and every ``Marker.mark`` an instant event to
a process-wide, thread-safe recorder, and :func:`dumps` aggregates them into
a JSON document (count/total/mean/min/max/p50/p95/p99 per span name). This
is the per-stage timing surface the serving runtime (``mx.serve``) reports
through — device-level detail still lives in the XProf trace directory.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

import jax

__all__ = ["set_config", "set_state", "pause", "resume", "dump", "dumps",
           "Scope", "Task", "Frame", "Marker", "scope", "span_records",
           "reset_spans", "recent_spans"]

_STATE = {"running": False, "dir": "profile_output", "aggregate": False,
          "started_at": None}

# -- host-side span recorder -------------------------------------------------
#: cap per span name so a long-lived server cannot grow without bound; the
#: aggregate counters keep counting past the cap, only raw samples drop
_MAX_SAMPLES_PER_NAME = 8192

_SPAN_LOCK = threading.Lock()
_SPANS: Dict[str, dict] = {}          # name -> {count, total_ms, samples[]}
_MARKERS: List[dict] = []
_MARKERS_DROPPED = [0]                # overflow count past the sample cap
#: raw (name, kind, wall_start_s, dur_ms) ring for the chrome-trace merge
#: (mx.telemetry.chrome_trace) — aggregates cannot be placed on a timeline
from collections import deque as _deque  # noqa: E402

_RECENT: "_deque" = _deque(maxlen=4096)


def _record_span(name: str, dur_ms: float, kind: str) -> None:
    t_end = time.time()
    with _SPAN_LOCK:
        ent = _SPANS.get(name)
        if ent is None:
            ent = _SPANS[name] = {"kind": kind, "count": 0, "total_ms": 0.0,
                                  "min_ms": float("inf"), "max_ms": 0.0,
                                  "samples": []}
        ent["count"] += 1
        ent["total_ms"] += dur_ms
        ent["min_ms"] = min(ent["min_ms"], dur_ms)
        ent["max_ms"] = max(ent["max_ms"], dur_ms)
        if len(ent["samples"]) < _MAX_SAMPLES_PER_NAME:
            ent["samples"].append(dur_ms)
        _RECENT.append((name, kind, t_end - dur_ms / 1e3, dur_ms))


def recent_spans() -> List[tuple]:
    """Newest-last raw spans ``(name, kind, wall_start_s, dur_ms)`` — the
    timeline form the telemetry chrome-trace export merges with bus
    events (bounded ring; aggregates in :func:`span_records` keep the
    full counts)."""
    with _SPAN_LOCK:
        return list(_RECENT)


def reset_spans() -> None:
    """Drop all recorded spans and markers (``dumps(reset=True)`` calls
    this after rendering)."""
    with _SPAN_LOCK:
        _SPANS.clear()
        _MARKERS.clear()
        _RECENT.clear()
        _MARKERS_DROPPED[0] = 0


def span_records() -> Dict[str, dict]:
    """Aggregated span table ``{name: {kind, count, total_ms, mean_ms,
    min_ms, max_ms, p50_ms, p95_ms, p99_ms}}`` — the programmatic form of
    what :func:`dumps` serializes."""
    out: Dict[str, dict] = {}
    with _SPAN_LOCK:
        for name, ent in _SPANS.items():
            samples = sorted(ent["samples"])
            # a name with zero completed spans (markers-only usage, or a
            # started-but-never-stopped Task) would serialize min_ms=inf
            # as the invalid JSON token Infinity — normalize to 0.0 here
            # so every consumer sees strict-JSON-safe numbers
            min_ms = ent["min_ms"] if ent["min_ms"] != float("inf") else 0.0
            row = {"kind": ent["kind"], "count": ent["count"],
                   "total_ms": round(ent["total_ms"], 4),
                   "mean_ms": round(ent["total_ms"] / max(ent["count"], 1), 4),
                   "min_ms": round(min_ms, 4),
                   "max_ms": round(ent["max_ms"], 4)}
            from .util import nearest_rank_percentile
            for q in (50, 95, 99):
                p = nearest_rank_percentile(samples, q)
                row[f"p{q}_ms"] = round(p, 4) if p == p else 0.0
            out[name] = row
    return out


def set_config(filename: str = "profile.json", profile_all: bool = False,
               profile_symbolic: bool = True, profile_imperative: bool = True,
               profile_memory: bool = True, profile_api: bool = True,
               aggregate_stats: bool = False, **kwargs) -> None:
    """Accepts the reference kwargs; the trace directory is derived from
    ``filename`` (XProf writes a directory, not one JSON file)."""
    base = filename[:-5] if filename.endswith(".json") else filename
    _STATE["dir"] = base + "_xprof"
    _STATE["aggregate"] = aggregate_stats


def set_state(state: str = "stop") -> None:
    if state == "run" and not _STATE["running"]:
        os.makedirs(_STATE["dir"], exist_ok=True)
        jax.profiler.start_trace(_STATE["dir"])
        _STATE["running"] = True
        _STATE["started_at"] = time.time()
    elif state == "stop" and _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False


def pause(profile_process: str = "worker") -> None:
    if _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False


def resume(profile_process: str = "worker") -> None:
    if not _STATE["running"]:
        jax.profiler.start_trace(_STATE["dir"])
        _STATE["running"] = True


def dump(finished: bool = True, profile_process: str = "worker") -> None:
    """Flush the trace (reference: MXDumpProfile). Stops an active trace —
    XProf writes on stop."""
    if _STATE["running"]:
        set_state("stop")


def dumps(reset: bool = False) -> str:
    """JSON document of every recorded user span and marker, plus a pointer
    at the XProf trace directory (per-op device detail lives in the trace
    viewer). ``reset=True`` clears the recorder after rendering — the
    serving bench uses this to emit per-phase reports."""
    with _SPAN_LOCK:
        markers = list(_MARKERS)
        dropped = _MARKERS_DROPPED[0]
    doc = {"trace_dir": _STATE["dir"],
           "note": "device-level op table: open trace_dir with "
                   "XProf/TensorBoard profile plugin",
           "spans": span_records(),
           "markers": markers,
           "markers_dropped": dropped}
    if reset:
        reset_spans()
    # strict JSON: any residual non-finite value (a pathological dur, a
    # future aggregate) becomes null instead of the Infinity/NaN tokens
    # json would otherwise emit (allow_nan=False enforces it)
    from .telemetry.export import sanitize
    return json.dumps(sanitize(doc), indent=1, sort_keys=True,
                      allow_nan=False)


class Scope:
    """User annotation scope (reference: mx.profiler.Scope; NVTX parity).
    Exits record a named wall-time span retrievable via :func:`dumps`."""

    _kind = "scope"

    def __init__(self, name: str = "<unk>"):
        self._name = name
        self._ann = jax.profiler.TraceAnnotation(name)
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        if self._t0 is not None:
            _record_span(self._name,
                         (time.perf_counter() - self._t0) * 1e3, self._kind)
            self._t0 = None


def scope(name: str = "<unk>") -> Scope:
    return Scope(name)


class Task(Scope):
    """Named task annotation (reference: profiler.Task)."""

    _kind = "task"

    def __init__(self, name: str = "task", domain=None):
        super().__init__(name)

    def start(self):
        self.__enter__()

    def stop(self):
        self.__exit__(None, None, None)


class Frame(Task):
    _kind = "frame"


class Marker:
    """Instant event (reference: profiler.Marker.mark). Each ``mark``
    appends a timestamped instant to the recorder (and emits a zero-length
    TraceAnnotation so it shows in the XProf timeline too)."""

    def __init__(self, name: str = "marker", domain=None):
        self._name = name

    def mark(self, scope_name: str = "process") -> None:
        with jax.profiler.TraceAnnotation(f"{self._name}:{scope_name}"):
            pass
        with _SPAN_LOCK:
            if len(_MARKERS) < _MAX_SAMPLES_PER_NAME:
                _MARKERS.append({"name": self._name, "scope": scope_name,
                                 "t": time.time()})
            else:  # bounded like span samples: a long-lived server must
                _MARKERS_DROPPED[0] += 1  # not grow without limit

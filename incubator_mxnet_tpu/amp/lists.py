"""AMP op lists (reference: python/mxnet/amp/lists/symbol_fp16.py).

FP16_FP32_FUNCS: matmul/conv-class ops cast down to the target dtype (MXU).
FP32_FUNCS: numerically sensitive ops pinned to float32.
WIDEST_TYPE_CASTS: ops that follow their widest input (handled implicitly by
jnp promotion; listed for parity/documentation).
"""

FP16_FP32_FUNCS = [
    "FullyConnected",
    "Convolution",
    "Deconvolution",
    "dot",
    "batch_dot",
    "linalg_gemm2",
    "dot_product_attention",
    "interleaved_matmul_selfatt_qk",
    "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk",
    "interleaved_matmul_encdec_valatt",
    "RNN",
]

FP32_FUNCS = [
    "BatchNorm",
    "LayerNorm",
    "GroupNorm",
    "InstanceNorm",
    "L2Normalization",
    "softmax",
    "log_softmax",
    "masked_softmax",
    "SoftmaxOutput",
    "softmax_cross_entropy",
    "exp", "log", "log2", "log10", "log1p",
    "sum", "mean", "prod", "norm", "logsumexp",
    "Dropout",
]

WIDEST_TYPE_CASTS = [
    "add_n", "concat", "stack", "where", "broadcast_add", "broadcast_mul",
]

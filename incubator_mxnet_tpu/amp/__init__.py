"""AMP — automatic mixed precision.

Reference parity (SURVEY §2.7): ``python/mxnet/amp/amp.py`` — op allow/deny
lists, ``amp.init()`` patching the op namespace with casts, dynamic
``LossScaler``, ``multi_precision`` optimizers, ``convert_hybrid_block``.

TPU-native design: the target dtype is **bfloat16** (MXU-native; same
exponent range as fp32, so the fp16 loss-scaling machinery is unnecessary —
it is kept for API parity and used only when someone forces float16).
``init()`` wraps the matmul/conv-class ops in ``mx.nd`` so their float32
array inputs are cast down (the reference's FP16_FUNCS list); reductions,
norms, softmax and losses stay fp32 (FP32_FUNCS). Under ``hybridize()`` the
casts trace into the jitted graph, giving XLA the bf16 MXU lowering.
"""
from __future__ import annotations

import warnings
from typing import List, Optional

import jax.numpy as jnp

from ..base import MXNetError
from .. import ndarray as nd_mod
from ..ndarray import NDArray
from . import lists

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "LossScaler", "lists"]

_STATE = {"initialized": False, "dtype": None, "patched": {}}


def _cast_wrapper(fn, target_dtype):
    def wrapped(*args, **kwargs):
        cast_args = []
        for a in args:
            if isinstance(a, NDArray) and a.dtype == jnp.float32:
                cast_args.append(a.astype(target_dtype))
            else:
                cast_args.append(a)
        return fn(*cast_args, **kwargs)
    wrapped.__name__ = getattr(fn, "__name__", "amp_op")
    wrapped._amp_wrapped = fn
    return wrapped


def init(target_dtype: str = "bfloat16", target_precision_ops: Optional[List[str]] = None,
         conditional_fp32_ops=None, fp32_ops: Optional[List[str]] = None) -> None:
    """Patch the imperative op namespace for mixed precision
    (reference: amp.init — graph-pass based there, namespace-patch here)."""
    if _STATE["initialized"]:
        return
    dtype = jnp.dtype(target_dtype)
    if dtype not in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        raise MXNetError("AMP target must be bfloat16 or float16")
    if dtype == jnp.dtype(jnp.float16):
        warnings.warn("float16 on TPU is emulated; bfloat16 is the native "
                      "MXU dtype and needs no loss scaling.")
    ops = list(target_precision_ops or lists.FP16_FP32_FUNCS)
    skip = set(fp32_ops or lists.FP32_FUNCS)
    for name in ops:
        if name in skip:
            continue
        fn = getattr(nd_mod, name, None)
        if fn is None:
            continue
        _STATE["patched"][name] = fn
        setattr(nd_mod, name, _cast_wrapper(fn, dtype))
    _STATE["initialized"] = True
    _STATE["dtype"] = dtype


def reset() -> None:
    """Undo init() (test helper; the reference has no unpatch)."""
    for name, fn in _STATE["patched"].items():
        setattr(nd_mod, name, fn)
    _STATE.update(initialized=False, dtype=None, patched={})


class LossScaler:
    """Dynamic loss scaling (reference: amp/loss_scaler.py). Needed for
    fp16 only; bf16 keeps scale=1 forever.

    The overflow check is the fault runtime's fused
    :func:`~incubator_mxnet_tpu.fault.guards.all_finite` (one jitted
    reduction over every gradient, one scalar transfer — the per-array
    host-sync loop the reference ran is gone), and an optional
    :class:`~incubator_mxnet_tpu.fault.StepGuard` escalates: scaler
    overflow steps are reported to ``guard.decide``, so ``halt`` (or the
    guard's consecutive-overflow limit) turns a diverging fp16 run into an
    immediate error instead of a silent scale collapse.
    """

    def __init__(self, init_scale: float = 2 ** 16, scale_factor: float = 2.0,
                 scale_window: int = 2000, guard=None):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0
        self._guard = guard
        #: total overflow (skipped-update) steps
        self.overflows = 0
        #: steps observed (one update_scale per training step)
        self.steps = 0

    def has_overflow(self, params) -> bool:
        from ..fault.guards import all_finite
        grads = [arr._data for p in params
                 for arr in (getattr(p, "_grad", None) or {}).values()]
        if not grads:
            return False
        return not all_finite(grads)

    def update_scale(self, overflow: bool) -> None:
        self.steps += 1
        if overflow:
            self.overflows += 1
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
            from ..telemetry import events as _tele
            _tele.emit("amp.loss_scale", severity="warning",
                       overflow=True, scale=self.loss_scale,
                       overflows=self.overflows)
            if self._guard is not None:
                # may raise NonFiniteError under policy='halt' or past
                # max_consecutive; 'skip' is the scaler's own behavior
                self._guard.decide(
                    self.steps, "loss-scale overflow",
                    detail=f"overflow #{self.overflows}, scale now "
                           f"{self.loss_scale:g}")
        else:
            self._unskipped += 1
            if self._guard is not None:
                self._guard.good_step()
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
                from ..telemetry import events as _tele
                _tele.emit("amp.loss_scale", overflow=False,
                           scale=self.loss_scale)


_SCALER = None


def init_trainer(trainer) -> None:
    """Attach dynamic loss scaling to a Trainer (fp16 path)."""
    global _SCALER
    if _STATE["dtype"] == jnp.dtype(jnp.float16):
        _SCALER = LossScaler()
    trainer._amp_loss_scaler = _SCALER


class scale_loss:
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``"""

    def __init__(self, loss, trainer):
        self._loss = loss
        self._trainer = trainer
        self._scaler = getattr(trainer, "_amp_loss_scaler", None)
        self._used_scale = None

    def __enter__(self):
        if self._scaler is None:
            return self._loss
        s = self._used_scale = self._scaler.loss_scale
        if isinstance(self._loss, (list, tuple)):
            return [l * s for l in self._loss]
        return self._loss * s

    def __exit__(self, *exc):
        if self._scaler is not None:
            overflow = self._scaler.has_overflow(self._trainer._params)
            # Unscale with the scale the loss was actually multiplied by —
            # update_scale may change loss_scale for the NEXT step.
            self._trainer._scale = 0.0 if overflow else 1.0 / self._used_scale
            self._scaler.update_scale(overflow)


def unscale(trainer) -> None:
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p._grad:
            for g in p._grad.values():
                g._set_data(g._data * inv)


def convert_hybrid_block(block, target_dtype: str = "bfloat16", ctx=None):
    """Cast a HybridBlock's parameters (reference: convert_hybrid_block
    rewrites the symbol graph; XLA recompiles on the new dtype for free)."""
    block.cast(target_dtype)
    return block


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16", **_):
    """Symbol-API model conversion: cast the param dicts."""
    cast = {k: v.astype(target_dtype) for k, v in arg_params.items()}
    return sym, cast, dict(aux_params)

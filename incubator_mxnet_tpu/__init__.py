"""incubator_mxnet_tpu — a TPU-native deep-learning framework with the
capabilities of Apache MXNet 1.x (reference: janucaria/incubator-mxnet).

Not a port: the reference's threaded dependency engine, mshadow/cuDNN/NCCL
kernels and ps-lite parameter server are replaced by XLA's async runtime over
PjRt buffers, jax.numpy/lax + Pallas kernels, ``hybridize()`` → ``jax.jit``
compilation, and mesh collectives over ICI/DCN. See SURVEY.md for the
component-by-component mapping.

Conventional import:  ``import incubator_mxnet_tpu as mx``
"""

__version__ = "0.1.0"

from .base import MXNetError  # noqa: F401
from .context import (  # noqa: F401
    Context, cpu, gpu, tpu, cpu_pinned, cpu_shared, current_context,
    num_gpus, num_tpus, gpu_memory_info, tpu_memory_info, memory_stats,
)
from . import base  # noqa: F401
from . import engine  # noqa: F401
from . import random  # noqa: F401
from . import autograd  # noqa: F401
from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from .ndarray import NDArray  # noqa: F401
from .engine import waitall  # noqa: F401
from . import operator  # noqa: F401  (registers the Custom op seam)
from .attribute import AttrScope  # noqa: F401

# Submodules that build on the core are imported lazily to keep import light
# and to allow partial builds during bootstrapping.
import importlib as _importlib

_LAZY = {
    "analysis": ".analysis",
    "autotune": ".autotune",
    "fault": ".fault",
    "gluon": ".gluon",
    "optimizer": ".optimizer",
    "initializer": ".initializer",
    "init": ".initializer",
    "metric": ".metric",
    "lr_scheduler": ".lr_scheduler",
    "kvstore": ".kvstore",
    "kv": ".kvstore",
    "io": ".io",
    "image": ".image",
    "recordio": ".recordio",
    "parallel": ".parallel",
    "profiler": ".profiler",
    "amp": ".amp",
    "contrib": ".contrib",
    "runtime": ".runtime",
    "serve": ".serve",
    "telemetry": ".telemetry",
    "test_utils": ".test_utils",
    "util": ".util",
    "callback": ".callback",
    "model": ".model",
    "module": ".module",
    "subgraph": ".subgraph",
    "symbol": ".symbol",
    "sym": ".symbol",
    "onnx": ".onnx",
    "numpy": ".numpy",
    "np": ".numpy",
    "numpy_extension": ".numpy_extension",
    "npx": ".numpy_extension",
    "models": ".models",
    "quantization": ".quantization",
    "attribute": ".attribute",
    "name": ".name",
    "monitor": ".monitor",
    "visualization": ".visualization",
    "viz": ".visualization",
}


def __getattr__(name):
    if name in _LAZY:
        mod = _importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))

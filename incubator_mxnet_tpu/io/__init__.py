"""``mx.io`` — data iterators.

Reference parity: ``include/mxnet/io.h`` (``IIterator<DataBatch>``) and
``src/io/`` (SURVEY §2.6): ``NDArrayIter``, ``CSVIter``, ``MNISTIter``,
``ImageRecordIter``, ``PrefetchingIter``, ``ResizeIter``, plus the
``DataBatch``/``DataDesc`` records the Module API consumes.

TPU-native design: iterators produce host-side batches (numpy-backed
NDArrays); the device hop happens once per step inside the compiled path
(``ShardedTrainer``/Trainer) — matching the reference's pinned-staging +
priority-copy-thread overlap, which PjRt performs internally. The decode/
augment pipeline of ``ImageRecordIter`` runs in a thread pool
(``ThreadedIter`` parity).
"""
from __future__ import annotations

import os
import struct
import threading
import time
import queue as _queue
from collections import namedtuple
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as onp

from ..base import MXNetError
from ..ndarray import NDArray, array
from .. import recordio as rec_mod

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "MNISTIter", "ImageRecordIter", "PrefetchingIter",
           "PrefetchIter", "ResizeIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    def __new__(cls, name, shape, dtype=onp.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        return 0 if layout is None else layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad: int = 0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __repr__(self):
        shapes = [getattr(d, "shape", None) for d in (self.data or [])]
        return f"DataBatch: data shapes {shapes} pad {self.pad}"


class DataIter:
    """Iterator base (reference: io.DataIter)."""

    def __init__(self, batch_size: int = 0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self) -> int:
        return 0


def _as_named_arrays(data, default_name: str):
    """Normalize array|list|dict into an ordered [(name, ndarray)] list."""
    if data is None:
        return []
    if isinstance(data, dict):
        items = list(data.items())
    elif isinstance(data, (list, tuple)):
        items = [(f"{default_name}" if i == 0 else f"{default_name}{i}", d)
                 for i, d in enumerate(data)]
    else:
        items = [(default_name, data)]
    out = []
    for name, d in items:
        if isinstance(d, NDArray):
            d = d.asnumpy()
        out.append((name, onp.asarray(d)))
    return out


class NDArrayIter(DataIter):
    """In-memory iterator (reference: io.NDArrayIter): shuffle,
    pad/discard/roll_over last-batch handling."""

    def __init__(self, data, label=None, batch_size: int = 1,
                 shuffle: bool = False, last_batch_handle: str = "pad",
                 data_name: str = "data", label_name: str = "softmax_label"):
        super().__init__(batch_size)
        self.data = _as_named_arrays(data, data_name)
        self.label = _as_named_arrays(label, label_name)
        self.num_data = self.data[0][1].shape[0] if self.data else 0
        for _, d in self.data + self.label:
            if d.shape[0] != self.num_data:
                raise MXNetError("all data/label arrays must share dim 0")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self._order = onp.arange(self.num_data)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(n, (self.batch_size,) + d.shape[1:], d.dtype)
                for n, d in self.data]

    @property
    def provide_label(self):
        return [DataDesc(n, (self.batch_size,) + d.shape[1:], d.dtype)
                for n, d in self.label]

    def reset(self):
        if self.shuffle:
            onp.random.shuffle(self._order)
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data)
        else:
            self.cursor = -self.batch_size

    def iter_next(self) -> bool:
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        for _, d in arrays:
            idx = self._order[max(0, self.cursor):self.cursor + self.batch_size]
            chunk = d[idx]
            if chunk.shape[0] < self.batch_size:  # pad by wrapping
                extra = self._order[:self.batch_size - chunk.shape[0]]
                chunk = onp.concatenate([chunk, d[extra]], axis=0)
            out.append(array(chunk))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self) -> int:
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class CSVIter(NDArrayIter):
    """CSV-backed iterator (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv: str, data_shape: Tuple[int, ...],
                 label_csv: Optional[str] = None, label_shape: Tuple[int, ...] = (1,),
                 batch_size: int = 1, **kwargs):
        data = onp.loadtxt(data_csv, delimiter=",", dtype=onp.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv:
            label = onp.loadtxt(label_csv, delimiter=",", dtype=onp.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        super().__init__(data, label, batch_size=batch_size, **kwargs)


class LibSVMIter(NDArrayIter):
    """LibSVM-format iterator (reference: src/io/iter_libsvm.cc).

    Parses ``label idx:val idx:val ...`` lines. The reference yields CSR
    batches; on TPU sparse storage is a dense facade (SURVEY §7 sparse
    scoping), so features densify to ``(n, *data_shape)`` float32 — the
    iterator surface (provide_data/label, pad/shuffle semantics) matches.
    """

    def __init__(self, data_libsvm: str, data_shape: Tuple[int, ...],
                 label_libsvm: Optional[str] = None,
                 label_shape: Tuple[int, ...] = (1,),
                 batch_size: int = 1, **kwargs):
        feat_dim = int(onp.prod(data_shape))
        labels, rows, cols, vals = [], [], [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    idx, val = tok.split(":")
                    rows.append(len(labels) - 1)
                    cols.append(int(idx))
                    vals.append(float(val))
        n = len(labels)
        data = onp.zeros((n, feat_dim), dtype=onp.float32)
        if rows:
            if max(cols) >= feat_dim or min(cols) < 0:
                raise MXNetError(
                    f"libsvm feature index out of range [0, {feat_dim}): "
                    f"[{min(cols)}, {max(cols)}]")
            data[rows, cols] = vals
        data = data.reshape((-1,) + tuple(data_shape))
        if label_libsvm:
            lab = []
            with open(label_libsvm) as f:
                for line in f:
                    if line.split():
                        lab.append([float(x) for x in line.split()])
            label = onp.asarray(lab, dtype=onp.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        else:
            label = onp.asarray(labels, dtype=onp.float32)
        super().__init__(data, label, batch_size=batch_size, **kwargs)


class MNISTIter(NDArrayIter):
    """idx-format MNIST reader (reference: src/io/iter_mnist.cc)."""

    def __init__(self, image: str, label: str, batch_size: int = 128,
                 shuffle: bool = False, flat: bool = False, **kwargs):
        imgs = _read_idx_images(image)
        labs = _read_idx_labels(label)
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, 28, 28)
        super().__init__(imgs.astype(onp.float32) / 255.0,
                         labs.astype(onp.float32),
                         batch_size=batch_size, shuffle=shuffle,
                         label_name="softmax_label", **kwargs)


def _read_idx_images(path: str) -> onp.ndarray:
    import gzip
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError(f"{path} is not an MNIST image idx file")
        return onp.frombuffer(f.read(), dtype=onp.uint8).reshape(n, rows, cols)


def _read_idx_labels(path: str) -> onp.ndarray:
    import gzip
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError(f"{path} is not an MNIST label idx file")
        return onp.frombuffer(f.read(), dtype=onp.uint8)


class ImageRecordIter(DataIter):
    """.rec image pipeline with threaded decode+augment
    (reference: src/io/iter_image_recordio_2.cc ImageRecordIOParser2).

    Supported aug params mirror the common reference set: resize,
    rand_crop, rand_mirror, data_shape, mean_r/g/b, std_r/g/b, shuffle.
    """

    def __init__(self, path_imgrec: str, data_shape: Tuple[int, int, int],
                 batch_size: int, path_imgidx: Optional[str] = None,
                 shuffle: bool = False, rand_crop: bool = False,
                 rand_mirror: bool = False, resize: int = -1,
                 mean_r: float = 0.0, mean_g: float = 0.0, mean_b: float = 0.0,
                 std_r: float = 1.0, std_g: float = 1.0, std_b: float = 1.0,
                 preprocess_threads: int = 4, round_batch: bool = True,
                 seed: int = 0, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        self._mean = onp.array([mean_r, mean_g, mean_b], onp.float32)
        self._std = onp.array([std_r, std_g, std_b], onp.float32)
        self._rng = onp.random.RandomState(seed)
        self._shuffle = shuffle
        self._threads = max(1, preprocess_threads)
        # Load the record offsets once; records decode lazily per batch.
        idx = path_imgidx or (path_imgrec[:-4] + ".idx")
        if os.path.isfile(idx):
            self._rec = rec_mod.MXIndexedRecordIO(idx, path_imgrec, "r")
            self._keys = list(self._rec.keys)
        else:
            self._rec = rec_mod.MXRecordIO(path_imgrec, "r")
            self._keys = None
            self._records = []
            while True:
                r = self._rec.read()
                if r is None:
                    break
                self._records.append(r)
        self._order = None
        self._pos = 0
        self.reset()

    def reset(self):
        n = len(self._keys) if self._keys is not None else len(self._records)
        self._order = onp.arange(n)
        if self._shuffle:
            self._rng.shuffle(self._order)
        self._pos = 0

    def _fetch(self, i: int) -> bytes:
        if self._keys is not None:
            return self._rec.read_idx(self._keys[i])
        return self._records[i]

    def _decode_one(self, raw: bytes):
        header, img = rec_mod.unpack_img(raw, iscolor=1)
        import cv2
        if self._resize > 0:
            h, w = img.shape[:2]
            scale = self._resize / min(h, w)
            img = cv2.resize(img, (int(w * scale + 0.5), int(h * scale + 0.5)))
        c, H, W = self.data_shape
        h, w = img.shape[:2]
        if self._rand_crop:
            # per-dimension: random offset where the image is larger, 0 where
            # it is smaller (the resize below fixes undersized dims)
            y = self._rng.randint(0, h - H + 1) if h > H else 0
            x = self._rng.randint(0, w - W + 1) if w > W else 0
        else:
            y, x = max(0, (h - H) // 2), max(0, (w - W) // 2)
        img = img[y:y + H, x:x + W]
        if img.shape[:2] != (H, W):
            img = cv2.resize(img, (W, H))
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB).astype(onp.float32)
        if self._rand_mirror and self._rng.rand() < 0.5:
            img = img[:, ::-1]
        img = (img - self._mean) / self._std
        label = header.label if onp.ndim(header.label) else float(header.label)
        return img.transpose(2, 0, 1), onp.float32(label)

    def iter_next(self) -> bool:
        return self._pos + self.batch_size <= len(self._order)

    def next(self) -> DataBatch:
        if not self.iter_next():
            raise StopIteration
        idxs = self._order[self._pos:self._pos + self.batch_size]
        self._pos += self.batch_size
        raws = [self._fetch(int(i)) for i in idxs]
        if self._threads > 1:
            from concurrent.futures import ThreadPoolExecutor
            if not hasattr(self, "_pool"):
                self._pool = ThreadPoolExecutor(self._threads)
            decoded = list(self._pool.map(self._decode_one, raws))
        else:
            decoded = [self._decode_one(r) for r in raws]
        data = onp.stack([d for d, _ in decoded])
        label = onp.stack([l for _, l in decoded])
        return DataBatch([array(data)], [array(label)], pad=0)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]


class PrefetchingIter(DataIter):
    """Background-thread prefetch wrapper (reference: iter_prefetcher.h —
    the ThreadedIter overlap that hides decode latency behind compute)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth: int = 2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter here wraps a single iterator")
        self._it = iters[0]
        super().__init__(self._it.batch_size)
        self._depth = prefetch_depth
        self._queue: _queue.Queue = _queue.Queue(maxsize=prefetch_depth)
        self._worker = None
        self._gen = 0
        self._start()

    def _start(self):
        gen = self._gen
        q = self._queue

        def run():
            # A stale generation (reset() bumped self._gen) must stop touching
            # the shared underlying iterator and exit without the sentinel.
            done = False
            try:
                while gen == self._gen:
                    try:
                        b = self._it.next()
                    except StopIteration:
                        done = True
                        break
                    while gen == self._gen:
                        try:
                            q.put(b, timeout=0.05)
                            break
                        except _queue.Full:
                            continue
            finally:
                if done and gen == self._gen:
                    q.put(None)

        self._worker = threading.Thread(target=run, name="mx-io-prefetch",
                                        daemon=True)
        self._worker.start()

    def reset(self):
        self._gen += 1  # signal the old worker to exit
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        if self._worker is not None:
            self._worker.join(timeout=5)
        self._it.reset()
        self._queue = _queue.Queue(maxsize=self._depth)
        self._start()

    def next(self) -> DataBatch:
        b = self._queue.get()
        if b is None:
            raise StopIteration
        return b

    def iter_next(self) -> bool:
        raise MXNetError("PrefetchingIter supports iteration via next() only")

    @property
    def provide_data(self):
        return self._it.provide_data

    @property
    def provide_label(self):
        return self._it.provide_label


class PrefetchIter(DataIter):
    """Async double-buffered DEVICE prefetch over any :class:`DataIter`.

    Where :class:`PrefetchingIter` overlaps host-side decode with
    compute, this wrapper additionally runs a *placement* function on the
    worker thread — typically ``ShardedTrainer.place`` — so the
    host→device hop of batch N+1 (and N+2, with the default ``depth=2``
    double buffer) proceeds while the compiled step is executing batch N.
    Input placement never serializes with the step: the training loop's
    per-step host work drops to one queue pop::

        it = mx.io.PrefetchIter(
            base_iter, place=lambda b: trainer.place(*b.data, *b.label))
        for placed in it:
            trainer.step(*placed)

    ``place`` takes the wrapped iterator's :class:`DataBatch` and may
    return anything (default: the batch unchanged — pure async
    prefetch). Batches arrive strictly in the wrapped iterator's order.
    Every consumer-side queue pop is timed: the blocked portion is
    recorded as an ``io.wait`` profiler span, the ``mxtpu_io_wait_ms``
    histogram + ``mxtpu_io_queue_depth`` gauge, and (when the goodput
    ledger is on) the ``input_wait`` attribution bucket — so "the step
    is starving on input" is a measured, gated fact, testable end to
    end via the seeded ``slow_input`` chaos knob (``fault.inject``
    delays the producer).
    A ``place``/iterator exception is captured on the worker and
    re-raised from :meth:`next` — never swallowed. The worker is one
    named daemon thread (``mx-io-device-prefetch``, lockcheck/MX804
    conventions); :meth:`close` (or ``with`` exit) shuts it down and
    joins it, :meth:`reset` restarts the stream from the wrapped
    iterator's top.

    **Host sharding** (the elastic data plane): :meth:`shard` gives this
    process a disjoint round-robin view of the wrapped stream — global
    batch ``g`` belongs to host ``g % process_count == process_index``;
    the worker *consumes* every batch from the wrapped iterator but
    delivers (and places) only this host's share, so N hosts driving N
    identical iterators partition the epoch with zero overlap and zero
    cross-host coordination. The shard boundary is checkpointable:
    :meth:`shard_state` returns the pod-wide consumed-through cursor
    (every host computes the same value at the same step — SPMD
    lockstep), trainers bank it in checkpoint meta, and
    :meth:`restore_shard` fast-forwards past it under a **new**
    ``(index, count)`` membership — so a 2-host run restored on 1 host
    resumes the stream with no sample replayed and no sample dropped.
    """

    _DONE = object()

    def __init__(self, data_iter, place=None, depth: int = 2):
        if depth < 1:
            raise MXNetError("PrefetchIter depth must be >= 1")
        super().__init__(getattr(data_iter, "batch_size", 0))
        self._it = data_iter
        self._place = place
        self._depth = depth
        self._queue: _queue.Queue = _queue.Queue(maxsize=depth)
        self._worker: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self._done = False           # stream ended (worker queues _DONE once)
        self._gen = 0
        self._closed = False
        # host-shard view (identity by default). All five fields are
        # written only while the worker is stopped (shard/restore/reset),
        # so the worker thread reads them race-free.
        self._shard_index = 0
        self._shard_count = 1
        self._shard_base = 0      # global index round-robin starts from
        self._skip_to = 0         # globals below this are already consumed
        self._boundary = 0        # pod-wide consumed-through cursor
        # input-wait instrumentation: every consumer-side queue pop is
        # timed — the blocked portion IS input starvation, the number
        # the goodput ledger's input_wait bucket and the "is the step
        # waiting on data" triage question both need. Registry handles
        # resolve ONCE (the per-call registry lookup takes a lock; this
        # sits on the per-batch hot path).
        from ..telemetry import metrics as _tmetrics
        self._m_wait = _tmetrics.histogram(
            "mxtpu_io_wait_ms",
            "Consumer wait on the PrefetchIter queue per batch (ms)")
        self._m_depth = _tmetrics.gauge(
            "mxtpu_io_queue_depth",
            "Prefetched batches ready at the last queue pop")
        self._start()

    def _start(self):
        gen = self._gen
        q = self._queue

        from ..fault import inject as _inject

        # the shard view, snapshotted at worker start (only mutated while
        # the worker is stopped); g counts batches pulled from the wrapped
        # iterator since its last reset — the GLOBAL batch index
        sh_index, sh_count = self._shard_index, self._shard_count
        sh_base, sh_skip = self._shard_base, self._skip_to

        def run():
            # A stale generation (reset()/close() bumped self._gen) stops
            # touching the shared underlying iterator and exits without
            # queueing its sentinel.
            tail = None
            g = 0
            try:
                while gen == self._gen:
                    try:
                        # chaos: the seeded slow_input knob starves the
                        # consumer HERE, on the producer — the realistic
                        # slow-storage/slow-decode signature the goodput
                        # ledger must attribute as input_wait
                        _inject.maybe_delay("slow_input")
                        b = self._it.next()
                    except StopIteration:
                        tail = PrefetchIter._DONE
                        break
                    except BaseException as e:  # surfaced to the consumer
                        self._exc = e
                        tail = PrefetchIter._DONE
                        break
                    g_cur, g = g, g + 1
                    if g_cur < sh_skip:
                        continue   # restored boundary: already trained on
                    if sh_count > 1 and \
                            (g_cur - sh_base) % sh_count != sh_index:
                        continue   # another host's batch: consume, not ours
                    if self._place is not None:
                        try:
                            # the device hop happens HERE, on the worker —
                            # overlapped with the step consuming the
                            # previous batch
                            b = self._place(b)
                        except BaseException as e:
                            self._exc = e
                            tail = PrefetchIter._DONE
                            break
                    while gen == self._gen:
                        try:
                            q.put((g_cur, b), timeout=0.05)
                            break
                        except _queue.Full:
                            continue
            finally:
                while tail is not None and gen == self._gen:
                    try:
                        q.put(tail, timeout=0.05)
                        break
                    except _queue.Full:
                        continue

        self._worker = threading.Thread(target=run,
                                        name="mx-io-device-prefetch",
                                        daemon=True)
        self._worker.start()

    def _stop_worker(self) -> bool:
        """Signal + join the worker; True when it actually exited."""
        self._gen += 1  # signal the worker to exit
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        if self._worker is not None:
            self._worker.join(timeout=5)
            if self._worker.is_alive():
                return False
            self._worker = None
        return True

    def reset(self):
        if self._closed:
            raise MXNetError("PrefetchIter is closed")
        if not self._stop_worker():
            # the old worker is still blocked inside the wrapped
            # iterator/place call — starting a second one would drive the
            # same (non-thread-safe) iterator from two threads; fail loud
            raise MXNetError(
                "PrefetchIter worker did not stop within 5s (the wrapped "
                "iterator or place() is blocked); cannot reset safely")
        self._exc = None
        self._done = False
        # a new epoch re-shards from global 0: the shard membership
        # (index/count) survives reset, any restored fast-forward does not
        self._shard_base = 0
        self._skip_to = 0
        self._boundary = 0
        self._it.reset()
        self._queue = _queue.Queue(maxsize=self._depth)
        self._start()

    @property
    def depth(self) -> int:
        """Prefetch queue capacity currently in force."""
        return self._depth

    def set_depth(self, depth: int) -> int:
        """Resize the prefetch bound **live** — no worker restart, no
        batch dropped or replayed. The stdlib queue re-reads ``maxsize``
        under its own mutex on every put, so mutating it there (and
        waking blocked producers) makes a grow take effect within one
        producer put; a shrink drains naturally as the consumer pops —
        queued batches are never discarded. This is the flight
        director's ``input_bound`` remediation, and it is allowlisted
        precisely because nothing else moves: stream order, the worker's
        global-batch cursor, and the shard/restore accounting are all
        untouched (a restart would rewind the worker's cursor to 0 and
        drop in-flight batches). ``reset``/``shard``/``restore_shard``
        rebuild their queues at the new depth. Returns the previous
        depth."""
        depth = int(depth)
        if depth < 1:
            raise MXNetError("PrefetchIter depth must be >= 1")
        if self._closed:
            raise MXNetError("PrefetchIter is closed")
        prev, q = self._depth, self._queue
        with q.mutex:
            q.maxsize = depth
            q.not_full.notify_all()
        self._depth = depth
        return prev

    def shard(self, process_index: int, process_count: int) -> "PrefetchIter":
        """Restrict this iterator to host ``process_index``'s round-robin
        share of the stream (global batch ``g`` is ours iff
        ``g % process_count == process_index``). Restarts the stream from
        the wrapped iterator's top so every host's view starts from the
        same global 0 — call it once, right after construction, with
        ``parallel.dist.world()``. Returns ``self`` for chaining. A
        ``(0, 1)`` shard is the identity view."""
        process_index, process_count = int(process_index), int(process_count)
        if process_count < 1 or not 0 <= process_index < process_count:
            raise MXNetError(
                f"invalid shard view ({process_index}, {process_count}): "
                "need 0 <= process_index < process_count")
        if self._closed:
            raise MXNetError("PrefetchIter is closed")
        if not self._stop_worker():
            raise MXNetError(
                "PrefetchIter worker did not stop within 5s; cannot "
                "reshard safely")
        self._shard_index = process_index
        self._shard_count = process_count
        self._shard_base = 0
        self._skip_to = 0
        self._boundary = 0
        self._exc = None
        self._done = False
        self._it.reset()
        self._queue = _queue.Queue(maxsize=self._depth)
        self._start()
        return self

    def shard_state(self) -> Dict[str, int]:
        """The checkpointable shard boundary. ``next_global`` is the
        pod-wide consumed-through cursor: with every host in SPMD
        lockstep (same step count at the save barrier), batches
        ``[0, next_global)`` have each been consumed by exactly one
        host, so a restore under ANY new membership starts there with
        no overlap and no gap. Trainers bank this dict in checkpoint
        meta (``meta["data_state"]``)."""
        return {"next_global": self._boundary,
                "index": self._shard_index,
                "count": self._shard_count,
                "batch_size": int(self.batch_size)}

    def restore_shard(self, state: Dict[str, int],
                      index: Optional[int] = None,
                      count: Optional[int] = None) -> "PrefetchIter":
        """Resume the stream from a banked :meth:`shard_state` under a
        (possibly different) membership — THE elastic-recovery data
        path: the wrapped iterator restarts from its top, the worker
        fast-forwards past the ``next_global`` already-consumed batches,
        and round-robin assignment restarts from that boundary with the
        NEW ``(index, count)`` (defaults: the saved membership). No
        consumed sample is replayed, no unconsumed sample is skipped."""
        state = dict(state or {})
        idx = int(state.get("index", 0)) if index is None else int(index)
        n = int(state.get("count", 1)) if count is None else int(count)
        if n < 1 or not 0 <= idx < n:
            raise MXNetError(
                f"invalid shard view ({idx}, {n}): need 0 <= index < count")
        boundary = max(0, int(state.get("next_global", 0)))
        if self._closed:
            raise MXNetError("PrefetchIter is closed")
        if not self._stop_worker():
            raise MXNetError(
                "PrefetchIter worker did not stop within 5s; cannot "
                "restore shard safely")
        self._shard_index = idx
        self._shard_count = n
        self._shard_base = boundary
        self._skip_to = boundary
        self._boundary = boundary
        self._exc = None
        self._done = False
        self._it.reset()
        self._queue = _queue.Queue(maxsize=self._depth)
        self._start()
        return self

    def close(self):
        """Stop and join the worker thread (idempotent). The wrapped
        iterator is left as-is — mid-stream batches it already produced
        into the dropped queue are consumed, matching any prefetcher's
        read-ahead semantics."""
        if self._closed:
            return
        self._closed = True
        self._stop_worker()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def next(self):
        if self._closed:
            raise MXNetError("PrefetchIter is closed")
        if self._done:
            # the worker queued its sentinel exactly once and exited; any
            # further next() must keep raising (matching plain iterators)
            # instead of blocking forever on an empty, producer-less queue
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        t0 = time.perf_counter()
        b = self._queue.get()
        wait_ms = (time.perf_counter() - t0) * 1e3
        # the blocked pop is the step's input starvation: an io.wait span
        # on the profiler timeline, the mxtpu_io_* metrics, and the
        # goodput ledger's input_wait bucket — all from the ONE timing
        from .. import profiler as _prof
        _prof.record_span("io.wait", wait_ms)
        self._m_wait.observe(wait_ms)
        self._m_depth.set(self._queue.qsize())
        from ..telemetry import goodput as _goodput
        if _goodput.enabled():
            _goodput.note("input_wait", wait_ms)
        if b is PrefetchIter._DONE:
            self._done = True
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        g, batch = b
        # consuming our batch of round r means the pod (SPMD lockstep)
        # consumed every global through the end of that round — THE
        # value shard_state() banks
        r = (g - self._shard_base) // self._shard_count
        self._boundary = self._shard_base + (r + 1) * self._shard_count
        return batch

    def iter_next(self) -> bool:
        raise MXNetError("PrefetchIter supports iteration via next() only")

    @property
    def provide_data(self):
        return self._it.provide_data

    @property
    def provide_label(self):
        return self._it.provide_label


class ResizeIter(DataIter):
    """Truncate/extend an iterator to exactly ``size`` batches
    (reference: io.ResizeIter)."""

    def __init__(self, data_iter, size: int, reset_internal: bool = True):
        super().__init__(data_iter.batch_size)
        self._it = data_iter
        self._size = size
        self._reset_internal = reset_internal
        self._cur = 0

    def reset(self):
        self._cur = 0
        if self._reset_internal:
            self._it.reset()

    def next(self) -> DataBatch:
        if self._cur >= self._size:
            raise StopIteration
        self._cur += 1
        try:
            return self._it.next()
        except StopIteration:
            self._it.reset()
            return self._it.next()

    def iter_next(self) -> bool:
        return self._cur < self._size

    @property
    def provide_data(self):
        return self._it.provide_data

    @property
    def provide_label(self):
        return self._it.provide_label

"""Pluggable subgraph-property registry + graph partitioner.

Reference counterpart: ``src/operator/subgraph/subgraph_property.h``
(``SubgraphProperty``, ``SubgraphBackendRegistry``,
``MXNET_REGISTER_SUBGRAPH_BACKEND`` / ``MXNET_REGISTER_SUBGRAPH_PROPERTY``)
and the partitioning pass in ``src/operator/subgraph/build_subgraph.cc``,
surfaced as ``sym.optimize_for(backend)`` / ``HybridBlock.optimize_for``
(SURVEY §2.4 subgraph framework).

TPU-native design — NOT a port of the nnvm pass machinery:

- Partitioning is a **pure Symbol -> Symbol rewrite**: the DAG is immutable,
  so the pass rebuilds it bottom-up, splicing replacement nodes where a
  property matches. No graph mutation, no node coloring.
- A matched region is replaced either by a property-specific fused op (a
  registered jnp composition — e.g. the in-tree ``DENSE_ACT`` backend) or
  by the generic ``_subgraph_exec`` node, which embeds the captured
  subgraph in the same ``sub`` attr wire format the control-flow ops use
  (so partitioned graphs JSON-round-trip for free).
- Execution stays on the registered-op path: XLA performs the actual
  kernel fusion when the graph is jitted — the pass exists for the
  *pluggable rewrite seam* (int8 swaps, custom fused kernels, vendor
  backends), not to hand-schedule what the compiler already fuses.

Third-party registration needs no framework edits::

    backend = mx.subgraph.register_backend("MY_BACKEND")

    @mx.subgraph.register_property("MY_BACKEND")
    class FuseAddRelu(mx.subgraph.SubgraphProperty):
        op_names = ("broadcast_add", "Activation")   # linear chain

    fused = sym.optimize_for("MY_BACKEND")           # or net.optimize_for
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .base import MXNetError

__all__ = ["SubgraphProperty", "SubgraphBackend", "register_backend",
           "register_property", "get_backend", "list_backends", "partition"]


class SubgraphProperty:
    """One rewrite rule: match a region, produce its replacement.

    Reference: ``SubgraphProperty`` + ``SubgraphSelector``
    (src/operator/subgraph/subgraph_property.h). The common selector shape —
    a linear op chain along the data path — is declarative here: set
    ``op_names = ("FullyConnected", "Activation")`` and the default
    :meth:`match` finds chains whose interior outputs have exactly one
    consumer. Override :meth:`match` for non-chain patterns and
    :meth:`rewrite` for a custom replacement node (the default wraps the
    region in an opaque ``_subgraph_exec`` node, the CreateSubgraphNode
    analogue)."""

    #: linear chain to match, producer -> consumer order
    op_names: Tuple[str, ...] = ()

    # -- selection ----------------------------------------------------------
    def match(self, node, n_consumers) -> Optional[List]:
        """Return the matched region as a deepest-first node list ending at
        ``node``, or None. ``n_consumers`` maps ``id(node)`` to its fan-out
        in the full graph — interior nodes of a fused region must feed the
        region only."""
        if not self.op_names or node._op != self.op_names[-1]:
            return None
        chain = [node]
        cur = node
        for want in reversed(self.op_names[:-1]):
            if not cur._inputs:
                return None
            prev = cur._inputs[0]
            if prev._op != want or prev._base is not None:
                return None
            if n_consumers.get(id(prev), 0) != 1:
                return None  # interior output escapes the region
            chain.append(prev)
            cur = prev
        chain.reverse()
        return chain

    # -- replacement --------------------------------------------------------
    def rewrite(self, region, inputs, externs):
        """Build the replacement Symbol for ``region`` (deepest-first node
        list). ``externs`` are the region's external input nodes in
        first-use order; ``inputs`` are their already-rebuilt counterparts
        to wire into the replacement. Return None to veto the match."""
        from . import symbol as S
        phs = [S.Variable(f"sg_in{i}") for i in range(len(externs))]
        cloned = _clone_region(region, dict(zip(map(id, externs), phs)))
        sub = {"roots": [cloned[id(region[-1])]],
               "arg_names": [p.name for p in phs]}
        return S.Symbol("_subgraph_exec", list(inputs),
                        attrs={"sub": sub, "n_outs": 1,
                               "prop": type(self).__name__},
                        name=region[-1]._name + "_sg")


def _clone_region(region, extern_map):
    """Clone the region's nodes over placeholder inputs (the subgraph cut:
    reference build_subgraph.cc CutGraphInputs)."""
    from . import symbol as S
    out: Dict[int, "S.Symbol"] = {}
    for n in region:
        ins = [out.get(id(i)) or extern_map[id(i)] for i in n._inputs]
        out[id(n)] = S.Symbol(n._op, ins, attrs=n._attrs, name=n._name,
                              num_outputs=n._num_outputs)
    return out


class SubgraphBackend:
    """A named, ordered collection of properties
    (reference: SubgraphBackend in subgraph_property.h)."""

    def __init__(self, name: str):
        self.name = name
        self.properties: List[SubgraphProperty] = []

    def add_property(self, prop) -> SubgraphProperty:
        if isinstance(prop, type):
            prop = prop()
        self.properties.append(prop)
        return prop


_BACKENDS: Dict[str, SubgraphBackend] = {}


def register_backend(name: str) -> SubgraphBackend:
    """Create (or fetch) a named backend — the
    MXNET_REGISTER_SUBGRAPH_BACKEND analogue. Idempotent so separate
    modules can attach properties to one backend."""
    if name not in _BACKENDS:
        _BACKENDS[name] = SubgraphBackend(name)
    return _BACKENDS[name]


def register_property(backend_name: str, prop=None):
    """Attach a property (class or instance) to a backend; usable as a
    decorator — the MXNET_REGISTER_SUBGRAPH_PROPERTY analogue."""
    backend = register_backend(backend_name)

    def _do(p):
        backend.add_property(p)
        return p

    return _do(prop) if prop is not None else _do


def get_backend(name: str) -> SubgraphBackend:
    if name not in _BACKENDS:
        raise MXNetError(
            f"unknown subgraph backend {name!r}; registered: "
            f"{list_backends()} (register with "
            "mx.subgraph.register_backend)")
    return _BACKENDS[name]


def list_backends() -> List[str]:
    return sorted(_BACKENDS)


# ---------------------------------------------------------------------------
# the partitioning pass (reference: build_subgraph.cc BuildSubgraph)
# ---------------------------------------------------------------------------

def partition(symbol, backend):
    """Rewrite ``symbol``, replacing every region matched by one of
    ``backend``'s properties. Pure function: returns a new Symbol, the
    input graph is untouched. Properties are tried in registration order;
    matching consults the ORIGINAL graph (consumer counts included), so one
    pass cannot cascade onto its own replacements — run partition again to
    fix-point if a backend wants that."""
    from . import symbol as S
    if isinstance(backend, str):
        backend = get_backend(backend)
    elif not isinstance(backend, SubgraphBackend):
        raise MXNetError(
            f"partition expects a backend name or SubgraphBackend, got "
            f"{type(backend).__name__}; registered: {list_backends()}")

    nodes = S._topo(symbol)
    n_consumers: Dict[int, int] = {}
    for n in nodes:
        for i in n._inputs:
            n_consumers[id(i)] = n_consumers.get(id(i), 0) + 1
        if n._base is not None:
            n_consumers[id(n._base)] = n_consumers.get(id(n._base), 0) + 1

    memo: Dict[int, "S.Symbol"] = {}

    def plain(node):
        ins = [rebuild(i) for i in node._inputs]
        if all(a is b for a, b in zip(ins, node._inputs)):
            return node  # untouched subtree: keep identity (and sharing)
        return S.Symbol(node._op, ins, attrs=node._attrs, name=node._name,
                        num_outputs=node._num_outputs)

    def rebuild(node):
        if id(node) in memo:
            return memo[id(node)]
        if node._base is not None:
            new = rebuild(node._base)[node._output_index]
        elif node._op is None:
            new = node
        else:
            new = None
            for prop in backend.properties:
                region = prop.match(node, n_consumers)
                if not region:
                    continue
                in_region = set(map(id, region))
                externs, seen = [], set()
                for r in region:
                    for i in r._inputs:
                        if id(i) not in in_region and id(i) not in seen:
                            seen.add(id(i))
                            externs.append(i)
                repl = prop.rewrite(region, [rebuild(e) for e in externs],
                                    externs)
                if repl is not None:
                    new = repl
                    break
            if new is None:
                new = plain(node)
        memo[id(node)] = new
        return new

    return rebuild(symbol)


# ---------------------------------------------------------------------------
# in-tree backend: DENSE_ACT — FullyConnected + Activation as one fused op
# (the ops themselves live in ops/subgraph_ops.py so they register eagerly
# with the op library: saved partitioned graphs load in fresh processes)
# ---------------------------------------------------------------------------

class DenseActivationProperty(SubgraphProperty):
    """Fuse ``FullyConnected -> Activation`` into ``_sg_dense_act``."""

    op_names = ("FullyConnected", "Activation")

    def rewrite(self, region, inputs, externs):
        from . import symbol as S
        fc, act = region
        attrs = {k: v for k, v in fc._attrs.items()}
        attrs["act_type"] = act.attr("act_type") or "relu"
        return S.Symbol("_sg_dense_act", list(inputs), attrs=attrs,
                        name=fc._name + "_" + attrs["act_type"])


register_property("DENSE_ACT", DenseActivationProperty)

"""Python-defined custom operators (``mx.operator``).

Reference counterpart: ``src/operator/custom/custom.cc``
(``CustomOperator::Push``) + ``python/mxnet/operator.py`` — a C++ shim that
marshals op execution onto a dedicated worker thread and calls back into
Python, integrating with the dependency engine.

TPU-native design: the host round-trip is `jax.pure_callback`, which XLA
schedules inside the compiled program — so a ``Custom`` op works eagerly,
under ``autograd.record``, and *inside a hybridized (jit) block*, exactly the
reference contract. The gradient is a ``jax.custom_vjp`` whose backward is a
second callback into :meth:`CustomOp.backward`. As in the reference, this is
an off-perf-path escape hatch (SURVEY §7: "perf-off-path only").

Divergences (documented): ``aux`` states are not supported (use regular
params), and ``ctx`` passed to ``create_operator`` is the *current* context
facade — the callback itself always runs on host.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

import jax
import jax.numpy as jnp
import numpy as onp

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop_cls"]


class CustomOp:
    """Base class for the op implementation (reference:
    python/mxnet/operator.py CustomOp). Subclasses override ``forward`` and
    ``backward``; arrays arrive as host NDArrays on the cpu context."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError(
            "CustomOp.backward not implemented — required once the op is "
            "used under autograd.record / jax.grad")

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honouring the write/add/null request."""
        if req in ("null", 0):
            return
        if req in ("add", 3):
            dst[:] = dst + src
        else:  # write / inplace
            dst[:] = src


class CustomOpProp:
    """Shape/type contract + factory (reference CustomOpProp).

    ``need_top_grad=False`` matches loss-style ops whose backward ignores
    the incoming gradient (the callback still receives it; it is simply
    all-ones at the chain root as in the reference).
    """

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = bool(need_top_grad)

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, shapes, dtypes) -> CustomOp:
        raise NotImplementedError

    def need_top_grad(self) -> bool:
        return self.need_top_grad_


_PROPS: Dict[str, Type[CustomOpProp]] = {}


def register(op_type: str):
    """Class decorator registering a :class:`CustomOpProp` under ``op_type``
    (reference: ``mx.operator.register``). The op is then invocable as
    ``mx.nd.Custom(*data, op_type=op_type)`` or ``mx.sym.Custom(...)``."""

    def _reg(cls: Type[CustomOpProp]):
        if not issubclass(cls, CustomOpProp):
            raise TypeError(f"{cls!r} must subclass CustomOpProp")
        _PROPS[op_type] = cls
        return cls

    return _reg


def get_prop_cls(op_type: str) -> Type[CustomOpProp]:
    try:
        return _PROPS[op_type]
    except KeyError:
        raise KeyError(
            f"no CustomOp registered as '{op_type}'. Registered: "
            f"{sorted(_PROPS)}") from None


# ---------------------------------------------------------------------------
# the Custom op itself
# ---------------------------------------------------------------------------

def _host_ndarrays(np_arrays: Sequence[onp.ndarray]):
    """Wrap host numpy buffers as cpu-context NDArrays so user code can use
    the full NDArray surface inside the callback."""
    from .context import cpu
    from .ndarray import NDArray
    c = cpu()
    with jax.default_device(jax.devices("cpu")[0]):
        return [NDArray(jnp.asarray(a), ctx=c) for a in np_arrays]


_FN_CACHE: Dict[tuple, object] = {}


def _custom_fn(op_type: str, str_kwargs: Dict[str, str], is_train: bool):
    """Build (and cache) the jax-level function (with custom VJP) for one
    Custom call signature. Shapes/types are resolved at trace time via the
    prop contract. As in the reference (one CustomOperator per op node), ONE
    CustomOp instance serves both forward and backward, so state stashed on
    ``self`` in forward (masks etc.) is visible in backward."""
    cache_key = (op_type, tuple(sorted(str_kwargs.items())), is_train)
    if cache_key in _FN_CACHE:
        return _FN_CACHE[cache_key]
    prop = get_prop_cls(op_type)(**str_kwargs)
    op_box: list = []  # created lazily, shared by fwd/bwd callbacks

    def _op_for(ishapes, itypes) -> CustomOp:
        if not op_box:
            from .context import current_context
            op_box.append(prop.create_operator(current_context(), ishapes,
                                               itypes))
        return op_box[0]

    def _resolve(vals):
        in_shapes = [list(v.shape) for v in vals]
        in_types = [onp.dtype(v.dtype) for v in vals]
        shp = prop.infer_shape(in_shapes)
        ishapes, oshapes = shp[0], shp[1]
        typ = prop.infer_type(in_types)
        otypes = typ[1]
        out_sd = tuple(jax.ShapeDtypeStruct(tuple(s), onp.dtype(t))
                       for s, t in zip(oshapes, otypes))
        return ishapes, in_types, out_sd

    @jax.custom_vjp
    def fn(*vals):
        return _fwd_impl(vals)

    def _fwd_impl(vals):
        ishapes, itypes, out_sd = _resolve(vals)

        def host_fwd(*np_vals):
            op = _op_for(ishapes, itypes)
            ins = _host_ndarrays(np_vals)
            outs = _host_ndarrays([onp.zeros(sd.shape, sd.dtype)
                                   for sd in out_sd])
            op.forward(is_train=is_train, req=["write"] * len(outs),
                       in_data=ins, out_data=outs, aux=[])
            return tuple(onp.asarray(o.asnumpy(), sd.dtype)
                         for o, sd in zip(outs, out_sd))

        return jax.pure_callback(host_fwd, out_sd, *vals, vmap_method="sequential")

    def fn_fwd(*vals):
        outs = _fwd_impl(vals)
        return outs, (vals, outs)

    def fn_bwd(res, gouts):
        vals, outs = res
        ishapes, itypes, _ = _resolve(vals)
        gin_sd = tuple(jax.ShapeDtypeStruct(tuple(v.shape), onp.dtype(v.dtype))
                       for v in vals)

        def host_bwd(*np_all):
            ni, no = len(vals), len(outs)
            ins = _host_ndarrays(np_all[:ni])
            os_ = _host_ndarrays(np_all[ni:ni + no])
            gs = _host_ndarrays(np_all[ni + no:])
            gin = _host_ndarrays([onp.zeros(sd.shape, sd.dtype)
                                  for sd in gin_sd])
            op = _op_for(ishapes, itypes)
            op.backward(req=["write"] * ni, out_grad=gs, in_data=ins,
                        out_data=os_, in_grad=gin, aux=[])
            return tuple(onp.asarray(g.asnumpy(), sd.dtype)
                         for g, sd in zip(gin, gin_sd))

        return jax.pure_callback(host_bwd, gin_sd, *vals, *outs, *gouts,
                                 vmap_method="sequential")

    fn.defvjp(fn_fwd, fn_bwd)
    _FN_CACHE[cache_key] = fn
    return fn


def _register_custom_op():
    from .ops.registry import register_op

    @register_op("Custom")
    def custom(*in_vals, op_type=None, **kwargs):
        """Invoke a registered Python CustomOp (reference:
        src/operator/custom/custom.cc; params ship as strings, as the
        reference's C ABI does)."""
        if op_type is None:
            raise TypeError("Custom requires op_type=<registered name>")
        from . import autograd
        str_kwargs = {k: str(v) for k, v in kwargs.items()}
        fn = _custom_fn(op_type, str_kwargs, autograd.is_training())
        out = fn(*in_vals)
        return out if len(out) > 1 else out[0]

    return custom


_register_custom_op()

# mx.nd may already have been reflected from the registry before this module
# ran — pick up the Custom op.
from .ndarray import refresh_ops as _refresh_ops  # noqa: E402
_refresh_ops()

"""``mx.image`` detection augmenters + ``ImageDetIter``.

Reference parity: ``python/mxnet/image/detection.py`` (``DetAugmenter``
zoo, ``CreateDetAugmenter``, ``ImageDetIter``) — SURVEY §2.6. Labels ride
with the images: every augmenter maps ``(src, label) -> (src, label)``
where ``label`` is an ``(M, 5)`` float array of
``[class, xmin, ymin, xmax, ymax]`` rows with coordinates normalized to
[0, 1] (the reference's object format after header stripping).

All augmentation is host-side numpy feeding device batches — per-image
Python never reaches the device (same design as ``image/__init__.py``).
"""
from __future__ import annotations

import json
import os
import random as pyrandom
from typing import List, Optional, Sequence, Tuple

import numpy as onp

from ..base import MXNetError
from ..ndarray import NDArray, array
from . import (Augmenter, CastAug, ColorNormalizeAug, ForceResizeAug,
               ResizeAug, imread)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter base (reference: detection.py DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self) -> str:
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src: NDArray, label: onp.ndarray):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection pipeline — the
    label passes through untouched (reference: DetBorrowAug)."""

    def __init__(self, augmenter: Augmenter):
        super().__init__(augmenter=type(augmenter).__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick ONE augmenter from ``aug_list`` (or skip entirely
    with ``skip_prob``) per sample (reference: DetRandomSelectAug)."""

    def __init__(self, aug_list: Sequence[DetAugmenter], skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes with probability p (reference:
    DetHorizontalFlipAug): x -> 1 - x, swapping xmin/xmax."""

    def __init__(self, p: float = 0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = array(onp.ascontiguousarray(src.asnumpy()[:, ::-1, :]))
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
        return src, label


def _box_coverage(label: onp.ndarray, crop: Tuple[float, float, float, float]):
    """Fraction of each object's area inside ``crop`` (normalized xywh)."""
    cx1, cy1, cw, ch = crop
    cx2, cy2 = cx1 + cw, cy1 + ch
    ix1 = onp.maximum(label[:, 1], cx1)
    iy1 = onp.maximum(label[:, 2], cy1)
    ix2 = onp.minimum(label[:, 3], cx2)
    iy2 = onp.minimum(label[:, 4], cy2)
    inter = onp.clip(ix2 - ix1, 0, None) * onp.clip(iy2 - iy1, 0, None)
    area = onp.clip(label[:, 3] - label[:, 1], 1e-12, None) * \
        onp.clip(label[:, 4] - label[:, 2], 1e-12, None)
    return inter / area


def _update_labels_crop(label, crop, min_eject_coverage):
    """Clip boxes to the crop, renormalize, eject low-coverage objects
    (reference: detection.py _update_labels)."""
    cx1, cy1, cw, ch = crop
    cov = _box_coverage(label, crop)
    keep = cov >= min_eject_coverage
    if not keep.any():
        return None
    out = label[keep].copy()
    out[:, 1] = onp.clip((out[:, 1] - cx1) / cw, 0, 1)
    out[:, 2] = onp.clip((out[:, 2] - cy1) / ch, 0, 1)
    out[:, 3] = onp.clip((out[:, 3] - cx1) / cw, 0, 1)
    out[:, 4] = onp.clip((out[:, 4] - cy1) / ch, 0, 1)
    return out


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop (reference: DetRandomCropAug): sample a
    normalized crop from ``area_range``/``aspect_ratio_range`` until some
    object keeps >= ``min_object_covered`` of its area; objects below
    ``min_eject_coverage`` are dropped, the rest clipped+renormalized.
    Falls through unchanged after ``max_attempts`` failures."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 1.0),
                 min_eject_coverage=0.3, max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _sample_crop(self, label):
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            w = min(1.0, (area * ratio) ** 0.5)
            h = min(1.0, (area / ratio) ** 0.5)
            x = pyrandom.uniform(0, 1 - w)
            y = pyrandom.uniform(0, 1 - h)
            crop = (x, y, w, h)
            if label.size == 0:
                return crop, label
            if _box_coverage(label, crop).max() >= self.min_object_covered:
                new_label = _update_labels_crop(label, crop,
                                                self.min_eject_coverage)
                if new_label is not None:
                    return crop, new_label
        return None

    def __call__(self, src, label):
        sampled = self._sample_crop(label)
        if sampled is None:
            return src, label
        crop, new_label = sampled
        img = src.asnumpy()
        H, W = img.shape[:2]
        x, y, w, h = crop
        x0, y0 = int(round(x * W)), int(round(y * H))
        x1 = min(W, x0 + max(1, int(round(w * W))))
        y1 = min(H, y0 + max(1, int(round(h * H))))
        return array(onp.ascontiguousarray(img[y0:y1, x0:x1, :])), new_label


class DetRandomPadAug(DetAugmenter):
    """Random expansion/pad (reference: DetRandomPadAug): place the image
    on a larger ``pad_val``-filled canvas sampled from ``area_range``
    (expansion factor) and ``aspect_ratio_range``, renormalizing boxes."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        img = src.asnumpy()
        H, W = img.shape[:2]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            nw = (area * ratio) ** 0.5
            nh = (area / ratio) ** 0.5
            if nw < 1.0 or nh < 1.0:
                continue
            newW, newH = int(round(nw * W)), int(round(nh * H))
            x0 = pyrandom.randint(0, newW - W)
            y0 = pyrandom.randint(0, newH - H)
            canvas = onp.empty((newH, newW, img.shape[2]), img.dtype)
            canvas[:] = onp.asarray(self.pad_val, img.dtype)
            canvas[y0:y0 + H, x0:x0 + W, :] = img
            new_label = label.copy()
            if new_label.size:
                new_label[:, 1] = (new_label[:, 1] * W + x0) / newW
                new_label[:, 3] = (new_label[:, 3] * W + x0) / newW
                new_label[:, 2] = (new_label[:, 2] * H + y0) / newH
                new_label[:, 4] = (new_label[:, 4] * H + y0) / newH
            return array(canvas), new_label
        return src, label


def CreateDetAugmenter(data_shape: Tuple[int, int, int], resize: int = 0,
                       rand_crop: float = 0, rand_pad: float = 0,
                       rand_mirror: bool = False, mean=None, std=None,
                       brightness: float = 0, contrast: float = 0,
                       saturation: float = 0, pca_noise: float = 0,
                       hue: float = 0, inter_method: int = 2,
                       min_object_covered: float = 0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0),
                       min_eject_coverage: float = 0.3,
                       max_attempts: int = 50,
                       pad_val=(127, 127, 127)) -> List[DetAugmenter]:
    """Standard detection augmenter pipeline (reference: detection.py
    CreateDetAugmenter): geometric det augs first, then borrowed
    image-only augs, then resize-to-shape, normalize, cast."""
    auglist: List[DetAugmenter] = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(
            min_object_covered=min_object_covered,
            aspect_ratio_range=aspect_ratio_range,
            area_range=(min(area_range[0], 1.0), min(area_range[1], 1.0)),
            min_eject_coverage=min_eject_coverage,
            max_attempts=max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range=aspect_ratio_range,
                              area_range=(max(1.0, area_range[0]),
                                          max(1.0, area_range[1])),
                              max_attempts=max_attempts, pad_val=pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # force to the network's input size (boxes are normalized: unaffected)
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None or std is not None:
        mean = onp.asarray(mean if mean is not None else [0, 0, 0],
                           onp.float32)
        std = onp.asarray(std if std is not None else [1, 1, 1], onp.float32)
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter:
    """Detection data iterator (reference: detection.py ImageDetIter).

    Yields ``DataBatch`` with data ``(B, C, H, W)`` and label
    ``(B, max_objects, 5)`` rows ``[class, xmin, ymin, xmax, ymax]``
    normalized to [0, 1], padded with -1 rows.

    Sources: ``path_imgrec`` (im2rec .rec whose header label is the flat
    det format ``[header_width, object_width, obj0..., obj1...]``) or
    ``imglist`` of ``(label_rows, path_or_array)`` — an ndarray in place
    of the path is accepted for in-memory datasets (tests, synthetic)."""

    def __init__(self, batch_size: int, data_shape: Tuple[int, int, int],
                 path_imgrec: Optional[str] = None,
                 imglist: Optional[Sequence] = None, path_root: str = "",
                 aug_list: Optional[List[DetAugmenter]] = None,
                 shuffle: bool = False, max_objects: Optional[int] = None,
                 **kwargs):
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **kwargs)
        self._shuffle = shuffle
        self._items: List = []
        if path_imgrec:
            from .. import recordio
            rec = recordio.MXRecordIO(path_imgrec, "r")
            while True:
                raw = rec.read()
                if raw is None:
                    break
                header, img = recordio.unpack_img(raw, iscolor=1)
                label = self._parse_label(onp.asarray(header.label,
                                                      onp.float32))
                import cv2
                img = onp.ascontiguousarray(
                    cv2.cvtColor(img, cv2.COLOR_BGR2RGB))
                self._items.append((label, img))
        elif imglist:
            for label, src in imglist:
                label = self._parse_label(onp.asarray(label, onp.float32))
                if isinstance(src, str):
                    self._items.append((label, os.path.join(path_root, src)))
                else:
                    self._items.append(
                        (label, onp.asarray(src.asnumpy() if isinstance(
                            src, NDArray) else src)))
        else:
            raise MXNetError("ImageDetIter needs path_imgrec or imglist")
        self.max_objects = max_objects or max(
            (lab.shape[0] for lab, _ in self._items), default=1)
        self.reset()

    @staticmethod
    def _parse_label(flat: onp.ndarray) -> onp.ndarray:
        """Accept (M, 5) rows or the flat lst/rec det format
        ``[header_width, object_width, header..., obj0..., ...]``."""
        flat = onp.asarray(flat, onp.float32)
        if flat.ndim == 2:
            if flat.shape[1] != 5:
                raise MXNetError(f"det label rows must be "
                                 f"[cls, x1, y1, x2, y2]; got {flat.shape}")
            return flat
        if flat.size >= 2 and float(flat[0]) >= 2 and float(flat[1]) >= 5:
            hw, ow = int(flat[0]), int(flat[1])
            body = flat[hw:]
            n = body.size // ow
            return body[:n * ow].reshape(n, ow)[:, :5]
        if flat.size % 5 == 0 and flat.size:
            return flat.reshape(-1, 5)
        raise MXNetError(f"cannot parse det label of size {flat.size}")

    def reset(self):
        self._order = list(range(len(self._items)))
        if self._shuffle:
            pyrandom.shuffle(self._order)
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        from ..io import DataBatch
        if self._pos + self.batch_size > len(self._order):
            raise StopIteration
        data, labels = [], []
        for i in self._order[self._pos:self._pos + self.batch_size]:
            label, src = self._items[i]
            img = imread(src) if isinstance(src, str) else array(src)
            for aug in self.auglist:
                img, label = aug(img, label)
            arr = img.asnumpy()
            if arr.dtype != onp.float32:
                arr = arr.astype(onp.float32)
            data.append(arr.transpose(2, 0, 1))
            padded = onp.full((self.max_objects, 5), -1.0, onp.float32)
            m = min(label.shape[0], self.max_objects)
            padded[:m] = label[:m]
            labels.append(padded)
        self._pos += self.batch_size
        return DataBatch([array(onp.stack(data))],
                         [array(onp.stack(labels))])

"""``mx.image`` — image decode + augmentation.

Reference parity: ``src/io/image_io.cc`` (imdecode over OpenCV) and
``python/mxnet/image/image.py`` (resize/crop/normalize helpers, Augmenter
zoo, ``ImageIter``) — SURVEY §2.6. Host-side numpy/cv2 work feeding device
batches; the device never sees per-image Python.
"""
from __future__ import annotations

import os
import random as pyrandom
from typing import List, Optional, Sequence, Tuple

import numpy as onp

from ..base import MXNetError
from ..ndarray import NDArray, array

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize", "random_size_crop",
           "Augmenter", "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "CenterCropAug", "HorizontalFlipAug", "ColorNormalizeAug",
           "CastAug", "CreateAugmenter", "ImageIter"]


def _cv2():
    import cv2
    return cv2


def imdecode(buf, flag: int = 1, to_rgb: bool = True) -> NDArray:
    """Decode jpeg/png bytes (reference: MXImgDecode → cv2.imdecode)."""
    cv2 = _cv2()
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = cv2.imdecode(onp.frombuffer(buf, dtype=onp.uint8), flag)
    if img is None:
        raise MXNetError("imdecode failed: invalid image data")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return array(img)


def imread(filename: str, flag: int = 1, to_rgb: bool = True) -> NDArray:
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src: NDArray, w: int, h: int, interp: int = 1) -> NDArray:
    cv2 = _cv2()
    out = cv2.resize(src.asnumpy(), (w, h),
                     interpolation=cv2.INTER_LINEAR if interp else cv2.INTER_NEAREST)
    return array(out)


def resize_short(src: NDArray, size: int, interp: int = 2) -> NDArray:
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src: NDArray, x0: int, y0: int, w: int, h: int,
               size: Optional[Tuple[int, int]] = None, interp: int = 2) -> NDArray:
    out = array(src.asnumpy()[y0:y0 + h, x0:x0 + w])
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src: NDArray, size: Tuple[int, int], interp: int = 2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src: NDArray, size: Tuple[int, int], interp: int = 2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0, y0 = (w - new_w) // 2, (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src: NDArray, size: Tuple[int, int], area, ratio,
                     interp: int = 2, max_attempts: int = 10):
    """Inception-style random area/aspect crop (reference parity)."""
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(max_attempts):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        aspect = onp.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * aspect) ** 0.5))
        new_h = int(round((target_area / aspect) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src: NDArray, mean, std=None) -> NDArray:
    x = src.asnumpy().astype(onp.float32)
    mean = onp.asarray(mean.asnumpy() if isinstance(mean, NDArray) else mean)
    x = x - mean
    if std is not None:
        std = onp.asarray(std.asnumpy() if isinstance(std, NDArray) else std)
        x = x / std
    return array(x)


# ---------------------------------------------------------------------------
# Augmenter zoo (reference: image.Augmenter subclasses)
# ---------------------------------------------------------------------------

class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self) -> str:
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src: NDArray) -> NDArray:
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size: int, interp: int = 2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p: float = 0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return array(src.asnumpy()[:, ::-1])
        return src


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class CastAug(Augmenter):
    def __init__(self, typ: str = "float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


def CreateAugmenter(data_shape: Tuple[int, int, int], resize: int = 0,
                    rand_crop: bool = False, rand_resize: bool = False,
                    rand_mirror: bool = False, mean=None, std=None,
                    inter_method: int = 2, **kwargs) -> List[Augmenter]:
    """Standard augmenter list builder (reference: image.CreateAugmenter)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std if std is not None else 1.0))
    return auglist


class ImageIter:
    """Python-level image iterator over .rec or an imglist
    (reference: python/mxnet/image/image.py ImageIter)."""

    def __init__(self, batch_size: int, data_shape: Tuple[int, int, int],
                 path_imgrec: Optional[str] = None,
                 imglist: Optional[Sequence] = None,
                 path_root: str = "", aug_list: Optional[List[Augmenter]] = None,
                 shuffle: bool = False, **kwargs):
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self._shuffle = shuffle
        self._items: List = []
        if path_imgrec:
            from .. import recordio
            rec = recordio.MXRecordIO(path_imgrec, "r")
            while True:
                raw = rec.read()
                if raw is None:
                    break
                self._items.append(("rec", raw))
        elif imglist:
            for entry in imglist:
                label, path = float(entry[0]), entry[-1]
                self._items.append(("file", (label, os.path.join(path_root, path))))
        else:
            raise MXNetError("ImageIter needs path_imgrec or imglist")
        self.reset()

    def reset(self):
        self._order = list(range(len(self._items)))
        if self._shuffle:
            pyrandom.shuffle(self._order)
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        from ..io import DataBatch
        if self._pos + self.batch_size > len(self._order):
            raise StopIteration
        data, labels = [], []
        for i in self._order[self._pos:self._pos + self.batch_size]:
            kind, payload = self._items[i]
            if kind == "rec":
                from .. import recordio
                header, img = recordio.unpack_img(payload, iscolor=1)
                cv2 = _cv2()
                img = array(cv2.cvtColor(img, cv2.COLOR_BGR2RGB))
                label = float(header.label) if not onp.ndim(header.label) \
                    else header.label
            else:
                label, path = payload
                img = imread(path)
            for aug in self.auglist:
                img = aug(img)
            data.append(img.asnumpy().transpose(2, 0, 1))
            labels.append(label)
        self._pos += self.batch_size
        return DataBatch([array(onp.stack(data))],
                        [array(onp.asarray(labels, onp.float32))])

    next = __next__


# detection augmenters + ImageDetIter (reference:
# python/mxnet/image/detection.py) — imported at the bottom since the
# submodule borrows the image-only augmenters defined above
from .detection import (  # noqa: E402,F401
    DetAugmenter, DetBorrowAug, DetRandomSelectAug, DetHorizontalFlipAug,
    DetRandomCropAug, DetRandomPadAug, CreateDetAugmenter, ImageDetIter)

__all__ += ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
            "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
            "CreateDetAugmenter", "ImageDetIter"]

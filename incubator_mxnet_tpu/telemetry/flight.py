"""Flight recorder — post-mortem bundles written at the moment of death.

Reference counterpart: none — when the reference crashed, the evidence
died with it (whatever stderr captured). This repo's situation before
this module was structurally the same: the event rings, the trace ring,
the compile ledger, the lock graph, and the profiler's step attribution
are all **in-process memory** — precisely the state that evaporates when
the watchdog trips, a guard halts, a replica is stall-killed, a device
allocation dies with ``RESOURCE_EXHAUSTED`` (``telemetry.memory``'s OOM
guard), or a chaos crash site fires. The flight recorder inverts that: trigger sites call
:func:`dump`, which atomically writes one strict-JSON bundle of every
in-memory diagnostic surface to ``MXTPU_FLIGHT_DIR``; then
``tools/postmortem.py`` renders a bundle into a human-readable timeline.

Contract:

- **Off by default, near-zero when off**: :func:`dump` is one env read
  when ``MXTPU_FLIGHT_DIR`` is unset. Nothing is recorded *for* the
  flight recorder — it snapshots rings that already exist.
- **Atomic**: bundles are written tmp → fsync → ``os.replace``; a
  mid-dump death (chaos site ``flight.dump``) leaves a ``.tmp-*`` file,
  never a torn bundle under the final name. Readers may trust any
  ``flight-*.json`` they can see.
- **Never the second fault**: :func:`dump` swallows its own errors
  (warning, not raise) — a broken disk must not mask the original
  failure. The one exception is :class:`~..fault.inject.ChaosCrash`
  from the ``flight.dump`` site itself, which propagates by design
  (it *is* the simulated mid-dump kill).
- **Storm-bounded**: at most ``MXTPU_FLIGHT_MAX`` bundles per process
  (default 16) and at least ``MXTPU_FLIGHT_MIN_S`` seconds apart
  (default 0) — a crash loop produces a few bundles, not a full disk.

Bundle format (``format: 1``, strict JSON, one file per trigger)::

    flight-<utc>-<reason>-p<pid>.json
    {"format": 1, "reason": ..., "site": ..., "ts": ..., "context": {...},
     "process": {"index": ..., "count": ...},   # which pod member wrote it
     "collective_schedule": {...banked fingerprints + dispatch ring:
                 the SPMD-divergence ledger (telemetry.collective_ledger);
                 a crosscheck-mismatch bundle from each host shows which
                 site/signature they compiled differently...},
     "trace":   {"summary": ..., "spans": [...recent...]},
     "events":  {kind: [...recent per-kind ring...], ...},
     "compiles": {...ledger rollup...},
     "lockcheck": {"edges": [...], "inversions": [...], "held_now": [...]},
     "memory":  {...device-memory ledger: live/site bytes, history,
                 static peaks, leak-watchdog state...},
     "numerics": {...per-site tensor-stats rings (the drift trajectory),
                 drift-watchdog state, calibration rollup...},
     "goodput": {...run-level wall-clock attribution vector +
                 measured-vs-roofline MFU (telemetry.goodput)...},
     "step_report": {...host-gap attribution...},
     "metrics": {...registry table...},
     "env": {...MXTPU_/MXNET_/DMLC_/JAX_/XLA_ vars...},
     "config": {...python/jax/platform...}}
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional

from ..lockcheck import make_lock

__all__ = ["enabled", "flight_dir", "set_dir", "bundle", "dump", "load",
           "list_bundles", "reset"]

_LOCK = make_lock("flight._LOCK")
_DIR_OVERRIDE: Optional[str] = None
_STATE = {"count": 0, "last_ts": 0.0}

#: environment prefixes worth preserving in a post-mortem (config that
#: changes behavior; never the whole environ — tokens/paths leak)
_ENV_PREFIXES = ("MXTPU_", "MXNET_", "DMLC_", "JAX_", "XLA_")


def flight_dir() -> Optional[str]:
    """The bundle directory (``MXTPU_FLIGHT_DIR``; :func:`set_dir`
    overrides), or None = recorder off. In a multi-host run the
    configured directory grows a per-process subdirectory
    (``<dir>/p<index>`` — ``dist.process_namespace``): every host keeps
    its own forensics with zero shared-file races, and the host-loss
    drill can assert "exactly one bundle per *surviving* host" by
    namespace."""
    base = _DIR_OVERRIDE if _DIR_OVERRIDE is not None \
        else os.environ.get("MXTPU_FLIGHT_DIR")
    if not base:
        return None
    from ..parallel.dist import process_namespace
    ns = process_namespace()
    return os.path.join(base, ns) if ns else base


def set_dir(path: Optional[str]) -> None:
    """Programmatic override (tests, the chaos drill). ``None`` re-reads
    the env; ``""`` forces off."""
    global _DIR_OVERRIDE
    _DIR_OVERRIDE = path


def enabled() -> bool:
    return flight_dir() is not None


def _limits():
    from ..util import getenv
    try:
        mx = int(getenv("MXTPU_FLIGHT_MAX"))
    except (TypeError, ValueError):
        mx = 16
    try:
        min_s = float(getenv("MXTPU_FLIGHT_MIN_S"))
    except (TypeError, ValueError):
        min_s = 0.0
    return mx, min_s


def _span_cap() -> int:
    from ..util import getenv
    try:
        return int(getenv("MXTPU_FLIGHT_SPANS"))
    except (TypeError, ValueError):
        return 2048


def bundle(reason: str, /, site: Optional[str] = None, **context) -> Dict:
    """Assemble the post-mortem dict from every in-memory diagnostic
    surface. Pure read — no I/O, no rate limit — so tests and
    ``telemetry.snapshot()``-style callers can inspect without writing.
    Each surface is snapshotted independently: one broken subsystem
    degrades its own section to an ``{"error": ...}`` stub instead of
    costing the whole bundle."""
    from .. import profiler
    from ..lockcheck import edges, held_now, inversions
    from . import (collective_ledger, compile_log, events, goodput, memory,
                   metrics, numerics, trace)
    from .export import sanitize

    doc: Dict = {"format": 1, "reason": reason, "site": site,
                 "ts": time.time(),
                 "pid": os.getpid(),
                 "thread": threading.current_thread().name,
                 "context": dict(context)}
    # which pod member wrote this bundle: a collective-schedule mismatch
    # produces one bundle PER process, and the cross-host diff starts by
    # lining them up by index (reads coordination state only — never
    # initializes a backend from a crash path)
    _, _pidx, _pcount = collective_ledger._coord()
    doc["process"] = {"index": _pidx, "count": _pcount}

    def section(name, fn):
        try:
            doc[name] = fn()
        except Exception as e:  # noqa: BLE001 — degrade, don't lose all
            doc[name] = {"error": f"{type(e).__name__}: {e}"}

    section("trace", lambda: {"summary": trace.summary(),
                              "spans": trace.spans()[-_span_cap():]})
    section("events", lambda: {
        kind: [e.to_dict() for e in events.events(kind)]
        for kind in sorted(events.counts())})
    section("compiles", compile_log.summary)
    section("lockcheck", lambda: {"edges": sorted(edges()),
                                  "inversions": sorted(inversions()),
                                  "held_now": held_now()})
    section("step_report", lambda: {
        "step": profiler.step_report("step"),
        "serve.predict": profiler.step_report("serve.predict")})
    section("metrics", metrics.to_dict)
    # the device-memory ledger: a fresh sample at the moment of death,
    # the recent history ring, and the statically-predicted peaks — an
    # OOM bundle (reason "resource_exhausted") reads prediction vs
    # measurement on one page
    section("memory", memory.snapshot)
    # numerics rings: a guard-halt bundle carries the per-site drift
    # trajectory — the hundreds of steps of rms growth BEFORE the
    # non-finite verdict, not just the corpse
    section("numerics", numerics.snapshot)
    # the goodput ledger: where the dead run's wall-seconds had been
    # going (attribution vector + measured-vs-roofline MFU) — the
    # "was it even training efficiently" page of the post-mortem
    section("goodput", goodput.snapshot)
    # the flight director's decision ring: which remediations the closed
    # loop applied (or reverted) before the run died — the "did the
    # autopilot touch anything" page of the post-mortem
    from . import director as _director
    section("director", _director.snapshot)
    # the collective-schedule ledger: banked fingerprints + the dispatch
    # ring — a crosscheck-mismatch bundle shows WHICH site/signature this
    # process compiled differently from its peers
    section("collective_schedule", collective_ledger.snapshot)
    from ..parallel import elastic as _elastic
    section("membership", _elastic.snapshot)
    section("env", lambda: {k: v for k, v in sorted(os.environ.items())
                            if k.startswith(_ENV_PREFIXES)})
    section("config", lambda: _config())
    return sanitize(doc)


def _config() -> Dict:
    import platform
    cfg = {"python": sys.version.split()[0],
           "platform": platform.platform()}
    try:
        import jax
        cfg["jax"] = jax.__version__
        cfg["backend"] = jax.default_backend()
    except Exception:  # noqa: BLE001 — config is best-effort
        pass
    return cfg


def dump(reason: str, /, site: Optional[str] = None, **context
         ) -> Optional[str]:
    # ``reason`` is positional-only so trigger context may itself carry a
    # "reason" field (a guard's trip info, a kill reason) without clashing
    """Write one bundle; returns its path, or None (recorder off, rate
    limit hit, or the write failed — a warning, never a raise: the dump
    must not become the second fault that masks the first).

    The write is atomic (tmp + fsync + ``os.replace``) with a chaos
    crash point ``flight.dump`` between the write and the rename — the
    harness's simulated mid-dump kill, which must leave no torn bundle
    under the final name."""
    d = flight_dir()
    if d is None:
        return None
    max_n, min_s = _limits()
    now = time.monotonic()
    with _LOCK:
        if _STATE["count"] >= max_n:
            return None
        if min_s > 0 and _STATE["last_ts"] and \
                now - _STATE["last_ts"] < min_s:
            return None
        _STATE["count"] += 1
        prev_ts = _STATE["last_ts"]
        _STATE["last_ts"] = now
        seq = _STATE["count"]
    from ..fault import inject as _inject
    from ..fault.inject import ChaosCrash
    try:
        doc = bundle(reason, site=site, **context)
        from .export import dumps_strict
        blob = dumps_strict(doc, sort_keys=True)
        os.makedirs(d, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(doc["ts"]))
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)
        path = os.path.join(
            d, f"flight-{stamp}-{safe}-p{os.getpid()}-{seq}.json")
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            # per-host divergence is the design: flight_dir() is
            # namespaced per process and the name carries the pid
            with open(tmp, "w",             # mxlint: disable=MX902
                      encoding="utf-8") as f:
                f.write(blob + "\n")
                f.flush()
                os.fsync(f.fileno())
            # the simulated mid-dump kill: tmp is on disk, the final
            # name is not — atomicity means readers never see a torn
            # bundle however exactly this process dies
            _inject.crash("flight.dump")
            os.replace(tmp, path)       # mxlint: disable=MX902
        except ChaosCrash:
            # the simulated SIGKILL: a real one cannot run cleanup, so
            # neither does the simulation — the ``.tmp-*`` file stays
            # behind exactly as the docstring tells operators to expect
            raise
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except ChaosCrash:
        raise          # the simulated death itself — see the docstring
    except Exception as e:  # noqa: BLE001 — never mask the first fault
        # refund the MXTPU_FLIGHT_MAX budget AND the MIN_S window: a
        # transiently unwritable dir during a crash loop must not eat
        # the cap — or start a storm-damping window that silences the
        # very next trigger — when zero bundles exist (the state was
        # taken before the write so concurrent triggers rate-limit
        # correctly)
        with _LOCK:
            _STATE["count"] -= 1
            if _STATE["last_ts"] == now:
                _STATE["last_ts"] = prev_ts
        import warnings
        warnings.warn(f"[telemetry.flight] bundle write failed "
                      f"({reason!r}): {type(e).__name__}: {e}")
        return None
    # announce AFTER the bundle exists: the event stream names a path
    # that is guaranteed readable
    from . import events as _events
    from . import metrics as _metrics
    _events.emit("flight.dump", severity="warning", reason=reason,
                 site=site, path=path)
    _metrics.counter("mxtpu_flight_bundles_total",
                     "Post-mortem bundles written", reason=reason).inc()
    return path


def load(path: str) -> Dict:
    """Read one bundle back (strict JSON; raises on a torn/invalid file —
    which, by the atomicity contract, means a bug, not a crash)."""
    from .export import loads_strict
    with open(path, encoding="utf-8") as f:
        doc = loads_strict(f.read())
    if doc.get("format") != 1:
        raise ValueError(f"{path}: unknown flight-bundle format "
                         f"{doc.get('format')!r}")
    return doc


def list_bundles(d: Optional[str] = None) -> List[str]:
    """Completed bundle paths in ``d`` (default: the active dir), oldest
    first by name (names embed the UTC stamp)."""
    d = d or flight_dir()
    if d is None or not os.path.isdir(d):
        return []
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.startswith("flight-") and f.endswith(".json"))


def reset() -> None:
    """Reset the per-process storm limiter (tests)."""
    with _LOCK:
        _STATE["count"] = 0
        _STATE["last_ts"] = 0.0

"""Goodput ledger — run-level wall-clock attribution + measured MFU.

Reference counterpart: none — the reference (and, until this module,
this repo) could time a step (``profiler.step_report``) and price a
graph device-blind (``analysis.hlo.cost``), but had no notion of
*goodput*: nothing attributed every wall-second of a training run to
where it actually went, so "why is the banked MFU stuck at 0.3789" was
unanswerable from telemetry alone. TVM and the XLA fusion study
(PAPERS.md) both score *whole-run* efficiency, not per-graph cost —
this module is that score for the live runtime.

The ledger folds the runtime's existing per-phase measurements — the
trainer's ``step`` frame segments (place/dispatch/device_wait), the
``io.PrefetchIter`` input-wait instrumentation, ``fault.checkpoint``
save spans, StepGuard rollback verdicts, and the compile ledger's
warmup walls — into one per-window **attribution vector**:

========================  ==================================================
``compute``               device time the host provably blocked on (the
                          guard's single sync), minus the collective share
``collective``            the communication share of device time, split by
                          the cost model's roofline ratio (comm_s vs
                          compute_s) — deterministic, documented, honest
                          about being a model
``input_wait``            host blocked on the input pipeline
                          (``PrefetchIter`` queue pops)
``host``                  per-step host tax: placement, dispatch, and the
                          un-instrumented Python remainder of each step
``compile``               first-signature trace+compile walls (one-off,
                          never steady-state)
``checkpoint``            ``fault.checkpoint`` save walls
``rollback_waste``        wall time of rolled-back steps PLUS the
                          since-snapshot steps a rollback discards (their
                          already-attributed time is *reclassified* — work
                          the run paid for and then threw away)
``unattributed``          run wall-clock not covered by any note — the
                          ledger's own honesty metric, gated ``< 10%`` by
                          the ``goodput-smoke`` CI job
========================  ==================================================

Headline: ``measured_mfu = flops_per_step · good_steps / (wall · PEAK)``
— reconciled against the cost-model roofline (``predicted_mfu``), so
predicted-vs-measured divergence is itself a tracked metric
(``mxtpu_goodput_mfu_divergence_pct``). The cost profile comes from
:func:`price` (one ``analysis.hlo.cost`` trace — zero XLA compiles) or
:func:`set_cost_profile`.

Everything is **off by default** (``MXTPU_GOODPUT`` unset): the hooks in
the trainer/io/checkpoint hot paths are one :func:`enabled` check, the
compiled graphs are untouched either way (the ledger is host-side
bookkeeping only — the perf-proxy CI gate proves banked PERF_PROXY.json
stays byte-identical, and the fused step still runs exactly one jitted
graph with the ledger on).

Usage::

    MXTPU_GOODPUT=1 python train.py     # or goodput.configure(on=True)

    goodput.price(trainer, sample_args=(x, y))   # roofline reconciliation
    goodput.begin()
    for placed in prefetch_iter:
        trainer.step(*placed)                    # notes itself
    rep = goodput.report()
    rep["classification"]                        # "input_bound" | ...
    rep["mfu"]["measured_mfu"]

Every ``MXTPU_GOODPUT_WINDOW`` steps the ledger emits one
``goodput.window`` event and refreshes the ``mxtpu_goodput_*`` gauges;
``telemetry.snapshot()``, flight bundles, and ``tools/postmortem.py``
all carry the full report. ``tools/perf_history.py`` is the offline
twin: it merges the banked ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` /
``PERF_PROXY.json`` artifacts into one trajectory with regression flags.
"""
from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Dict, Optional

from ..lockcheck import make_lock

__all__ = ["CATEGORIES", "enabled", "configure", "begin", "begin_from_env",
           "note", "note_step", "set_cost_profile", "cost_profile", "price",
           "collective_ms", "report", "snapshot", "reset", "window_steps",
           "note_serve", "set_serve_cost_profile", "serve_report"]

#: the attribution vector, in triage order (docs/observability.md §6):
#: an operator works the list top-down — input starvation first, host
#: tax second, communication third; only then is "make compute faster"
#: the right lever
CATEGORIES = ("input_wait", "host", "collective", "compute", "compile",
              "checkpoint", "rollback_waste")

#: categories eligible to classify a run as X-bound (one-off compile /
#: checkpoint / waste are symptoms, not steady-state regimes)
_BOUND_CATEGORIES = ("input_wait", "host", "collective", "compute")

_LOCK = make_lock("goodput._LOCK")
_ON_OVERRIDE: Optional[bool] = None
_WINDOW_OVERRIDE: Optional[int] = None


def _new_state() -> Dict[str, Any]:
    return {
        "t0": None,              # perf_counter at begin()
        "wall_anchor": None,     # wall clock at begin() (reporting only)
        "ms": {c: 0.0 for c in CATEGORIES},
        "steps": 0, "good_steps": 0, "rolled_back": 0,
        "checkpoints": 0, "windows": 0,
        # per-step attribution ring: the rollback reclassification needs
        # to know where the discarded steps' time originally went
        "ring": deque(maxlen=256),
        # inter-step gap accounting: perf_counter at the last step's
        # end, and note() ms accumulated since — the loop time BETWEEN
        # steps (iterator protocol, logging, the ledger's own overhead)
        # is host tax, attributed at the next note_step instead of
        # leaking into unattributed
        "last_mark": None,
        "gap_notes_ms": 0.0,
        "win": {"t0": None, "ms": {c: 0.0 for c in CATEGORIES},
                "steps": 0, "good_steps": 0, "rolled_back": 0},
        "cost": None,            # set_cost_profile() result
    }


_S = _new_state()


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """Ledger on? One env read on the hot path (``MXTPU_GOODPUT=1``;
    :func:`configure` overrides) — the same zero-cost-when-off contract
    as ``fault.inject``/``telemetry.numerics``."""
    if _ON_OVERRIDE is not None:
        return _ON_OVERRIDE
    return os.environ.get("MXTPU_GOODPUT", "0") == "1"


def window_steps() -> int:
    """Steps per ``goodput.window`` event (``MXTPU_GOODPUT_WINDOW``,
    default 32; :func:`configure` overrides)."""
    if _WINDOW_OVERRIDE is not None:
        return _WINDOW_OVERRIDE
    try:
        return max(1, int(os.environ.get("MXTPU_GOODPUT_WINDOW", "32")))
    except ValueError:
        return 32


def configure(on: Optional[bool] = None,
              window: Optional[int] = None) -> None:
    """Programmatic override of the env knobs (tests, the smoke tool).
    Calling with no arguments clears both overrides (back to the env)."""
    global _ON_OVERRIDE, _WINDOW_OVERRIDE
    if on is None and window is None:
        _ON_OVERRIDE = None
        _WINDOW_OVERRIDE = None
        return
    if on is not None:
        _ON_OVERRIDE = bool(on)
    if window is not None:
        _WINDOW_OVERRIDE = max(1, int(window))


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

def begin(reset_totals: bool = True) -> None:
    """Anchor the run clock NOW. Everything between :func:`begin` and
    :func:`report` is the wall this ledger must account for; call it
    right before the training loop so setup/compile of earlier phases
    does not land in ``unattributed``. Notes auto-begin if the caller
    never does."""
    global _S
    with _LOCK:
        if reset_totals:
            cost = _S["cost"]
            _S = _new_state()
            _S["cost"] = cost
        _S["t0"] = time.perf_counter()
        _S["wall_anchor"] = time.time()
        _S["win"]["t0"] = _S["t0"]
        _S["last_mark"] = _S["t0"]
        _S["gap_notes_ms"] = 0.0


def begin_from_env() -> bool:
    """:func:`begin` iff the ledger is enabled — the one-liner drivers
    (serve_bench, training scripts) call unconditionally."""
    if not enabled():
        return False
    begin()
    return True


def _auto_begin_locked() -> None:
    if _S["t0"] is None:
        _S["t0"] = time.perf_counter()
        _S["wall_anchor"] = time.time()
        _S["win"]["t0"] = _S["t0"]
        _S["last_mark"] = _S["t0"]


def note(category: str, dur_ms: float) -> None:
    """Attribute ``dur_ms`` of wall time to one category — the generic
    hook (``io.PrefetchIter`` notes ``input_wait``, ``fault.checkpoint``
    notes ``checkpoint``). No-op when the ledger is off."""
    if not enabled() or category not in _S["ms"]:
        return
    with _LOCK:
        _auto_begin_locked()
        _S["ms"][category] += dur_ms
        _S["win"]["ms"][category] += dur_ms
        _S["gap_notes_ms"] += dur_ms
        if category == "checkpoint":
            _S["checkpoints"] += 1


def collective_ms() -> float:
    """Cumulative wall attributed to the ``collective`` bucket — the
    per-host straggler signal the elastic heartbeat banks with each
    lease, so a host whose collectives are slow is a *gauge* on its
    peers' lease tables before it is a detected failure. 0.0 when the
    ledger is off."""
    with _LOCK:
        return float(_S["ms"].get("collective", 0.0))


def _collective_fraction() -> float:
    """The roofline comm share of device time — ``comm_s / (compute_s +
    comm_s)`` from the cost profile. 0.0 without a profile (all device
    time reads as compute). A *model*, not a measurement: collectives
    execute inside the compiled graph where the host cannot see them,
    so the split is the cost model's — which is exactly what makes
    predicted-vs-measured divergence meaningful."""
    cost = _S["cost"]
    if not cost:
        return 0.0
    comp_s = cost.get("compute_s") or 0.0
    comm_s = cost.get("comm_s") or 0.0
    total = comp_s + comm_s
    return (comm_s / total) if total > 0 else 0.0


def note_step(step: int, wall_ms: float, device_wait_ms: float = 0.0,
              compile_ms: float = 0.0, rolled_back: bool = False,
              rollback_to: Optional[int] = None) -> None:
    """Attribute one training step's wall time (``ShardedTrainer.step``
    calls this from the timings it already measures — the ledger and
    the ``train.step`` event can never disagree).

    Split: ``device_wait_ms`` (the guard's single host sync — the one
    point the host provably blocks on the device) becomes compute +
    collective by the roofline comm fraction; ``compile_ms`` (the
    dispatch wall of a first-signature trace) is one-off compile; the
    rest of the frame — placement, steady dispatch, Python remainder,
    none of which can change the split — is per-step ``host`` tax (the
    finer breakdown lives in ``profiler.step_report``). A rolled-back
    step's ENTIRE wall is ``rollback_waste``, and ``rollback_to`` (the
    snapshot step the trainer restored) additionally reclassifies the
    since-snapshot steps' recorded time as waste — their updates were
    discarded, so their wall bought nothing."""
    if not enabled():
        return
    from . import events as _events
    from . import metrics as _metrics
    now = time.perf_counter()
    with _LOCK:
        _auto_begin_locked()
        # the gap since the previous step's end, minus whatever was
        # already noted inside it (io waits, checkpoint saves), is the
        # loop's host-side time between steps — attribute it so the
        # vector sums to the run wall instead of leaking the loop tax
        # into unattributed
        start = now - wall_ms / 1e3
        mark = _S["last_mark"]
        if mark is not None:
            gap_host = max((start - mark) * 1e3 - _S["gap_notes_ms"], 0.0)
            if gap_host > 0:
                _S["ms"]["host"] += gap_host
                _S["win"]["ms"]["host"] += gap_host
        _S["last_mark"] = now
        _S["gap_notes_ms"] = 0.0
        vec: Dict[str, float] = {}
        if rolled_back:
            vec["rollback_waste"] = wall_ms
        else:
            compile_part = min(max(compile_ms, 0.0), wall_ms)
            device = min(max(device_wait_ms, 0.0),
                         max(wall_ms - compile_part, 0.0))
            coll = device * _collective_fraction()
            vec["compile"] = compile_part
            vec["collective"] = coll
            vec["compute"] = device - coll
            vec["host"] = max(wall_ms - device - compile_part, 0.0)
        for cat, ms in vec.items():
            _S["ms"][cat] += ms
            _S["win"]["ms"][cat] += ms
        _S["steps"] += 1
        _S["win"]["steps"] += 1
        if rolled_back:
            _S["rolled_back"] += 1
            _S["win"]["rolled_back"] += 1
            if rollback_to is not None:
                _reclassify_discarded_locked(rollback_to)
        else:
            _S["good_steps"] += 1
            _S["win"]["good_steps"] += 1
            _S["ring"].append((step, vec))
        close = _S["win"]["steps"] >= window_steps()
        win_doc = _close_window_locked() if close else None
    if win_doc is not None:
        # emit outside the ledger lock (the bus fans out to subscribers)
        _events.emit("goodput.window", step=step, **win_doc)
        _publish_gauges(_metrics, win_doc)


def _reclassify_discarded_locked(rollback_to: int) -> None:
    """A rollback restored the step counter to ``rollback_to``: every
    recorded step AFTER it was work the run paid for and then threw
    away. Move its attributed time — wherever it originally went —
    into ``rollback_waste``, in both the cumulative and current-window
    vectors (window moves are clamped to what the window still holds:
    time attributed in an already-closed window stays reported there)."""
    keep = deque(maxlen=_S["ring"].maxlen)
    discarded = 0
    for step, vec in _S["ring"]:
        if step <= rollback_to:
            keep.append((step, vec))
            continue
        discarded += 1
        for cat, ms in vec.items():
            moved = min(ms, _S["ms"][cat])
            _S["ms"][cat] -= moved
            _S["ms"]["rollback_waste"] += moved
            win_moved = min(ms, _S["win"]["ms"][cat])
            _S["win"]["ms"][cat] -= win_moved
            _S["win"]["ms"]["rollback_waste"] += win_moved
    _S["ring"] = keep
    # the discarded steps are no longer productive: measured_mfu counts
    # only updates that SURVIVED, so a run that trains 99 steps and
    # rolls them all back reads as ~zero goodput, not near-full MFU
    _S["good_steps"] = max(_S["good_steps"] - discarded, 0)
    _S["win"]["good_steps"] = max(_S["win"]["good_steps"] - discarded, 0)


# ---------------------------------------------------------------------------
# serve twin — token-level goodput for the decode path
# ---------------------------------------------------------------------------

def _new_serve_state() -> Dict[str, Any]:
    return {"t0": None,
            "ms": {"prefill": 0.0, "decode": 0.0},
            "tokens": {"prefill": 0, "decode": 0},
            "calls": {"prefill": 0, "decode": 0},
            "cost": None}


_SERVE = _new_serve_state()


def note_serve(kind: str, tokens: int, wall_ms: float) -> None:
    """Attribute one serve-side dispatch: ``kind`` is ``"prefill"`` (one
    prompt, ``tokens`` = prompt length) or ``"decode"`` (one step,
    ``tokens`` = active rows advanced). The DecodeBatcher calls this at
    every token boundary; no-op when the ledger is off — same zero-cost
    contract as the training hooks."""
    if not enabled() or kind not in ("prefill", "decode"):
        return
    with _LOCK:
        if _SERVE["t0"] is None:
            _SERVE["t0"] = time.perf_counter()
        _SERVE["ms"][kind] += float(wall_ms)
        _SERVE["tokens"][kind] += int(tokens)
        _SERVE["calls"][kind] += 1


def set_serve_cost_profile(flops_per_token: float,
                           hbm_bytes_per_token: float = 0.0,
                           source: Optional[str] = None) -> Dict[str, Any]:
    """Install the per-generated-token cost the decode roofline ceiling
    is computed against (same ``util.roofline_peaks()`` constants as the
    training profile). Decode is almost always HBM-bound — every step
    re-reads the params and the live cache pages — so the ceiling is
    ``1 / max(flops/PEAK, hbm/BW)`` tokens/sec. Returns the profile."""
    from ..util import roofline_peaks
    peak_flops, peak_bw, _ici = roofline_peaks()
    compute_s = flops_per_token / peak_flops
    mem_s = hbm_bytes_per_token / peak_bw
    token_s = max(compute_s, mem_s)
    prof = {"flops_per_token": float(flops_per_token),
            "hbm_bytes_per_token": float(hbm_bytes_per_token),
            "compute_s": compute_s, "mem_s": mem_s,
            "roofline_tokens_per_s": (1.0 / token_s) if token_s > 0
            else None,
            "bound": "hbm" if mem_s >= compute_s else "compute",
            "source": source}
    with _LOCK:
        _SERVE["cost"] = prof
    return prof


def serve_report() -> Dict[str, Any]:
    """The decode-goodput twin of :func:`report`: measured tokens/sec vs
    the per-token roofline ceiling, and the prefill-bound vs decode-bound
    wall split (which of the two graphs the serve wall actually went to).
    Publishes the ``mxtpu_goodput_serve_*`` gauges. Strict-JSON-safe."""
    from . import metrics as _metrics
    with _LOCK:
        t0 = _SERVE["t0"]
        wall_ms = ((time.perf_counter() - t0) * 1e3
                   if t0 is not None else 0.0)
        pre_ms = _SERVE["ms"]["prefill"]
        dec_ms = _SERVE["ms"]["decode"]
        dec_tok = _SERVE["tokens"]["decode"]
        doc: Dict[str, Any] = {
            "enabled": enabled(),
            "wall_ms": round(wall_ms, 3),
            "prefill": {"ms": round(pre_ms, 3),
                        "tokens": _SERVE["tokens"]["prefill"],
                        "calls": _SERVE["calls"]["prefill"]},
            "decode": {"ms": round(dec_ms, 3),
                       "tokens": dec_tok,
                       "steps": _SERVE["calls"]["decode"]},
        }
        attributed = pre_ms + dec_ms
        doc["attributed_ms"] = round(attributed, 3)
        doc["unattributed_pct"] = (
            round(100.0 * max(wall_ms - attributed, 0.0) / wall_ms, 2)
            if wall_ms > 0 else 0.0)
        doc["tokens_per_s"] = (round(dec_tok / (wall_ms / 1e3), 3)
                               if wall_ms > 0 else None)
        doc["decode_tokens_per_s"] = (round(dec_tok / (dec_ms / 1e3), 3)
                                      if dec_ms > 0 else None)
        doc["classification"] = (None if attributed == 0 else
                                 ("prefill_bound" if pre_ms > dec_ms
                                  else "decode_bound"))
        cost = _SERVE["cost"]
        doc["cost_profile"] = dict(cost) if cost else None
        ceiling = cost["roofline_tokens_per_s"] if cost else None
        doc["roofline_tokens_per_s"] = (round(ceiling, 3)
                                        if ceiling else None)
        doc["roofline_fraction"] = (
            round((dec_tok / (wall_ms / 1e3)) / ceiling, 6)
            if ceiling and wall_ms > 0 else None)
    if doc["tokens_per_s"] is not None:
        _metrics.gauge("mxtpu_goodput_serve_tokens_per_s",
                       "Generated tokens/sec over the serve ledger window"
                       ).set(doc["tokens_per_s"])
    if doc["roofline_fraction"] is not None:
        _metrics.gauge("mxtpu_goodput_serve_roofline_fraction",
                       "Measured tokens/sec over the per-token roofline "
                       "ceiling").set(doc["roofline_fraction"])
    return doc


# ---------------------------------------------------------------------------
# cost profile / MFU reconciliation
# ---------------------------------------------------------------------------

def set_cost_profile(flops_per_step: float,
                     hbm_bytes_per_step: float = 0.0,
                     comm_bytes_per_step: float = 0.0,
                     source: Optional[str] = None) -> Dict[str, Any]:
    """Install the deterministic per-step cost the MFU headline and the
    collective split are computed against. ``roofline_s`` is the
    steady-state core of ``benchmark/autotune.py``'s score —
    ``max(flops/PEAK, hbm/BW) + comm/ICI`` over the SAME
    ``util.roofline_peaks()`` constants (the autotuner additionally
    amortizes per-kernel launch and warmup-compile terms, which are not
    per-step device time). Returns the profile."""
    from ..util import roofline_peaks
    peak_flops, peak_bw, ici_bw = roofline_peaks()
    compute_s = flops_per_step / peak_flops
    mem_s = hbm_bytes_per_step / peak_bw
    comm_s = comm_bytes_per_step / ici_bw
    roofline_s = max(compute_s, mem_s) + comm_s
    prof = {
        "flops_per_step": float(flops_per_step),
        "hbm_bytes_per_step": float(hbm_bytes_per_step),
        "comm_bytes_per_step": float(comm_bytes_per_step),
        "peak_tflops": peak_flops / 1e12,
        "compute_s": compute_s, "mem_s": mem_s, "comm_s": comm_s,
        "roofline_s": roofline_s,
        "predicted_mfu": ((flops_per_step / (roofline_s * peak_flops))
                          if roofline_s > 0 else None),
        "source": source,
    }
    with _LOCK:
        _S["cost"] = prof
    return prof


def cost_profile() -> Optional[Dict[str, Any]]:
    with _LOCK:
        return dict(_S["cost"]) if _S["cost"] else None


def price(target, sample_args=None) -> Dict[str, Any]:
    """Price ``target`` (a ``ShardedTrainer``, ``CompiledModel``, or any
    ``analysis.hlo`` traceable) with the device-blind cost model — one
    ``make_jaxpr`` trace, zero XLA compiles — and install the result as
    the ledger's cost profile. The one-call roofline reconciliation."""
    from ..analysis import hlo
    prep = getattr(target, "prepare", None)
    if prep is not None and sample_args is not None:
        # a ShardedTrainer that has not stepped yet: prepare() builds
        # the pjit step WITHOUT dispatching, so pricing stays trace-only
        prep(*sample_args)
    rep = hlo.cost(target, sample_args=sample_args)
    return set_cost_profile(
        flops_per_step=rep.model_flops_per_step(),
        hbm_bytes_per_step=rep.bytes_per_step(),
        comm_bytes_per_step=rep.comm_bytes_per_step(),
        source="analysis.hlo.cost")


def _mfu(wall_ms: float, good_steps: int) -> Optional[Dict[str, Any]]:
    """measured vs roofline-predicted MFU over ``wall_ms`` of run time
    containing ``good_steps`` productive steps. None without a profile."""
    cost = _S["cost"]
    if not cost or wall_ms <= 0:
        return None
    peak_flops = cost["peak_tflops"] * 1e12
    measured = (cost["flops_per_step"] * good_steps) \
        / (wall_ms / 1e3 * peak_flops)
    predicted = cost["predicted_mfu"]
    div = (100.0 * (measured / predicted - 1.0)
           if predicted else None)
    return {"measured_mfu": round(measured, 9),
            "predicted_mfu": (round(predicted, 9)
                              if predicted is not None else None),
            "divergence_pct": (round(div, 2) if div is not None else None),
            "flops_per_step": cost["flops_per_step"],
            "peak_tflops": cost["peak_tflops"],
            "cost_source": cost.get("source")}


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------

def _vector_doc(ms: Dict[str, float], wall_ms: float) -> Dict[str, Any]:
    attributed = sum(ms.values())
    unattr = max(wall_ms - attributed, 0.0)
    cats = {c: {"ms": round(v, 3),
                "share_pct": (round(100.0 * v / wall_ms, 2)
                              if wall_ms > 0 else 0.0)}
            for c, v in ms.items()}
    cats["unattributed"] = {
        "ms": round(unattr, 3),
        "share_pct": (round(100.0 * unattr / wall_ms, 2)
                      if wall_ms > 0 else 0.0)}
    return {"attributed_ms": round(attributed, 3),
            "unattributed_ms": round(unattr, 3),
            "unattributed_pct": cats["unattributed"]["share_pct"],
            "categories": cats}


def _classify(ms: Dict[str, float]) -> Optional[str]:
    """Dominant steady-state bucket → ``"<bucket>_bound"`` (``input_wait``
    reads as ``input_bound``). Ties break in triage order — the runbook's
    input → host → collective → compute."""
    best, best_ms = None, 0.0
    for cat in _BOUND_CATEGORIES:          # triage order: first wins ties
        v = ms.get(cat, 0.0)
        if v > best_ms:
            best, best_ms = cat, v
    if best is None:
        return None
    return ("input_bound" if best == "input_wait" else f"{best}_bound")


def _close_window_locked() -> Dict[str, Any]:
    """Roll the current window into a ``goodput.window`` event payload
    (caller emits outside the lock) and reset it."""
    win = _S["win"]
    now = time.perf_counter()
    wall_ms = (now - win["t0"]) * 1e3 if win["t0"] is not None else 0.0
    _S["windows"] += 1
    doc = {"window": _S["windows"], "wall_ms": round(wall_ms, 3),
           "steps": win["steps"], "good_steps": win["good_steps"],
           "rolled_back_steps": win["rolled_back"]}
    doc.update(_vector_doc(win["ms"], wall_ms))
    doc["classification"] = _classify(win["ms"])
    mfu = _mfu(wall_ms, win["good_steps"])
    if mfu is not None:
        doc["mfu"] = mfu
    # events carry the flat ms vector (strict-JSON scalars); the nested
    # per-category dicts stay in report()/snapshot()
    doc["categories"] = {c: v["ms"] for c, v in doc["categories"].items()}
    _S["win"] = {"t0": now, "ms": {c: 0.0 for c in CATEGORIES},
                 "steps": 0, "good_steps": 0, "rolled_back": 0}
    return doc


def _publish_gauges(_metrics, win_doc: Dict[str, Any]) -> None:
    wall = win_doc["wall_ms"] or 1.0
    for cat, ms in win_doc["categories"].items():
        _metrics.gauge("mxtpu_goodput_share_pct",
                       "Goodput attribution share over the last window",
                       category=cat).set(round(100.0 * ms / wall, 2))
    _metrics.gauge("mxtpu_goodput_unattributed_pct",
                   "Unattributed share of the last goodput window"
                   ).set(win_doc["unattributed_pct"])
    _metrics.counter("mxtpu_goodput_windows_total",
                     "Closed goodput attribution windows").inc()
    mfu = win_doc.get("mfu")
    if mfu:
        _metrics.gauge("mxtpu_goodput_measured_mfu",
                       "Measured MFU over the last goodput window"
                       ).set(mfu["measured_mfu"])
        if mfu.get("predicted_mfu") is not None:
            _metrics.gauge("mxtpu_goodput_predicted_mfu",
                           "Cost-model roofline MFU ceiling"
                           ).set(mfu["predicted_mfu"])
        if mfu.get("divergence_pct") is not None:
            _metrics.gauge("mxtpu_goodput_mfu_divergence_pct",
                           "Measured-vs-roofline MFU divergence"
                           ).set(mfu["divergence_pct"])


def report() -> Dict[str, Any]:
    """The cumulative ledger: run wall since :func:`begin`, the full
    attribution vector (``unattributed`` = wall the ledger never saw),
    rollback-waste accounting, the dominant-bucket classification, and
    the measured-vs-roofline MFU headline. Strict-JSON-safe."""
    with _LOCK:
        on = enabled()
        t0 = _S["t0"]
        wall_ms = ((time.perf_counter() - t0) * 1e3
                   if t0 is not None else 0.0)
        doc: Dict[str, Any] = {
            "enabled": on,
            "window_steps": window_steps(),
            "began_at": _S["wall_anchor"],
            "wall_ms": round(wall_ms, 3),
            "steps": _S["steps"], "good_steps": _S["good_steps"],
            "rolled_back_steps": _S["rolled_back"],
            "checkpoints": _S["checkpoints"],
            "windows": _S["windows"],
        }
        doc.update(_vector_doc(_S["ms"], wall_ms))
        doc["classification"] = _classify(_S["ms"])
        doc["mfu"] = _mfu(wall_ms, _S["good_steps"])
        doc["cost_profile"] = dict(_S["cost"]) if _S["cost"] else None
    # per-host attribution stamp: N hosts emit N ledgers (namespaced
    # JSONL), and the process pair is what lets a straggler host be
    # singled out when the reports are laid side by side
    from ..parallel.dist import world
    idx, count = world()
    doc["process"] = {"index": idx, "count": count}
    return doc


def snapshot() -> Dict[str, Any]:
    """The ledger's section of ``telemetry.snapshot()`` and flight
    bundles — :func:`report` (already a pure read)."""
    return report()


def reset() -> None:
    """Drop all ledger state including the cost profile and any
    :func:`configure` overrides (test isolation)."""
    global _S, _SERVE, _ON_OVERRIDE, _WINDOW_OVERRIDE
    with _LOCK:
        _S = _new_state()
        _SERVE = _new_serve_state()
        _ON_OVERRIDE = None
        _WINDOW_OVERRIDE = None

"""Collective-schedule ledger — the runtime twin of the MX9xx passes.

Reference counterpart: none. The ps-lite lineage's dominant multi-host
failure was visible (a dead server, a dropped connection, a timeout);
the multi-controller SPMD model trades it for an *invisible* one — one
process takes a divergent branch, compiles a different step graph, and
the whole pod blocks inside a collective that part of it never issues.
No crash, no log line, a hung pod burning its reservation.

This ledger makes the invariant checkable at runtime, the same shape as
``MX802 ↔ lockcheck`` and ``MX706 ↔ compile ledger`` one tier down:

- **Bank at build time**: every pjit step / serve bucket build banks a
  cheap *fingerprint* of its compiled collective structure — the ordered
  collective verb/axis schedule (the SAME
  :func:`~...analysis.distributed.schedule.schedule_of` extractor the
  static MX905 pass uses), the cost model's collective-op counts and
  per-device comm bytes, and the triggering signature — keyed by
  ``(site, signature)``.
- **Ring at dispatch time**: each executed step appends ``(site,
  signature)`` to a bounded schedule ring — the "what was this pod
  actually dispatching" half of a post-mortem, snapshotted into every
  flight bundle.
- **Crosscheck at the dangerous moments**: :func:`crosscheck` exchanges
  each process's banked digest table through the jax coordination
  service (key-value store, NOT a collective — a missing peer times out
  loudly instead of hanging) at ``dist.initialize()`` and after any
  post-warmup recompile. A mismatch — or a peer that never shows up,
  which IS the divergence — writes one flight bundle and raises
  ``MXNetError`` instead of letting the pod wedge.

Contract: **off by default, near-zero when off** — every hook is one
env-cached boolean read when ``MXTPU_COLLECTIVE_LEDGER`` is unset.
Banking re-traces the step (no XLA compile) only when enabled, and only
once per new signature — build-time cost, never per-step cost.

Chaos hook: the seeded ``collective_divergence`` knob
(``fault.inject``) perturbs THIS process's digest table with a value
folded over ``process_index()`` just before the exchange, so any
>=2-process crosscheck with the knob fired must trip — the end-to-end
drill ``tools/collective_smoke.py`` and the CI crosscheck smoke run.

Env knobs (catalogued in ``util.ENV_VARS`` / docs/env_vars.md):
``MXTPU_COLLECTIVE_LEDGER`` (master switch),
``MXTPU_COLLECTIVE_LEDGER_RING`` (dispatch ring size),
``MXTPU_COLLECTIVE_LEDGER_TIMEOUT_S`` (peer exchange timeout).
"""
from __future__ import annotations

import hashlib
import time
import warnings
from collections import deque
from typing import Dict, List, Optional

from ..base import MXNetError
from ..lockcheck import make_lock

__all__ = ["enabled", "fingerprint", "bank", "bank_graph", "bank_closed",
           "bank_trainer",
           "banked", "digest_table", "note_dispatch", "schedule_ring",
           "crosscheck", "CollectiveMismatchError", "snapshot", "reset"]

_LOCK = make_lock("collective_ledger._LOCK")
#: (site, signature) -> fingerprint dict (with its "digest" filled in)
_BANKED: Dict[tuple, Dict] = {}
_RING: Optional[deque] = None
_DISPATCHES: Dict[str, int] = {}
#: crosscheck bookkeeping: per-tag epoch counters (the exchange keys
#: must match across processes, so they derive from call order — a
#: process whose call order diverges times out, which IS the finding)
_EPOCHS: Dict[str, int] = {}
_STATS = {"crosschecks": 0, "mismatches": 0, "last": None}
_TRIPPED = [False]


class CollectiveMismatchError(MXNetError):
    """Raised when the cross-process fingerprint exchange disagrees.

    A peer that never publishes raises too: the pod was about to
    diverge inside a collective — die loudly with evidence instead of
    hanging."""


def enabled() -> bool:
    """True when ``MXTPU_COLLECTIVE_LEDGER`` is 1/true/on/yes."""
    from ..util import getenv
    return str(getenv("MXTPU_COLLECTIVE_LEDGER") or "0").lower() \
        in ("1", "true", "on", "yes")


def _ring() -> deque:
    global _RING
    if _RING is None:
        from ..util import getenv
        try:
            n = int(getenv("MXTPU_COLLECTIVE_LEDGER_RING"))
        except (TypeError, ValueError):
            n = 512
        _RING = deque(maxlen=max(16, n))
    return _RING


def _timeout_s() -> float:
    from ..util import getenv
    try:
        return float(getenv("MXTPU_COLLECTIVE_LEDGER_TIMEOUT_S"))
    except (TypeError, ValueError):
        return 20.0


def _sig_key(signature) -> str:
    return repr(signature)[:300]


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

def fingerprint(schedule: List[str], collective_ops: Dict[str, int],
                comm_bytes: float, signature, mesh_axes=None) -> Dict:
    """One build's collective fingerprint, ``digest`` included.

    The fingerprint carries the ordered explicit schedule, the verb
    counts, per-device comm bytes, the triggering signature, and the
    mesh axes. The ordered explicit schedule comes from the same extractor MX905
    uses; the cost model's verb counts include the implied SPMD gradient
    exchange the jaxpr cannot show. The digest is a sha1 over the
    strict-JSON canonical form — the only thing the exchange ships."""
    from .export import dumps_strict
    if isinstance(mesh_axes, dict):
        axes = [f"{k}={v}" for k, v in sorted(mesh_axes.items())]
    else:
        axes = [str(a) for a in (mesh_axes or ())]
    doc = {"schedule": list(schedule),
           "collective_ops": {k: int(v)
                              for k, v in sorted(collective_ops.items())},
           "comm_bytes": int(comm_bytes),
           "signature": _sig_key(signature),
           "mesh_axes": axes}
    doc["digest"] = hashlib.sha1(
        dumps_strict(doc, sort_keys=True).encode()).hexdigest()
    return doc


def fingerprint_of_graph(g) -> Dict:
    """Fingerprint one :class:`~...analysis.hlo.trace.TracedGraph`."""
    from ..analysis.distributed.schedule import schedule_of
    from ..analysis.hlo.cost import graph_cost
    c = graph_cost(g)
    return fingerprint(schedule_of(g.closed), c.collective_ops,
                       c.comm_bytes, g.signature, g.mesh_axes)


def bank(site: str, signature, fp: Dict) -> None:
    """Bank one build's fingerprint under ``(site, signature)``.

    When this is a POST-WARMUP recompile in a multi-process run it also
    crosschecks immediately — a late recompile only one host performs
    is the classic divergence onset, and the exchange timeout catches
    exactly that."""
    if not enabled():
        return
    key = (site, _sig_key(signature))
    rebank = False
    with _LOCK:
        prev = _BANKED.get(key)
        rebank = prev is not None and prev.get("digest") != fp.get("digest")
        _BANKED[key] = dict(fp)
    from . import events as _events
    from . import metrics as _metrics
    _events.emit("collective.bank", site=site, signature=_sig_key(signature),
                 digest=fp.get("digest"), rebank=rebank,
                 collectives=sum(fp.get("collective_ops", {}).values()))
    _metrics.counter("mxtpu_collective_banked_total",
                     "Collective-schedule fingerprints banked",
                     site=site).inc()
    from . import compile_log
    if compile_log.is_warmed(site) and _num_processes() > 1:
        crosscheck(f"recompile/{site}")


def bank_graph(site: str, g) -> Optional[Dict]:
    """Fingerprint + bank one traced graph (no XLA compile).

    Returns the fingerprint, or None when the ledger is off or tracing
    failed — banking must never become the fault that breaks a step."""
    if not enabled():
        return None
    try:
        fp = fingerprint_of_graph(g)
    except Exception as e:  # noqa: BLE001 — diagnostics never break builds
        warnings.warn(f"[collective_ledger] could not fingerprint "
                      f"{site}: {type(e).__name__}: {e}")
        return None
    bank(site, g.signature, fp)
    return fp


def bank_closed(site: str, closed, signature, mesh_axes=None
                ) -> Optional[Dict]:
    """Fingerprint + bank one (closed) jaxpr — the serving tier's hook.

    Here the build hands us the traced program directly and the cost
    model's per-graph accounting is not in play (verb counts derive from
    the schedule itself; comm bytes are not part of the serve digest)."""
    if not enabled():
        return None
    try:
        from ..analysis.distributed.schedule import schedule_of
        sched = schedule_of(closed)
        counts: Dict[str, int] = {}
        for entry in sched:
            verb = entry.split("@", 1)[0]
            counts[verb] = counts.get(verb, 0) + 1
        fp = fingerprint(sched, counts, 0, signature, mesh_axes)
    except Exception as e:  # noqa: BLE001 — diagnostics never break builds
        warnings.warn(f"[collective_ledger] could not fingerprint "
                      f"{site}: {type(e).__name__}: {e}")
        return None
    bank(site, signature, fp)
    return fp


def bank_trainer(trainer, batch_vals) -> Optional[Dict]:
    """Trace + fingerprint + bank a ShardedTrainer's step graph.

    Called by ``trainer.step`` on each NEW batch signature when the
    ledger is on. Pure tracing — no XLA compile."""
    if not enabled():
        return None
    try:
        from ..analysis.hlo.trace import _trace_trainer
        res = _trace_trainer(trainer, tuple(batch_vals))
        g = res.graphs[0]
    except Exception as e:  # noqa: BLE001 — diagnostics never break steps
        warnings.warn(f"[collective_ledger] could not trace trainer step: "
                      f"{type(e).__name__}: {e}")
        return None
    return bank_graph("trainer.step", g)


def banked() -> Dict[str, Dict[str, Dict]]:
    """Snapshot: ``{site: {signature: fingerprint}}``."""
    with _LOCK:
        out: Dict[str, Dict[str, Dict]] = {}
        for (site, sig), fp in _BANKED.items():
            out.setdefault(site, {})[sig] = dict(fp)
        return out


def digest_table() -> List[List[str]]:
    """The exchange payload: sorted ``[site, signature, digest]`` rows.

    Small and stable; the exchange never ships the schedules
    themselves."""
    with _LOCK:
        return sorted([site, sig, fp.get("digest", "")]
                      for (site, sig), fp in _BANKED.items())


# ---------------------------------------------------------------------------
# dispatch ring
# ---------------------------------------------------------------------------

def note_dispatch(site: str, signature) -> None:
    """Append one executed dispatch to the bounded schedule ring.

    Cheap: one deque append; no tracing, no hashing."""
    if not enabled():
        return
    sig = _sig_key(signature)
    with _LOCK:
        _ring().append({"site": site, "signature": sig,
                        "ts": round(time.time(), 6)})
        _DISPATCHES[site] = _DISPATCHES.get(site, 0) + 1


def schedule_ring() -> List[Dict]:
    """The dispatch ring, oldest first (a copy)."""
    with _LOCK:
        return [] if _RING is None else list(_RING)


# ---------------------------------------------------------------------------
# the cross-process exchange
# ---------------------------------------------------------------------------

def _coord():
    """(client, process_index, num_processes) from the jax coordination
    service WITHOUT initializing any backend — ``(None, 0, 1)`` when the
    process never rendezvoused (single-host runs, unit tests)."""
    try:
        from jax._src.distributed import global_state
        client = getattr(global_state, "client", None)
        if client is None:
            return None, 0, 1
        return (client, int(global_state.process_id or 0),
                int(global_state.num_processes or 1))
    except Exception:  # noqa: BLE001 — jax version drift degrades to off
        return None, 0, 1


def _num_processes() -> int:
    return _coord()[2]


def _trip(tag: str, reason: str, detail: str, **ctx) -> None:
    """The mismatch path: one flight bundle per process lifetime, a
    telemetry event, a counter, then the loud raise — a wrong pod must
    die with evidence, not hang without any."""
    from . import events as _events
    from . import flight as _flight
    from . import metrics as _metrics
    with _LOCK:
        _STATS["mismatches"] += 1
        _STATS["last"] = {"tag": tag, "ok": False, "reason": reason}
        first = not _TRIPPED[0]
        _TRIPPED[0] = True
    _events.emit("collective.mismatch", severity="error", tag=tag,
                 reason=reason)
    _metrics.counter("mxtpu_collective_mismatch_total",
                     "Collective-schedule crosscheck trips",
                     reason=reason).inc()
    if first:
        _flight.dump("collective_schedule_mismatch", site=tag,
                     reason=reason, **ctx)
    raise CollectiveMismatchError(
        f"collective-schedule crosscheck failed at {tag!r} ({reason}): "
        f"{detail}\nThis pod would have hung inside a collective; "
        "raising instead. A flight bundle with the local schedule "
        "ledger was written (MXTPU_FLIGHT_DIR).")


def _diff_tables(mine: List, theirs: List) -> str:
    a = {tuple(r[:2]): r[2] for r in mine}
    b = {tuple(r[:2]): r[2] for r in theirs}
    lines = []
    for key in sorted(set(a) | set(b)):
        da, db = a.get(key), b.get(key)
        if da == db:
            continue
        site, sig = key
        lines.append(f"  {site} {sig}: local={da or '(unbanked)'} "
                     f"peer={db or '(unbanked)'}")
    return "\n".join(lines) or "  (tables differ only in chaos salt)"


def crosscheck(tag: str = "manual", peers: Optional[List[str]] = None,
               timeout_s: Optional[float] = None) -> Dict:
    """Exchange the banked digest table across the pod; raise on drift.

    ``peers`` injects peer payloads directly (unit tests); otherwise the
    jax coordination service's key-value store carries the exchange —
    deliberately NOT a collective, so a peer that never reaches this
    call (divergent control flow: the very bug being checked) turns
    into a loud timeout instead of a silent hang.

    Returns ``{"checked": bool, ...}``; raises
    :class:`CollectiveMismatchError` (an ``MXNetError``) on any
    mismatch, absent peer, or chaos-perturbed digest, after writing one
    flight bundle."""
    if not enabled():
        return {"checked": False, "reason": "disabled"}
    from .export import dumps_strict, loads_strict
    table = digest_table()
    blob = dumps_strict(table, sort_keys=True)
    # the seeded divergence drill: fold THIS process's identity into the
    # payload so every >=2-process exchange with the knob fired differs
    from ..fault import inject as _inject
    client, idx, n = _coord()
    if _inject.should("collective_divergence"):
        blob = dumps_strict({"table": table,
                             "chaos": f"divergence-p{idx}"},
                            sort_keys=True)
    with _LOCK:
        _STATS["crosschecks"] += 1
        epoch = _EPOCHS[tag] = _EPOCHS.get(tag, 0) + 1
    if peers is not None:
        for i, peer_blob in enumerate(peers):
            if peer_blob != blob:
                theirs = loads_strict(peer_blob)
                theirs = theirs["table"] if isinstance(theirs, dict) \
                    else theirs
                _trip(tag, "digest_mismatch",
                      f"peer #{i} banked a different collective "
                      f"schedule:\n{_diff_tables(table, theirs)}",
                      peer=i, local_table=table, peer_table=theirs)
        with _LOCK:
            _STATS["last"] = {"tag": tag, "ok": True,
                              "peers": len(peers)}
        return {"checked": True, "processes": len(peers) + 1,
                "entries": len(table)}
    if client is None or n <= 1:
        with _LOCK:
            _STATS["last"] = {"tag": tag, "ok": True,
                              "reason": "single_process"}
        return {"checked": False, "reason": "single_process"}
    timeout_ms = int((_timeout_s() if timeout_s is None
                      else timeout_s) * 1000)
    prefix = f"mxtpu/collective_ledger/{tag}/{epoch}"
    try:
        client.key_value_set(f"{prefix}/{idx}", blob)
    except Exception as e:  # noqa: BLE001 — coordination infra drift
        warnings.warn(f"[collective_ledger] crosscheck {tag!r}: "
                      f"key_value_set failed: {e}")
        return {"checked": False, "reason": "kv_set_failed"}
    for p in range(n):
        if p == idx:
            continue
        try:
            peer_blob = client.blocking_key_value_get(
                f"{prefix}/{p}", timeout_ms)
        except Exception:
            _trip(tag, "peer_timeout",
                  f"process {p} never published its fingerprint table "
                  f"within {timeout_ms} ms — it did not reach this "
                  f"crosscheck (tag {tag!r}, epoch {epoch}): divergent "
                  "control flow or a wedged host",
                  peer=p, local_table=table)
        if peer_blob != blob:
            theirs = loads_strict(peer_blob)
            theirs = theirs["table"] if isinstance(theirs, dict) else theirs
            _trip(tag, "digest_mismatch",
                  f"process {p} banked a different collective "
                  f"schedule:\n{_diff_tables(table, theirs)}",
                  peer=p, local_table=table, peer_table=theirs)
    from . import events as _events
    _events.emit("collective.crosscheck", tag=tag, processes=n,
                 entries=len(table))
    with _LOCK:
        _STATS["last"] = {"tag": tag, "ok": True, "processes": n}
    return {"checked": True, "processes": n, "entries": len(table)}


# ---------------------------------------------------------------------------
# snapshot / reset
# ---------------------------------------------------------------------------

def snapshot() -> Dict:
    """The ledger's flight-bundle / ``telemetry.snapshot()`` section."""
    on = enabled()  # env read outside the lock
    with _LOCK:
        ring = [] if _RING is None else list(_RING)
        return {"enabled": on,
                "banked": {f"{site}|{sig}": dict(fp)
                           for (site, sig), fp in sorted(_BANKED.items())},
                "dispatches": dict(_DISPATCHES),
                "ring": ring[-64:],
                "crosschecks": dict(_STATS, last=_STATS["last"])}


def reset() -> None:
    """Clear every ledger surface (tests; ``telemetry.reset()``)."""
    global _RING
    with _LOCK:
        _BANKED.clear()
        _DISPATCHES.clear()
        _EPOCHS.clear()
        _RING = None
        _STATS["crosschecks"] = _STATS["mismatches"] = 0
        _STATS["last"] = None
        _TRIPPED[0] = False

"""Distributed tracing — W3C-style trace/span context for the whole tier.

Reference counterpart: none — the reference correlated nothing across its
process boundaries (ps-lite hops, the MMS frontend) and debugging a slow
request meant grepping per-process logs. PRs 4/6 gave this repo a
correlated event bus and a span-recording profiler, but correlation still
dies at every boundary: the router's failover/hedge attempts, the TCP
front end, and the kvstore client→PS-server hop each emit events that
cannot be stitched into one causal story. This module closes that gap the
way OpenTelemetry does: a ``(trace_id, span_id, sampled)`` context rides
a thread-local stack, child spans record their parent, and the context
crosses the wire as a small JSON object — so one sampled request renders
as ONE rooted span tree: request → router attempt → replica batcher →
CompiledModel pad/compute/unpad, hedges as sibling attempts under one
parent (the PyGraph position — attribute overhead AT the boundary —
applied to cross-process boundaries instead of graph launches).

Mechanics:

- :func:`span` opens a child of the current context (or a NEW trace when
  none is active); :func:`use` activates a carried context (a wire hop, a
  batcher worker resuming a request's context) without recording a span.
- **Head sampling**: the root draw (``MXTPU_TRACE_SAMPLE``, default 0.1)
  decides once per trace; unsampled traces still propagate ids (cheap —
  no ring writes anywhere downstream), so always-on tracing costs two
  thread-local reads per span on the unsampled path — the serve_bench
  tracing-overhead gate holds the p50 tax at the default rate under 3%.
  CI's trace-smoke job sets 1.0 so the stitching gate sees every
  request.
- Completed spans land in one bounded process ring
  (``MXTPU_TRACE_RING``); :func:`export.otel_spans` renders it, and
  :func:`tree`/:func:`orphans` stitch it — the ``trace-smoke`` CI gate is
  "every sampled request yields a single rooted tree, zero orphans".
- The event bus stamps every event with the active context, and
  ``profiler.Scope``/``Frame`` open trace spans when a sampled context is
  active, so the profiler's wall-time story and the trace tree are one
  structure (``SpanRecord.trace`` carries the ids into chrome_trace).
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..lockcheck import make_lock

__all__ = ["SpanContext", "Span", "current", "start_span", "span", "use",
           "to_wire", "from_wire", "spans", "clear", "trace_ids", "tree",
           "orphans", "sample_rate", "set_sample_rate", "summary"]

#: wall-clock anchor shared with the profiler's idea of "one clock":
#: every span timestamp is _EPOCH + perf_counter(), so trace spans and
#: profiler spans compare exactly on a merged timeline
_EPOCH = time.time() - time.perf_counter()

_TLS = threading.local()        # .stack: [SpanContext, ...]; .ids: counter

_LOCK = make_lock("trace._LOCK")
_RING: Optional[list] = None    # built lazily (deque) — see _ring()
_SAMPLE_OVERRIDE: Optional[float] = None
_RATE_CACHE: Optional[float] = None
_current_request = _current_step = None  # lazy events accessors (cycle)


class SpanContext(tuple):
    """Immutable ``(trace_id, span_id, sampled)`` — the propagated unit.
    A tuple subclass so contexts are hashable, comparable, and free to
    copy across threads."""

    __slots__ = ()

    def __new__(cls, trace_id: str, span_id: str, sampled: bool):
        return tuple.__new__(cls, (trace_id, span_id, bool(sampled)))

    @property
    def trace_id(self) -> str:
        return self[0]

    @property
    def span_id(self) -> str:
        return self[1]

    @property
    def sampled(self) -> bool:
        return self[2]

    def __repr__(self):
        bit = "sampled" if self.sampled else "unsampled"
        return f"SpanContext({self.trace_id}/{self.span_id}, {bit})"


# -- id generation (hot path: no os.urandom per span) ------------------------
def _next_span_id() -> str:
    """64-bit hex span id: a per-thread random base + counter, so ids are
    unique across threads without a syscall or lock per span."""
    base = getattr(_TLS, "id_base", None)
    if base is None:
        base = _TLS.id_base = int.from_bytes(os.urandom(8), "big") or 1
        _TLS.id_n = 0
    _TLS.id_n += 1
    return format((base + _TLS.id_n) & 0xFFFFFFFFFFFFFFFF, "016x")


def _new_trace_id() -> str:
    return os.urandom(16).hex()


# -- sampling ----------------------------------------------------------------
def sample_rate() -> float:
    """Head-sampling probability for NEW traces (``MXTPU_TRACE_SAMPLE``,
    cached; :func:`set_sample_rate` overrides)."""
    global _RATE_CACHE
    if _SAMPLE_OVERRIDE is not None:
        return _SAMPLE_OVERRIDE
    if _RATE_CACHE is None:
        from ..util import getenv
        try:
            _RATE_CACHE = min(1.0, max(0.0,
                                       float(getenv("MXTPU_TRACE_SAMPLE"))))
        except (TypeError, ValueError):
            _RATE_CACHE = 0.1
    return _RATE_CACHE


def set_sample_rate(rate: Optional[float]) -> None:
    """Programmatic override (``None`` re-reads the env) — tests and the
    serve_bench tracing-overhead A/B use this."""
    global _SAMPLE_OVERRIDE, _RATE_CACHE
    _SAMPLE_OVERRIDE = None if rate is None else min(1.0, max(0.0,
                                                              float(rate)))
    _RATE_CACHE = None


def _draw_sampled() -> bool:
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return random.random() < rate


# -- the ring ----------------------------------------------------------------
def _ring():
    global _RING
    if _RING is None:
        with _LOCK:
            if _RING is None:
                from collections import deque
                from ..util import getenv
                try:
                    cap = int(getenv("MXTPU_TRACE_RING"))
                except (TypeError, ValueError):
                    cap = 65536
                _RING = deque(maxlen=max(cap, 16))
    return _RING


# -- context stack -----------------------------------------------------------
def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def current() -> Optional[SpanContext]:
    """The active span context on this thread (None = no trace)."""
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


def _push(ctx: SpanContext) -> None:
    _stack().append(ctx)


def _pop(ctx: SpanContext) -> None:
    st = _stack()
    if st and st[-1] is ctx:
        st.pop()
    elif ctx in st:          # exotic unwind order: remove the right entry
        st.remove(ctx)


class Span:
    """One in-flight span. Create via :func:`start_span` (manual finish —
    the batcher holds a request's span across threads) or :func:`span`
    (scoped). ``finish`` is idempotent; unsampled spans skip the ring."""

    __slots__ = ("ctx", "parent_id", "name", "kind", "attrs", "_t0",
                 "_done")

    def __init__(self, ctx: SpanContext, parent_id: Optional[str],
                 name: str, kind: str, attrs: Dict):
        self.ctx = ctx
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.attrs = attrs
        self._t0 = time.perf_counter()
        self._done = False

    def finish(self, **attrs) -> None:
        if self._done:
            return
        self._done = True
        if not self.ctx.sampled:
            return
        if attrs:
            self.attrs.update(attrs)
        rec = {"trace_id": self.ctx.trace_id,
               "span_id": self.ctx.span_id,
               "parent_id": self.parent_id,
               "name": self.name, "kind": self.kind,
               "ts": _EPOCH + self._t0,
               "dur_ms": round((time.perf_counter() - self._t0) * 1e3, 4),
               "thread": threading.current_thread().name}
        # step/request correlation rides on the span like on every event
        # (module-global accessors: finish() is the sampled hot path)
        global _current_request, _current_step
        if _current_step is None:
            from .events import current_request, current_step
            _current_request, _current_step = current_request, current_step
        current_request, current_step = _current_request, _current_step
        step = self.attrs.pop("step", None)
        if step is None:
            step = current_step()
        if step is not None:
            rec["step"] = step
        rid = current_request()
        if rid is not None:
            rec["request_id"] = rid
        if self.attrs:
            rec["attrs"] = dict(self.attrs)
        ring = _ring()   # resolved OUTSIDE the lock (_ring takes it too)
        with _LOCK:
            ring.append(rec)

    def __repr__(self):
        state = "open" if not self._done else "finished"
        return f"Span({self.name!r}, {self.ctx.span_id}, {state})"


def start_span(name: str, kind: str = "internal",
               parent: Optional[SpanContext] = None, **attrs) -> Span:
    """Open one span WITHOUT activating it (caller owns ``finish()``).
    ``parent`` defaults to the thread's current context; with neither, a
    new trace starts and the head-sampling draw happens here."""
    if parent is None:
        parent = current()
    if parent is None:
        ctx = SpanContext(_new_trace_id(), _next_span_id(), _draw_sampled())
        parent_id = None
    else:
        ctx = SpanContext(parent.trace_id, _next_span_id(), parent.sampled)
        parent_id = parent.span_id
    return Span(ctx, parent_id, name, kind, attrs)


class span:
    """Scoped span: opens a child of the current context, activates it
    for the block, records it on exit (an exception lands in ``attrs``)::

        with trace.span("router.request", model=name) as sp:
            ...  # events + nested profiler scopes join sp's trace
    """

    def __init__(self, name: str, kind: str = "internal",
                 parent: Optional[SpanContext] = None, **attrs):
        self._sp = start_span(name, kind=kind, parent=parent, **attrs)

    def __enter__(self) -> Span:
        _push(self._sp.ctx)
        return self._sp

    def __exit__(self, exc_type, exc, tb):
        _pop(self._sp.ctx)
        if exc_type is not None:
            self._sp.finish(error=exc_type.__name__)
        else:
            self._sp.finish()


class use:
    """Activate a carried context (wire hop, cross-thread resume) for the
    block — no span is recorded, children parent under it. A ``None``
    context is a no-op, so call sites need no conditional."""

    def __init__(self, ctx: Optional[SpanContext]):
        self._ctx = ctx

    def __enter__(self):
        if self._ctx is not None:
            _push(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        if self._ctx is not None:
            _pop(self._ctx)


# -- wire form ---------------------------------------------------------------
def to_wire(ctx: Optional[SpanContext] = None) -> Optional[Dict]:
    """The JSON-safe carried form (``None`` when no context is active) —
    the TCP front end's optional ``trace`` field and the kvstore message
    meta both carry exactly this."""
    if ctx is None:
        ctx = current()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id,
            "sampled": ctx.sampled}


def from_wire(obj) -> Optional[SpanContext]:
    """Parse a carried context; malformed input yields None (a bad peer
    must degrade to an untraced request, never an error)."""
    if not isinstance(obj, dict):
        return None
    tid, sid = obj.get("trace_id"), obj.get("span_id")
    if not (isinstance(tid, str) and isinstance(sid, str) and tid and sid):
        return None
    return SpanContext(tid, sid, bool(obj.get("sampled", True)))


# -- inspection / stitching --------------------------------------------------
def spans(trace_id: Optional[str] = None) -> List[Dict]:
    """Completed span records, oldest first (bounded ring)."""
    ring = _ring()
    with _LOCK:
        out = list(ring)
    if trace_id is not None:
        out = [r for r in out if r["trace_id"] == trace_id]
    return out


def clear() -> None:
    ring = _RING
    if ring is not None:
        with _LOCK:
            ring.clear()


def trace_ids() -> List[str]:
    """Distinct trace ids in the ring, in first-seen order."""
    seen, out = set(), []
    for r in spans():
        t = r["trace_id"]
        if t not in seen:
            seen.add(t)
            out.append(t)
    return out


def tree(trace_id: str) -> Optional[Dict]:
    """Stitch one trace into a nested dict ``{span, children: [...]}``.
    Returns None for an unknown trace; raises nothing on malformed data —
    orphans (parent missing from the ring) are surfaced by
    :func:`orphans`, not silently grafted."""
    recs = spans(trace_id)
    if not recs:
        return None
    by_id = {r["span_id"]: {"span": r, "children": []} for r in recs}
    roots = []
    for r in recs:
        node = by_id[r["span_id"]]
        pid = r.get("parent_id")
        if pid and pid in by_id:
            by_id[pid]["children"].append(node)
        else:
            roots.append(node)
    if len(roots) == 1:
        return roots[0]
    return {"span": {"trace_id": trace_id, "name": "<forest>",
                     "roots": len(roots)},
            "children": roots}


def orphans(records: Optional[List[Dict]] = None) -> List[Dict]:
    """Spans whose ``parent_id`` is set but absent from their trace — the
    stitching failures the rooted-trace CI gate counts. A span parented
    on a still-open (never-finished) span is an orphan too: an
    un-finished parent is exactly the evidence loss the gate exists to
    catch."""
    if records is None:
        records = spans()
    by_trace: Dict[str, set] = {}
    for r in records:
        by_trace.setdefault(r["trace_id"], set()).add(r["span_id"])
    return [r for r in records
            if r.get("parent_id")
            and r["parent_id"] not in by_trace[r["trace_id"]]]


def summary() -> Dict:
    """One-line stitching health: span/trace/root/orphan counts — inlined
    into ``telemetry.snapshot()`` and the flight-recorder bundle. One
    ring copy + one id-set pass (snapshot is polled over the wire, and
    the ring can hold 64Ki spans — :func:`orphans` would rebuild the
    same per-trace sets a second time)."""
    recs = spans()
    roots = 0
    by_trace: Dict[str, set] = {}
    for r in recs:
        by_trace.setdefault(r["trace_id"], set()).add(r["span_id"])
        if not r.get("parent_id"):
            roots += 1
    orphan_n = sum(1 for r in recs
                   if r.get("parent_id")
                   and r["parent_id"] not in by_trace[r["trace_id"]])
    return {"spans": len(recs), "traces": len(by_trace),
            "roots": roots, "orphans": orphan_n,
            "sample_rate": sample_rate()}

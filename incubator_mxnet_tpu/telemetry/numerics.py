"""In-graph numerics observability — per-site tensor statistics.

Reference counterpart: ``python/mxnet/monitor.py`` — the reference's
Monitor re-executed a *second* capture program per monitored batch and
pulled every intermediate to host. On a jit runtime that design is
doubly wrong: a second executable violates the whole-step-capture
contract (PR 11's one-donated-pjit-step invariant, PyGraph's
capture-once argument), and per-step host callbacks inside the graph
are exactly the MX701/MX708 anti-pattern. This module does it the
TPU-native way:

- statistics are **computed in-graph** — ``summary_stats`` /
  ``hist_counts`` are ordinary traceable reductions whose results ride
  out of the SAME jitted graph as a few extra pinned replicated scalar
  outputs (the step stays ONE executable; the compile ledger and
  MX704/MX708 stay clean with stats on, tested);
- the host **decimates**: stat outputs are device arrays the host only
  syncs every ``MXTPU_NUMERICS_EVERY`` steps (default 16), folded into
  the step's existing single host sync (the guard's loss/grad-norm
  read) — never an extra per-step device round trip;
- recorded samples land in ``numerics.step`` events, ``mxtpu_numerics_*``
  gauges, and a bounded per-site history ring — the raw material of the
  **drift watchdog**: monotonic rms growth or finite-fraction decay
  across the ring emits damped ``numerics.drift`` warnings *before* the
  run ever produces a non-finite value, and (``MXTPU_NUMERICS_DRIFT=
  rollback``) can arm the existing ``fault.StepGuard`` escalation;
- ``hist`` mode additionally accumulates in-graph log2-magnitude
  histograms per site, exported via :func:`calibration_table` as
  ``quantization.Observer`` calibration tables — the int8 pipeline's
  range data (ROADMAP item 4) collected from live traffic for free.

Sites are named strings: the trainer publishes ``param:<name>`` /
``grad:<name>`` per parameter, models tag activations explicitly with
:func:`tap` (``act:<name>``), and ``serve.CompiledModel`` publishes
``serve.out:<i>`` per output. ``MXTPU_NUMERICS_SITES`` is an fnmatch
allowlist over those names (empty = all), so a 300-parameter model can
watch just ``grad:*attn*``.

Everything is **off by default** (``MXTPU_NUMERICS`` unset): the traced
graphs are bit-identical to a build that never imported this module —
the perf-proxy CI gate proves banked PERF_PROXY.json stays byte-equal.

Usage::

    MXTPU_NUMERICS=summary MXTPU_NUMERICS_EVERY=8 python train.py

    # inside a model: tag an activation (identity; collected at trace time)
    from incubator_mxnet_tpu.telemetry import numerics
    h = numerics.tap("encoder_out", h)

    # after a hist-mode run: export calibration for int8 quantization
    from incubator_mxnet_tpu import quantization
    obs = quantization.Observer(numerics.calibration_table())
    obs.ranges()          # {"act:encoder_out": (-3.1, 3.1), ...}
"""
from __future__ import annotations

import fnmatch
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..lockcheck import make_lock

__all__ = ["NumericsConfig", "config", "configure", "tap", "collecting",
           "summary_stats", "hist_counts", "graph_stats", "record",
           "rings", "ring", "drift_state", "calibration_table",
           "snapshot", "reset",
           "STAT_FIELDS", "HIST_LO_EXP", "MODES"]

MODES = ("summary", "hist")

#: layout of the (6,) summary-stat vector every site publishes
STAT_FIELDS = ("min", "max", "mean", "rms", "zero_fraction",
               "finite_fraction")

#: histogram bucket i counts |x| in [2^(LO+i), 2^(LO+i+1)); underflows
#: clamp into bucket 0, overflows into the last — fixed edges, so the
#: in-graph computation is trace-safe (no data-dependent shapes)
HIST_LO_EXP = -24

_LOCK = make_lock("numerics._LOCK")
_CONFIG_OVERRIDE: Optional["NumericsConfig"] = None
#: "<scope>/<site>" -> deque of {"step": int, "min": ..., ...} host
#: records. Keys carry the recording scope ("trainer.step",
#: "serve.compiled") so a trainer and a server sharing tap names can
#: never interleave into one drift window — the monotonicity evidence
#: stays per recording stream. (Two trainers with IDENTICAL explicit
#: gluon prefixes still share keys; auto-incremented prefixes make
#: parameter names process-unique, so that needs deliberate aliasing.)
_RINGS: Dict[str, deque] = {}
#: per-key drift damping: key -> {"rms_level": float|None,
#: "ff_level": float|None}
_DRIFT: Dict[str, Dict[str, Any]] = {}
#: hist-mode calibration accumulation: key -> {"counts": [floats],
#: "lo_exp": int, "min": float, "max": float, "samples": int}
_CALIB: Dict[str, Dict[str, Any]] = {}
#: the config most recently used to record — what snapshot()/bundles
#: report, so a ctor-configured trainer's postmortem header reflects
#: the build that actually recorded, not the (possibly unset) env
_LAST_CFG: List[Optional["NumericsConfig"]] = [None]


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class NumericsConfig:
    """One resolved numerics-telemetry configuration. Builders
    (``ShardedTrainer._build_step``, ``serve.CompiledModel``) resolve it
    ONCE at build time — flipping the env mid-run does not re-trace a
    compiled step."""

    #: None = off; "summary" = the (6,) stat vector per site; "hist" =
    #: summary + log2-magnitude histogram per site
    mode: Optional[str] = None
    #: host-side decimation: sync + record stats every N steps/requests
    every: int = 16
    #: fnmatch allowlist over site names; empty = every site
    sites: Tuple[str, ...] = ()
    #: log2-magnitude histogram buckets (hist mode)
    bins: int = 40
    #: per-site history-ring capacity
    ring: int = 128
    #: drift-watchdog action: "warn" emits events only; "rollback" also
    #: escalates a sustained drift to the trainer's StepGuard (its
    #: policy decides warn/skip_and_rollback/halt)
    drift_action: str = "warn"
    #: recorded samples the drift verdict needs (monotonic across all)
    drift_window: int = 4
    #: rms growth factor across the window that counts as drift
    drift_ratio: float = 4.0

    @property
    def enabled(self) -> bool:
        return self.mode in MODES

    @property
    def hist(self) -> bool:
        return self.mode == "hist"

    def wants(self, site: str) -> bool:
        """Allowlist check (empty allowlist admits every site)."""
        if not self.sites:
            return True
        return any(fnmatch.fnmatchcase(site, pat) for pat in self.sites)

    @classmethod
    def from_env(cls) -> "NumericsConfig":
        from ..util import getenv
        raw = (getenv("MXTPU_NUMERICS") or "").strip().lower()
        mode = raw if raw in MODES else None

        def _int(name: str, default: int) -> int:
            try:
                return max(1, int(getenv(name) or default))
            except (TypeError, ValueError):
                return default

        sites = tuple(p.strip() for p in
                      (getenv("MXTPU_NUMERICS_SITES") or "").split(",")
                      if p.strip())
        action = (getenv("MXTPU_NUMERICS_DRIFT") or "warn").strip().lower()
        if action not in ("warn", "rollback"):
            action = "warn"
        return cls(mode=mode,
                   every=_int("MXTPU_NUMERICS_EVERY", 16),
                   sites=sites,
                   bins=_int("MXTPU_NUMERICS_BINS", 40),
                   ring=_int("MXTPU_NUMERICS_RING", 128),
                   drift_action=action)


def config() -> NumericsConfig:
    """The active configuration: a :func:`configure` override, else the
    environment (parsed fresh — builders cache the result themselves)."""
    return _CONFIG_OVERRIDE if _CONFIG_OVERRIDE is not None \
        else NumericsConfig.from_env()


def configure(cfg: Optional[NumericsConfig]) -> None:
    """Programmatic override of the env config (tests, the Monitor
    bridge). ``None`` restores env resolution. Only builds that happen
    AFTER the call see it."""
    global _CONFIG_OVERRIDE
    _CONFIG_OVERRIDE = cfg


# ---------------------------------------------------------------------------
# in-graph statistics (traceable; these run INSIDE the jitted step)
# ---------------------------------------------------------------------------

def summary_stats(x):
    """The (6,) f32 stat vector of one tensor — ``STAT_FIELDS`` order —
    as ordinary XLA reductions (traceable; NaN/inf-safe: min/max/mean/
    rms reduce over the finite entries only, so a poisoned tensor still
    reports the magnitude story of its healthy part next to its
    ``finite_fraction``)."""
    import jax.numpy as jnp
    v = getattr(x, "_data", x)
    f = jnp.ravel(v).astype(jnp.float32)
    n = max(int(f.size), 1)
    finite = jnp.isfinite(f)
    nfin = jnp.sum(finite)
    denom = jnp.maximum(nfin, 1).astype(jnp.float32)
    safe = jnp.where(finite, f, 0.0)
    mean = jnp.sum(safe) / denom
    rms = jnp.sqrt(jnp.sum(safe * safe) / denom)
    mn = jnp.min(jnp.where(finite, f, jnp.inf))
    mx = jnp.max(jnp.where(finite, f, -jnp.inf))
    zero = jnp.sum(jnp.logical_and(finite, f == 0.0)).astype(jnp.float32)
    return jnp.stack([mn, mx, mean, rms, zero / n,
                      nfin.astype(jnp.float32) / n])


def hist_counts(x, bins: int):
    """Log2-magnitude histogram of one tensor: bucket ``i`` counts the
    finite non-zero entries with ``|x|`` in ``[2^(LO+i), 2^(LO+i+1))``
    (``LO`` = :data:`HIST_LO_EXP`; under/overflows clamp into the edge
    buckets). Fixed edges make it traceable AND mergeable across steps
    — the calibration accumulator just adds counts."""
    import jax.numpy as jnp
    v = getattr(x, "_data", x)
    f = jnp.ravel(v).astype(jnp.float32)
    mag = jnp.abs(f)
    valid = jnp.logical_and(jnp.isfinite(mag), mag > 0.0)
    # log2 of 0/inf would poison the index; valid entries carry weight 1
    exp = jnp.floor(jnp.log2(jnp.where(valid, mag, 1.0)))
    idx = jnp.clip(exp - HIST_LO_EXP, 0, bins - 1).astype(jnp.int32)
    return jnp.bincount(idx, weights=valid.astype(jnp.float32),
                        length=bins)


def graph_stats(x, cfg: NumericsConfig) -> Dict[str, Any]:
    """One site's full in-graph stat pytree: ``{"s": (6,)}`` plus
    ``{"h": (bins,)}`` in hist mode. This dict IS the extra output the
    jitted graph returns for the site (replicated scalars — donation
    and the sharding contract untouched)."""
    out = {"s": summary_stats(x)}
    if cfg.hist:
        out["h"] = hist_counts(x, cfg.bins)
    return out


# ---------------------------------------------------------------------------
# trace-time tap collection
# ---------------------------------------------------------------------------

class _TapCollector:
    """Collects ``tap()``-tagged activation stats during ONE trace of a
    jitted function. The collected stat tracers must be returned from
    the traced function (the trainer threads them through its aux
    outputs) — they are tracers of the active trace, not values."""

    def __init__(self, cfg: NumericsConfig):
        self.cfg = cfg
        self.names: List[str] = []
        self.values: List[Dict[str, Any]] = []

    def add(self, site: str, x) -> None:
        if not self.cfg.wants(site):
            return
        if site in self.names:            # re-tapped name: newest wins
            self.values[self.names.index(site)] = graph_stats(x, self.cfg)
            return
        self.names.append(site)
        self.values.append(graph_stats(x, self.cfg))


class _TapState(threading.local):
    def __init__(self):
        self.stack: List[_TapCollector] = []


_TAPS = _TapState()


class collecting:
    """Scope a trace with tap collection::

        with numerics.collecting(cfg) as col:
            out = traced_forward(x)      # taps inside record into col
        # col.names / col.values are the extra outputs to return
    """

    def __init__(self, cfg: NumericsConfig):
        self._cfg = cfg
        self.collector: Optional[_TapCollector] = None

    def __enter__(self) -> _TapCollector:
        self.collector = _TapCollector(self._cfg)
        _TAPS.stack.append(self.collector)
        return self.collector

    def __exit__(self, *exc):
        _TAPS.stack.pop()


def tap(name: str, x):
    """Tag an activation for numerics telemetry — an identity op. When
    a collection scope is active (the instrumented trainer/serve build
    is tracing) the tensor's in-graph stats are recorded under site
    ``act:<name>``; otherwise (numerics off, eager execution, an
    uninstrumented trace) it returns ``x`` untouched for free."""
    if _TAPS.stack:
        _TAPS.stack[-1].add(f"act:{name}", x)
    return x


# ---------------------------------------------------------------------------
# host-side recording, rings, drift watchdog
# ---------------------------------------------------------------------------

def _as_float(v) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return float("nan")


def record(scope: str, step: Optional[int],
           stats: Dict[str, Dict[str, Any]],
           cfg: NumericsConfig) -> List[Dict[str, Any]]:
    """Fold one synced batch of per-site stat arrays into the telemetry
    surfaces: the per-site history ring, ``mxtpu_numerics_*`` gauges,
    one ``numerics.step`` event, the hist-mode calibration accumulator
    — then run the drift watchdog. Returns the (possibly empty) list of
    drift verdicts so the caller (the trainer) can escalate to its
    StepGuard under ``drift_action='rollback'``.

    ``stats``: ``{site: {"s": host (6,) array[, "h": (bins,) array]}}``
    — the device_get of the graph's stat outputs. History/drift/
    calibration state is keyed ``"<scope>/<site>"`` so different
    recording streams never interleave one drift window."""
    from . import events as _events
    from . import metrics as _metrics
    verdicts: List[Dict[str, Any]] = []
    if not stats:
        return verdicts
    with _LOCK:
        _LAST_CFG[0] = cfg
    rms_top = ("", float("-inf"))
    ff_bot = ("", float("inf"))
    for site in sorted(stats):
        key = f"{scope}/{site}"
        vec = stats[site].get("s")
        rec: Dict[str, Any] = {"step": step}
        for i, fname in enumerate(STAT_FIELDS):
            rec[fname] = _as_float(vec[i]) if vec is not None else None
        if rec["rms"] is not None and rec["rms"] > rms_top[1]:
            rms_top = (site, rec["rms"])
        if rec["finite_fraction"] is not None \
                and rec["finite_fraction"] < ff_bot[1]:
            ff_bot = (site, rec["finite_fraction"])
        for fname in ("rms", "finite_fraction", "zero_fraction",
                      "min", "max", "mean"):
            val = rec[fname]
            if val is not None and val == val \
                    and abs(val) != float("inf"):
                _metrics.gauge(f"mxtpu_numerics_{fname}",
                               f"Per-site tensor {fname} "
                               "(telemetry.numerics)",
                               site=site, scope=scope).set(val)
        with _LOCK:
            r = _RINGS.get(key)
            if r is None:
                r = _RINGS[key] = deque(maxlen=cfg.ring)
            r.append(rec)
            if cfg.hist and stats[site].get("h") is not None:
                _accumulate_calibration(key, stats[site]["h"], rec, cfg)
            verdict = _drift_verdict(key, list(r), cfg)
        if verdict is not None:
            verdict.update(scope=scope, step=step)
            verdicts.append(verdict)
            _events.emit("numerics.drift", severity="warning", **verdict)
            _metrics.counter("mxtpu_numerics_drift_total",
                             "Drift-watchdog warnings", site=site).inc()
    _events.emit("numerics.step", scope=scope, sites=len(stats),
                 rms_max_site=rms_top[0], rms_max=_finite_or_none(rms_top[1]),
                 finite_min_site=ff_bot[0],
                 finite_min=_finite_or_none(ff_bot[1]))
    _metrics.counter("mxtpu_numerics_records_total",
                     "Decimated numerics samples recorded",
                     scope=scope).inc()
    return verdicts


def _finite_or_none(v: float) -> Optional[float]:
    return v if v == v and abs(v) != float("inf") else None


def _drift_verdict(site: str, ring: List[Dict],
                   cfg: NumericsConfig) -> Optional[Dict[str, Any]]:
    """Drift decision over the site's recorded history (caller holds
    the lock; the newest ``drift_window`` entries are the evidence).
    Two signatures, both *pre-non-finite*:

    - **rms growth**: monotonically non-decreasing rms across a full
      window ending >= ``drift_ratio`` x the window start AND at a new
      ring-wide high — the grad/activation blow-up trajectory hundreds
      of steps before overflow. The new-high requirement kills the
      convergence false positive (a grad rms that decayed to ~0 at a
      loss-minimum crossing then ticked back up shows a huge *ratio*
      at a tiny *scale*; a real blow-up always makes new highs);
    - **finite-fraction decay**: monotonically non-increasing
      finite_fraction that lost ground across the window — values are
      already dying at the edges.

    Damped like the memory-leak watchdog: after flagging, the level
    must move another ratio factor (or the site must recover) before
    the same site re-flags."""
    window = ring[-cfg.drift_window:]
    if len(window) < cfg.drift_window:
        return None
    st = _DRIFT.setdefault(site, {"rms_level": None, "ff_level": None})
    rms = [w["rms"] for w in window]
    ff = [w["finite_fraction"] for w in window]
    if all(v is not None and v == v for v in rms):
        if st["rms_level"] is not None and rms[-1] < st["rms_level"]:
            st["rms_level"] = None              # recovered: re-arm
        base = rms[0]
        hist = [w["rms"] for w in ring[:-1]
                if w["rms"] is not None and w["rms"] == w["rms"]
                and abs(w["rms"]) != float("inf")]
        new_high = not hist or rms[-1] >= max(hist)
        # a zero-rms window start (a fresh bias) has no growth RATIO —
        # skip rather than divide by a floor and flag healthy warmup
        if base > 0.0 and new_high \
                and all(b >= a for a, b in zip(rms, rms[1:])) \
                and rms[-1] >= cfg.drift_ratio * base \
                and (st["rms_level"] is None
                     or rms[-1] >= cfg.drift_ratio * st["rms_level"]):
            st["rms_level"] = rms[-1]
            return {"site": site, "reason": "rms_growth",
                    "rms_first": rms[0], "rms_last": rms[-1],
                    "ratio": rms[-1] / base,
                    "window_steps": [w["step"] for w in window]}
    if all(v is not None and v == v for v in ff):
        if st["ff_level"] is not None and ff[-1] > st["ff_level"]:
            st["ff_level"] = None               # recovered: re-arm
        if all(b <= a for a, b in zip(ff, ff[1:])) and ff[-1] < ff[0] \
                and (st["ff_level"] is None or ff[-1] < st["ff_level"]):
            st["ff_level"] = ff[-1]
            return {"site": site, "reason": "finite_fraction_decay",
                    "finite_first": ff[0], "finite_last": ff[-1],
                    "window_steps": [w["step"] for w in window]}
    return None


def _accumulate_calibration(site: str, counts, rec: Dict,
                            cfg: NumericsConfig) -> None:
    """Merge one step's histogram into the run-long calibration table
    (caller holds the lock). Fixed bucket edges make the merge a plain
    per-bucket add."""
    c = _CALIB.get(site)
    host = [float(v) for v in counts]
    if c is None or len(c["counts"]) != len(host):
        c = _CALIB[site] = {"counts": [0.0] * len(host),
                            "lo_exp": HIST_LO_EXP,
                            "min": float("inf"), "max": float("-inf"),
                            "samples": 0}
    c["counts"] = [a + b for a, b in zip(c["counts"], host)]
    c["samples"] += 1
    for key, fname, pick in (("min", "min", min), ("max", "max", max)):
        v = rec.get(fname)
        if v is not None and v == v and abs(v) != float("inf"):
            c[key] = pick(c[key], v)


# ---------------------------------------------------------------------------
# read surfaces
# ---------------------------------------------------------------------------

def rings() -> Dict[str, List[Dict]]:
    """Every recorded history, oldest first, keyed
    ``"<scope>/<site>"``."""
    with _LOCK:
        return {key: list(r) for key, r in _RINGS.items()}


def ring(site: str) -> List[Dict]:
    """One history: by full ``"<scope>/<site>"`` key, or by bare site
    name (entries merged across scopes, step order) — the form the
    Monitor bridge and tests use."""
    with _LOCK:
        r = _RINGS.get(site)
        if r is not None:
            return list(r)
        out: List[Dict] = []
        for key, rr in _RINGS.items():
            if key.endswith("/" + site):
                out.extend(rr)
    out.sort(key=lambda e: (e.get("step") is None, e.get("step") or 0))
    return out


def drift_state() -> Dict[str, Dict]:
    with _LOCK:
        return {s: dict(v) for s, v in _DRIFT.items()}


def calibration_table() -> Dict[str, Dict]:
    """The accumulated hist-mode calibration data, strict-JSON shaped:
    ``{"<scope>/<site>": {"counts": [...], "lo_exp": int, "bins": int,
    "min": float, "max": float, "samples": int}}`` — the exact table
    ``quantization.Observer`` consumes (and round-trips)."""
    with _LOCK:
        out = {}
        for site, c in _CALIB.items():
            out[site] = {"counts": list(c["counts"]),
                         "lo_exp": int(c["lo_exp"]),
                         "bins": len(c["counts"]),
                         "min": _finite_or_none(c["min"]) or 0.0,
                         "max": _finite_or_none(c["max"]) or 0.0,
                         "samples": int(c["samples"])}
        return out


def snapshot(history: int = 16) -> Dict:
    """Everything numerics knows — the ``numerics`` section of
    ``telemetry.snapshot()`` and flight bundles: active config, the
    newest ``history`` ring entries per site (the drift trajectory a
    postmortem renders), damping state, and the calibration rollup."""
    with _LOCK:
        # prefer the config that actually RECORDED (a ctor-configured
        # trainer with the env unset must not render "mode=None" above
        # its own drift rows); fall back to env/override resolution
        cfg = _LAST_CFG[0]
        sites = {key: list(r)[-history:] for key, r in _RINGS.items()}
        drift = {s: dict(v) for s, v in _DRIFT.items()}
        calib = {s: {"samples": c["samples"],
                     "total": sum(c["counts"])}
                 for s, c in _CALIB.items()}
    if cfg is None:
        cfg = config()
    return {"config": {"mode": cfg.mode, "every": cfg.every,
                       "sites": list(cfg.sites), "bins": cfg.bins,
                       "drift_action": cfg.drift_action},
            "sites": sites,
            "drift": drift,
            "calibration": calib}


def reset() -> None:
    """Clear rings, drift damping, and calibration accumulation
    (tests; ``telemetry.reset()`` calls this)."""
    global _CONFIG_OVERRIDE
    with _LOCK:
        _RINGS.clear()
        _DRIFT.clear()
        _CALIB.clear()
        _LAST_CFG[0] = None
    _CONFIG_OVERRIDE = None

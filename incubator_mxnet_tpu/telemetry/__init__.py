"""``mx.telemetry`` — unified runtime observability.

Reference counterpart: none — the reference had a C++ profiler and log
lines. This subsystem is the single telemetry spine every runtime layer
publishes into, designed around the jit-runtime reality that the
dominant silent failures (recompiles, capture misses, stalls) are
*measured, not guessed* (PyGraph arXiv:2503.19779; XLA fusion study
arXiv:2301.13062):

===================  ====================================================
:mod:`~.events`      bounded, thread-safe structured event bus —
                     ``emit(kind, **fields)`` with monotonic timestamps,
                     step/request correlation ids, severity, per-kind
                     ring buffers. Publishers: ``fault.inject``,
                     ``fault.watchdog``, ``fault.guards``,
                     ``kvstore.async_ps``, ``parallel.trainer``,
                     ``serve`` (admit/batch/execute/reply), ``amp``
:mod:`~.metrics`     typed Counter/Gauge/Histogram registry; the one
                     reservoir-percentile implementation ``metric.
                     Percentile`` and ``serve.ServeMetrics`` delegate to
:mod:`~.compile_log` recompile ledger over every jit cache
                     (``CompiledModel``, ``ShardedTrainer.step``,
                     hybridize) — signature, wall time, call site;
                     "zero post-warmup compiles" assertable anywhere
:mod:`~.export`      sinks: rotating JSON-lines file, Prometheus text
                     scrape (served by ``mx.serve.Server``),
                     chrome://tracing merge with ``profiler`` spans
===================  ====================================================

One call answers "what is this job doing right now"::

    mx.telemetry.snapshot()
    # {"events": {...per-kind counts + recent...},
    #  "metrics": {...counters/gauges/histograms...},
    #  "compiles": {...ledger rollup, post_warmup count...},
    #  "spans": {...profiler wall-time aggregates...}}

Env knobs (catalogued in ``util.ENV_VARS`` / docs/env_vars.md):
``MXTPU_TELEMETRY`` (master switch), ``MXTPU_TELEMETRY_RING`` (per-kind
ring size), ``MXTPU_TELEMETRY_JSONL`` (event stream path),
``MXTPU_TELEMETRY_JSONL_MAX_MB`` (rotation threshold).
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from . import collective_ledger  # noqa: F401
from . import compile_log  # noqa: F401
from . import director  # noqa: F401
from . import events  # noqa: F401
from . import export  # noqa: F401
from . import flight  # noqa: F401
from . import goodput  # noqa: F401
from . import memory  # noqa: F401
from . import metrics  # noqa: F401
from . import numerics  # noqa: F401
from . import slo  # noqa: F401
from . import trace  # noqa: F401
from .events import (  # noqa: F401
    BUS, Event, EventBus, clear, counts, emit, enable, enabled,
    get_events, request_scope, step_scope, subscribe, unsubscribe,
)
from .export import (  # noqa: F401
    JsonlSink, chrome_trace, dumps_strict, install_from_env, install_jsonl,
    otel_spans, prometheus_text, sanitize,
)
from .slo import SLO, SLOMonitor  # noqa: F401
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, REGISTRY, counter, gauge,
    histogram,
)

__all__ = ["emit", "events", "get_events", "counts", "clear",
           "subscribe", "unsubscribe",
           "enable", "enabled", "step_scope", "request_scope",
           "Event", "EventBus", "BUS",
           "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram",
           "compile_log", "collective_ledger", "metrics", "export",
           "trace", "flight", "slo",
           "memory", "numerics", "goodput", "director",
           "SLO", "SLOMonitor",
           "prometheus_text", "chrome_trace", "otel_spans",
           "install_jsonl",
           "install_from_env", "sanitize", "dumps_strict",
           "JsonlSink", "snapshot", "reset"]


def snapshot(recent: int = 5) -> Dict:
    """One JSON-ready dict answering "what is this job doing right now":
    per-kind event counts (+ the newest ``recent`` events per kind), the
    full metrics table, the compile-ledger rollup, and the profiler's
    span aggregates. Strict-JSON safe (``export.sanitize`` applied)."""
    from .. import profiler
    ev_counts = events.counts()
    recent_by_kind = {k: [e.to_dict() for e in events.events(k, n=recent)]
                      for k in sorted(ev_counts)}
    doc = {
        "ts": time.time(),
        "events": {"counts": ev_counts,
                   "dropped": BUS.dropped(),
                   "recent": recent_by_kind},
        "metrics": metrics.to_dict(),
        "compiles": compile_log.summary(),
        "spans": profiler.span_records(),
        # distributed-trace stitching health (span/trace/orphan counts)
        "trace": trace.summary(),
        # host-gap attribution over the recorded step frames (trainer
        # "step", serving "serve.predict") — empty-shaped when no frames
        "step_report": {"step": profiler.step_report("step"),
                        "serve.predict":
                            profiler.step_report("serve.predict")},
        # the device-memory ledger: residency, per-site attribution,
        # leak-watchdog state, noted static peaks
        "memory": memory.snapshot(),
        # in-graph tensor-stats telemetry: per-site rings, drift
        # watchdog state, calibration rollup
        "numerics": numerics.snapshot(),
        # the goodput ledger: run-level wall-clock attribution vector +
        # measured-vs-roofline MFU (empty-shaped when the ledger is off)
        "goodput": goodput.snapshot(),
        # the flight director's audit surface: loop config, hysteresis
        # state, and the bounded decision ring (one-line shape when off)
        "director": director.snapshot(),
        # the collective-schedule ledger: banked per-site fingerprints,
        # the dispatch ring, and crosscheck state (the SPMD divergence
        # detector; empty-shaped when the ledger is off)
        "collective_schedule": collective_ledger.snapshot(),
        "membership": _membership_snapshot(),
    }
    return sanitize(doc)


def _membership_snapshot() -> Dict:
    """The elastic control plane's lease table / election / generation
    (``parallel.elastic.snapshot``) — imported lazily so the telemetry
    package never pulls the parallel stack at import time."""
    try:
        from ..parallel import elastic as _elastic
        return _elastic.snapshot()
    except Exception as e:  # noqa: BLE001 — degrade like flight sections
        return {"error": repr(e)}


def reset() -> None:
    """Clear every telemetry surface (events, metrics, compile ledger,
    installed sinks) — test isolation; production code never needs it."""
    clear()
    REGISTRY.clear()
    compile_log.clear()
    export.uninstall_all()
    trace.clear()
    flight.reset()
    numerics.reset()
    goodput.reset()
    director.reset()
    collective_ledger.reset()
    from ..parallel import elastic as _elastic
    _elastic.reset()

"""Typed metrics registry — Counter / Gauge / Histogram, one process table.

Reference counterpart: the reference had no metrics plane at all; this
repo then grew two parallel reservoir-percentile implementations
(``metric.Percentile`` for training, ``serve.ServeMetrics`` for serving).
:class:`Histogram` is now THE one implementation both delegate to —
algorithm-R uniform reservoir (deterministically seeded) + nearest-rank
percentiles over the full stream, mean/count exact past the cap.

The :class:`MetricsRegistry` keys instruments by ``(name, labels)`` so the
same series is shared wherever it is requested (Prometheus identity
semantics), and renders the whole table as

- ``to_dict()``  — JSON-ready nested dict (``telemetry.snapshot()``), and
- ``prometheus_text()`` — Prometheus text exposition: strict 0.0.4 by
  default (the scrape the serve
  :class:`~incubator_mxnet_tpu.serve.server.Server` answers with
  ``{"cmd": "prometheus"}``); ``exemplars=True`` opts into the
  OpenMetrics exposition, where each traced histogram gains a companion
  ``<name>_observations_total`` counter sample carrying the trace-id
  exemplar (the only sample type OpenMetrics lets an exemplar ride —
  the Server's ``{"format": "openmetrics"}`` wire command opts in).

Counters are monotonic for Prometheus sanity; per-window views belong to
the owning subsystem's snapshot (e.g. ``ServeMetrics.reset`` resets its
window, not the registry series).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as onp

from ..lockcheck import make_lock
from ..util import nearest_rank_percentile
from . import trace as _trace

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram", "prometheus_text", "to_dict"]


def _labels_key(labels: Dict[str, str]) -> Tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    """Prometheus text-exposition escaping for label values (the format
    requires ``\\`` → ``\\\\``, ``"`` → ``\\"``, newline → ``\\n``) —
    label values flow from user-controlled model names, and one bad name
    must not make the whole scrape unparseable."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


#: seconds after which the windowed "max" exemplar is considered stale
#: and replaced by the next traced observation — an all-time max would
#: point every scrape at a trace long gone from the span ring
EXEMPLAR_MAX_AGE_S = 60.0


def _exemplar_str(value: float, trace_id: str, ts: float) -> str:
    """OpenMetrics exemplar suffix: `` # {trace_id="..."} value ts`` —
    the link from a scraped series point to an actual recorded trace."""
    return (f' # {{trace_id="{_escape_label_value(trace_id)}"}} '
            f"{repr(value)} {round(ts, 3)}")


def om_family(name: str, kind: str) -> str:
    """The OpenMetrics metric-FAMILY name for a series: counter families
    are declared without the ``_total`` suffix their samples carry
    (``# TYPE x counter`` + sample ``x_total``); every other kind keeps
    its name. Shared by every exemplar-mode renderer so the convention
    cannot drift between them."""
    if kind == "counter" and name.endswith("_total"):
        return name[:-len("_total")]
    return name


def _labels_str(labels: Tuple) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label_value(v)}"'
                          for k, v in labels) + "}"


class Counter:
    """Monotonically increasing count (requests served, faults injected)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", labels: Tuple = ()):
        self.name = name
        self.help = help
        self.labels = labels
        self._lock = make_lock("Counter._lock")
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge:
    """Point-in-time value (queue depth, loss scale, grad norm)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labels: Tuple = ()):
        self.name = name
        self.help = help
        self.labels = labels
        self._lock = make_lock("Gauge._lock")
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def reset(self) -> None:
        self.set(0.0)


class Histogram:
    """Streaming scalar distribution: bounded uniform reservoir
    (algorithm R, seeded) + exact count/sum/min/max.

    Past capacity each new sample replaces a random slot with probability
    ``reservoir/seen`` so the summary tracks the FULL stream — a late
    latency regression moves the p99 instead of being dropped. This is the
    shared kernel ``metric.Percentile`` and ``serve.ServeMetrics`` both
    delegate to (one reservoir implementation in the codebase, by
    construction).
    """

    kind = "histogram"

    def __init__(self, name: str = "", help: str = "",
                 labels: Tuple = (), q=(50, 95, 99),
                 reservoir: int = 8192, seed: int = 0):
        self.name = name
        self.help = help
        self.labels = labels
        self.q = tuple(q)
        self.reservoir = int(reservoir)
        self._seed = int(seed)
        self._lock = make_lock("Histogram._lock")
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._samples: List[float] = []
            self._seen = 0
            self._total = 0.0
            self._min = float("inf")
            self._max = float("-inf")
            self._rng = onp.random.RandomState(self._seed)
            #: Prometheus exemplars: {"last"|"max": (value, trace_id, ts)}
            #: — recorded when a SAMPLED distributed-trace context is
            #: active at observe() time, so a p99 spike on the scrape
            #: links to an actual trace (OpenMetrics exemplar syntax in
            #: prometheus_text)
            self._exemplars: Dict[str, Tuple[float, str, float]] = {}

    def observe(self, value: float) -> None:
        v = float(value)
        ctx = _trace.current()   # outside the lock: two TLS reads
        with self._lock:
            self._seen += 1
            self._total += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            if len(self._samples) < self.reservoir:
                self._samples.append(v)
            else:
                j = int(self._rng.randint(0, self._seen))
                if j < self.reservoir:
                    self._samples[j] = v
            if ctx is not None and ctx.sampled:
                ts = time.time()
                self._exemplars["last"] = (v, ctx.trace_id, ts)
                mx = self._exemplars.get("max")
                # the max exemplar is WINDOWED: an all-time max would
                # pin a cold-start outlier's trace id on every future
                # scrape long after that trace aged out of the ring —
                # a stale window restarts from the current observation
                if (mx is None or v >= mx[0]
                        or ts - mx[2] > EXEMPLAR_MAX_AGE_S):
                    self._exemplars["max"] = (v, ctx.trace_id, ts)

    def exemplars(self) -> Dict[str, Tuple[float, str, float]]:
        """The recorded trace exemplars (``{"last"|"max": (value,
        trace_id, ts)}``; empty when no traced observation happened)."""
        with self._lock:
            return dict(self._exemplars)

    def reservoir_snapshot(self) -> Tuple[int, List[float]]:
        """Consistent ``(seen, samples)`` read of the reservoir: the
        total observation count and a copy of the current sample set,
        taken under one lock so cross-module consumers (SLO latency
        evaluation) never see a torn count/samples pair."""
        with self._lock:
            return self._seen, list(self._samples)

    # -- summaries ------------------------------------------------------
    @property
    def count(self) -> int:
        return self._seen

    @property
    def total(self) -> float:
        return self._total

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the reservoir; NaN when empty."""
        with self._lock:
            samples = sorted(self._samples)
        return nearest_rank_percentile(samples, q)

    def percentiles(self, qs=None) -> Dict[str, float]:
        with self._lock:
            samples = sorted(self._samples)
        return {f"p{q:g}": nearest_rank_percentile(samples, q)
                for q in (qs or self.q)}

    def summary(self) -> Dict[str, float]:
        """count/mean/min/max + the configured percentiles (JSON-ready;
        non-finite values from an empty histogram become None downstream
        via ``export.sanitize``)."""
        with self._lock:
            samples = sorted(self._samples)
            n, total = self._seen, self._total
            lo, hi = self._min, self._max
        out = {"count": n, "total": total,
               "mean": total / n if n else float("nan"),
               "min": lo if n else float("nan"),
               "max": hi if n else float("nan")}
        for q in self.q:
            out[f"p{q:g}"] = nearest_rank_percentile(samples, q)
        return out


class MetricsRegistry:
    """Process-wide instrument table keyed by ``(name, labels)``."""

    def __init__(self):
        self._lock = make_lock("MetricsRegistry._lock")
        self._table: Dict[Tuple[str, Tuple], object] = {}

    def _get(self, cls, name: str, help: str, labels: Dict, **kw):
        key = (name, _labels_key(labels))
        with self._lock:
            inst = self._table.get(key)
            if inst is None:
                inst = cls(name=name, help=help, labels=key[1], **kw)
                self._table[key] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r}{dict(key[1])} is a "
                    f"{type(inst).__name__}, requested {cls.__name__}")
        return inst

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", q=(50, 95, 99),
                  reservoir: int = 8192, **labels) -> Histogram:
        return self._get(Histogram, name, help, labels, q=q,
                         reservoir=reservoir)

    def instruments(self) -> List:
        with self._lock:
            return list(self._table.values())

    def clear(self) -> None:
        """Drop every instrument (tests)."""
        with self._lock:
            self._table.clear()

    # -- rendering ------------------------------------------------------
    def to_dict(self) -> Dict:
        """``{name: {labels_str: value-or-summary}}`` — JSON-ready after
        ``export.sanitize``."""
        out: Dict[str, Dict] = {}
        for inst in self.instruments():
            ent = out.setdefault(inst.name, {})
            key = _labels_str(inst.labels) or "_"
            ent[key] = (inst.summary() if isinstance(inst, Histogram)
                        else inst.value)
        return out

    def prometheus_text(self, exemplars: bool = False) -> str:
        """Prometheus text exposition. Histograms render as summaries
        (quantile series + _count/_sum) — the host-side reservoir has
        true quantiles, which beat lossy fixed buckets.

        The default is strict 0.0.4: the classic text format rejects
        ANYTHING after the value except a numeric timestamp, so the
        zero-argument call always yields what a scrape endpoint
        advertising ``text/plain; version=0.0.4`` must serve.

        ``exemplars=True`` opts into the OpenMetrics exposition: each
        traced histogram gains a companion ``<name>_observations``
        counter whose ``_total`` sample carries the exemplar suffix
        (`` # {trace_id="..."} v ts``) for the WORST traced observation
        — "this p99 spike IS trace <id>". The exemplar rides a counter
        sample because OpenMetrics permits exemplars only on counter and
        histogram-bucket samples, never on the summary quantile/_count
        lines the histogram itself renders as (the Server's
        ``{"format": "openmetrics"}`` wire command opts in; its default
        scrape stays 0.0.4)."""
        by_name: Dict[str, List] = {}
        for inst in self.instruments():
            by_name.setdefault(inst.name, []).append(inst)
        lines: List[str] = []
        for name in sorted(by_name):
            insts = by_name[name]
            kind = ("summary" if isinstance(insts[0], Histogram)
                    else insts[0].kind)
            # OpenMetrics names the counter FAMILY without the _total
            # suffix its samples carry; 0.0.4 conventionally types the
            # sample name itself
            family = om_family(name, kind) if exemplars else name
            if insts[0].help:
                lines.append(f"# HELP {family} {insts[0].help}")
            lines.append(f"# TYPE {family} {kind}")
            exemplar_lines: List[str] = []
            for inst in insts:
                if isinstance(inst, Histogram):
                    base = dict(inst.labels)
                    s = inst.summary()
                    for q in inst.q:
                        ql = _labels_str(_labels_key(
                            {**base, "quantile": f"{q / 100:g}"}))
                        v = s[f"p{q:g}"]
                        lines.append(f"{name}{ql} "
                                     f"{'NaN' if v != v else repr(v)}")
                    ls = _labels_str(inst.labels)
                    lines.append(f"{name}_count{ls} {s['count']}")
                    lines.append(f"{name}_sum{ls} {repr(s['total'])}")
                    # OpenMetrics forbids exemplars on summary samples;
                    # a companion counter's _total sample is the legal
                    # carrier for the worst traced observation — "this
                    # p99 spike IS trace <id>"
                    ex = inst.exemplars() if exemplars else {}
                    pick = ex.get("max") or ex.get("last")
                    if pick is not None:
                        exemplar_lines.append(
                            f"{name}_observations_total{ls} {s['count']}"
                            + _exemplar_str(*pick))
                else:
                    ls = _labels_str(inst.labels)
                    lines.append(f"{name}{ls} {repr(inst.value)}")
            if exemplar_lines:
                lines.append(f"# TYPE {name}_observations counter")
                lines.extend(exemplar_lines)
        return "\n".join(lines) + "\n"


#: the process-wide registry (the Prometheus scrape renders exactly this)
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "", **labels) -> Counter:
    return REGISTRY.counter(name, help, **labels)


def gauge(name: str, help: str = "", **labels) -> Gauge:
    return REGISTRY.gauge(name, help, **labels)


def histogram(name: str, help: str = "", q=(50, 95, 99),
              reservoir: int = 8192, **labels) -> Histogram:
    return REGISTRY.histogram(name, help, q=q, reservoir=reservoir,
                              **labels)


def prometheus_text(exemplars: bool = False) -> str:
    return REGISTRY.prometheus_text(exemplars=exemplars)


def to_dict() -> Dict:
    return REGISTRY.to_dict()

"""Flight director — the closed adaptive loop over goodput × autotune.

Reference counterpart: none. PRs 11–14 built every piece of an adaptive
loop and left it open: the autotune cache banks per-(model, mesh, chip)
roofline winners from a trace-only search, and the goodput ledger
measures where wall-clock actually went — including the
``mxtpu_goodput_mfu_divergence_pct`` gauge and a dominant-bucket
classification — yet nothing consumed either signal. This module closes
it: a :class:`FlightDirector` subscribes to ``goodput.window`` events
and, when measured MFU diverges below the roofline by more than a
threshold (or the dominant bucket drifts) across consecutive windows,
re-runs the trace-only autotune search with the *measured* attribution
folded into the roofline score (``benchmark.autotune.score(metrics,
measured=...)``), then hot-applies **one** safe remediation per site
from the allowlisted :data:`POLICY` table:

========================  ==================================================
``input_bound``           grow the prefetch queue —
                          ``io.PrefetchIter.set_depth`` (live resize, no
                          worker restart, no batch dropped)
``compute_bound``         staged recompile — ``ShardedTrainer.retune``
                          swaps the tuned config and rebuilds the pjit
                          step; the one compile the next step pays is
                          banked on the compile ledger under the
                          ``director.recompile`` site, so the
                          ``trainer.step`` zero-post-warmup contract
                          stays assertable across the cutover
``slo.burn`` breach       serve-side shed/hedge —
                          ``Router.set_overload_policy`` (tighter shed
                          depth, hedging enabled)
========================  ==================================================

Every decision is itself first-class observability: a
``director.decision`` event carrying the trigger window, divergence,
candidate table, chosen action and hysteresis state; ``mxtpu_director_*``
gauges; and a bounded decision ring embedded in ``telemetry.snapshot()``
and flight bundles and rendered by ``tools/postmortem.py``. The loop is
*damped*: a trigger needs ``MXTPU_DIRECTOR_WINDOWS`` consecutive breached
windows, every action opens a ``MXTPU_DIRECTOR_COOLDOWN``-window cooldown,
and the first post-cooldown window is compared against the pre-action
baseline — revert-if-worse with **exactly one revert** (a reverted action
kind is vetoed for the rest of the run), so a chaos-injected phase
triggers one correct remediation and can never oscillate A→B→A.

Everything is **off by default** (``MXTPU_DIRECTOR`` unset):
:func:`install` is one env read and returns ``None``; nothing subscribes,
no hot path changes, and the compiled graphs are untouched either way
(host-side bookkeeping only — the perf-proxy CI gate proves banked
PERF_PROXY.json stays byte-identical, same as numerics/goodput).

Usage::

    MXTPU_DIRECTOR=1 python train.py   # or director.configure(on=True)

    goodput.configure(on=True); goodput.price(tr, sample_args=(x, y))
    director.install(trainer=tr, prefetch=it)   # None while off
    goodput.begin()
    ...                                         # loop runs itself
    telemetry.snapshot()["director"]["decisions"]   # the audit trail
"""
from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..lockcheck import make_lock

__all__ = ["FlightDirector", "POLICY", "enabled", "configure", "install",
           "uninstall", "get", "snapshot", "reset"]

#: the remediation allowlist — dominant-bucket classification → the ONE
#: action kind the director may hot-apply for it. Classifications absent
#: here (``collective_bound``, ``host_bound``) produce an audited
#: no-action decision: there is no safe single-knob remediation, so the
#: director records the diagnosis and stays hands-off.
POLICY: Dict[str, str] = {
    "input_bound": "io.prefetch_depth",
    "compute_bound": "trainer.retune",
    # a window with rolled-back steps outranks its bound-bucket: the run
    # is paying for work it then discards (grad blowup under chaos or
    # bad geometry), and the safe knob is the same staged recompile —
    # re-stage the tuned config, never touch the guard's policy
    "rollback_storm": "trainer.retune",
    "serve_breach": "router.overload_policy",
}

_ON_OVERRIDE: Optional[bool] = None
_DIRECTOR: Optional["FlightDirector"] = None


def enabled() -> bool:
    """One env read (``MXTPU_DIRECTOR``) unless :func:`configure`
    overrode it — the entire cost of the feature while off."""
    if _ON_OVERRIDE is not None:
        return _ON_OVERRIDE
    return os.environ.get("MXTPU_DIRECTOR", "0") == "1"


def configure(on: Optional[bool] = None) -> None:
    """Process-wide override of the ``MXTPU_DIRECTOR`` switch (tests and
    drivers); ``None`` leaves the env in charge."""
    global _ON_OVERRIDE
    _ON_OVERRIDE = on


def _envf(name: str) -> float:
    from ..util import getenv
    return float(getenv(name))


def _envi(name: str) -> int:
    from ..util import getenv
    return int(getenv(name))


class FlightDirector:
    """The closed loop: goodput windows in, allowlisted remediations out,
    every decision on the audit ring. Host-side only; all state under one
    lock; the event subscription is the only hook into the runtime."""

    def __init__(self, trainer=None, prefetch=None, router=None, *,
                 divergence_pct: Optional[float] = None,
                 windows: Optional[int] = None,
                 cooldown: Optional[int] = None,
                 revert_margin_pct: Optional[float] = None,
                 ring: Optional[int] = None,
                 max_depth: Optional[int] = None,
                 search_budget: Optional[int] = None,
                 hedge_ms: Optional[float] = None):
        self.trainer = trainer
        self.prefetch = prefetch
        self.router = router
        self.divergence_pct = (divergence_pct if divergence_pct is not None
                               else _envf("MXTPU_DIRECTOR_DIVERGENCE_PCT"))
        self.windows_needed = max(1, windows if windows is not None
                                  else _envi("MXTPU_DIRECTOR_WINDOWS"))
        self.cooldown = max(1, cooldown if cooldown is not None
                            else _envi("MXTPU_DIRECTOR_COOLDOWN"))
        self.revert_margin_pct = (
            revert_margin_pct if revert_margin_pct is not None
            else _envf("MXTPU_DIRECTOR_REVERT_MARGIN_PCT"))
        self.max_depth = max(1, max_depth if max_depth is not None
                             else _envi("MXTPU_DIRECTOR_MAX_DEPTH"))
        self.search_budget = max(1, search_budget if search_budget is not None
                                 else _envi("MXTPU_DIRECTOR_BUDGET"))
        self.hedge_ms = (hedge_ms if hedge_ms is not None
                         else _envf("MXTPU_DIRECTOR_HEDGE_MS"))
        self._lock = make_lock("FlightDirector._lock")
        self._ring: deque = deque(maxlen=max(
            1, ring if ring is not None else _envi("MXTPU_DIRECTOR_RING")))
        self._n = 0                  # decision ids (monotonic)
        self._streak = 0             # consecutive breached windows
        self._cooldown_left = 0      # windows the loop still holds
        self._stable_class: Optional[str] = None
        self._last_div: Optional[float] = None
        self._pending: Optional[Dict[str, Any]] = None  # action under eval
        self._vetoed: set = set()    # action kinds disabled after a revert
        self._held: set = set()      # kinds kept but frozen (no effect)
        self._serve_acted: set = set()   # slo names already remediated
        self._reverts = 0
        self._decisions = 0
        self._sub: Optional[Callable] = None

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------
    def start(self) -> "FlightDirector":
        from . import events as _events
        if self._sub is None:
            self._sub = self._on_event
            _events.subscribe(self._sub)
        return self

    def close(self) -> None:
        from . import events as _events
        if self._sub is not None:
            _events.unsubscribe(self._sub)
            self._sub = None

    def _on_event(self, ev) -> None:
        # the one hook: everything else in this module runs only when a
        # window closes or an SLO alert fires — never per step/request
        if ev.kind == "goodput.window":
            self._on_window(dict(ev.fields or {}))
        elif ev.kind == "slo.burn":
            self._on_burn(ev)

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------
    def _on_window(self, win: Dict[str, Any]) -> None:
        mfu = win.get("mfu") or {}
        div = mfu.get("divergence_pct")
        cls = win.get("classification")
        wid = win.get("window")
        evaluate = trigger = False
        with self._lock:
            self._last_div = div
            # divergence sign convention (pinned by test_goodput):
            # 100·(measured/predicted − 1) — measured BELOW the roofline
            # is negative, so the breach test is div <= −threshold
            breach = div is not None and div <= -self.divergence_pct
            drift = (cls is not None and self._stable_class is not None
                     and cls != self._stable_class)
            if cls is not None and self._stable_class is None:
                self._stable_class = cls
            if self._cooldown_left > 0:
                self._cooldown_left -= 1
                self._streak = 0
                # the first fully-post-cooldown window is the evaluation
                # sample for revert-if-worse
                evaluate = (self._cooldown_left == 0
                            and self._pending is not None)
            else:
                self._streak = self._streak + 1 if (breach or drift) else 0
                trigger = self._streak >= self.windows_needed
                if trigger:
                    self._streak = 0
        self._publish_gauges()
        if evaluate:
            self._evaluate(win, div)
        elif trigger:
            self._trigger(win, wid, div, cls, breach, drift)

    def _trigger(self, win: Dict, wid, div, cls, breach: bool,
                 drift: bool) -> None:
        key = ("rollback_storm" if (win.get("rolled_back_steps") or 0) > 0
               else (cls or ""))
        kind = POLICY.get(key)
        candidates: List[Dict[str, Any]] = []
        action: Dict[str, Any]
        undo: Optional[Callable] = None
        baseline = div
        if kind is None:
            action = {"kind": "none", "reason":
                      f"no allowlisted remediation for {key!r}"}
        elif kind in self._vetoed:
            action = {"kind": "none",
                      "reason": f"{kind} vetoed after its one revert"}
        elif kind in self._held:
            action = {"kind": "none",
                      "reason": f"{kind} held: a previous application "
                                "produced no measurable improvement"}
        elif kind == "io.prefetch_depth":
            candidates, action, undo = self._apply_prefetch()
        elif kind == "trainer.retune":
            candidates, action, undo = self._apply_retune(win)
        else:                                    # pragma: no cover
            action = {"kind": "none", "reason": f"unknown policy {kind!r}"}
        with self._lock:
            # any decision — applied or audited no-action — opens a
            # cooldown: the loop never spams one diagnosis per window
            self._cooldown_left = self.cooldown
            if undo is not None:
                self._pending = {"kind": action["kind"], "undo": undo,
                                 "baseline_div": baseline, "window": wid}
            if cls is not None:
                self._stable_class = cls
        self._decide(trigger={"window": wid, "divergence_pct": div,
                              "classification": cls, "policy_key": key,
                              "rolled_back_steps":
                                  win.get("rolled_back_steps"),
                              "breach": breach, "drift": drift},
                     candidates=candidates, action=action)

    def _evaluate(self, win: Dict, post_div: Optional[float]) -> None:
        """The damping half of the loop, one outcome per applied action:
        compare the first post-cooldown window against the pre-action
        baseline. Clearly *worse* → revert (exactly once — the kind is
        vetoed afterwards). Clearly *better* → keep, and the kind stays
        armed (further escalation is allowed while it is measurably
        helping). Neither → keep but **hold** the kind: re-applying a
        knob that did not move the needle is the hunting behavior the
        hysteresis exists to prevent."""
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is None:
            return
        base = pending.get("baseline_div")
        worse = (post_div is not None and base is not None
                 and post_div < base - self.revert_margin_pct)
        better = (post_div is not None and base is not None
                  and post_div > base + self.revert_margin_pct)
        if not worse:
            if not better:
                with self._lock:
                    self._held.add(pending["kind"])
                self._decide(
                    trigger={"window": win.get("window"),
                             "divergence_pct": post_div,
                             "classification": win.get("classification"),
                             "breach": False, "drift": False},
                    candidates=[],
                    action={"kind": "hold", "of": pending["kind"],
                            "baseline_divergence_pct": base,
                            "post_divergence_pct": post_div,
                            "reason": "no measurable improvement — kept, "
                                      "but this kind will not re-fire"})
            return
        try:
            pending["undo"]()
            err = None
        except Exception as e:  # noqa: BLE001 — audit, never propagate
            err = repr(e)[:200]
        with self._lock:
            self._vetoed.add(pending["kind"])
            self._reverts += 1
            self._cooldown_left = self.cooldown
            for dec in self._ring:
                if dec["action"].get("kind") == pending["kind"] \
                        and not dec.get("reverted"):
                    dec["reverted"] = True
        action = {"kind": "revert", "of": pending["kind"],
                  "baseline_divergence_pct": base,
                  "post_divergence_pct": post_div}
        if err:
            action["error"] = err
        self._decide(trigger={"window": win.get("window"),
                              "divergence_pct": post_div,
                              "classification": win.get("classification"),
                              "breach": True, "drift": False},
                     candidates=[], action=action)

    # ------------------------------------------------------------------
    # remediations (the allowlist bodies)
    # ------------------------------------------------------------------
    def _apply_prefetch(self) -> Tuple[List, Dict, Optional[Callable]]:
        it = self.prefetch
        if it is None:
            return [], {"kind": "none",
                        "reason": "input_bound but no PrefetchIter "
                                  "registered"}, None
        old = int(it.depth)
        new = min(max(old * 2, old + 1), self.max_depth)
        cands = [{"depth": d, "current": d == old}
                 for d in sorted({old, new, self.max_depth})]
        if new == old:
            return cands, {"kind": "none",
                           "reason": f"prefetch depth already at the "
                                     f"{self.max_depth} cap"}, None
        it.set_depth(new)
        return (cands,
                {"kind": "io.prefetch_depth", "site": "io.PrefetchIter",
                 "from": old, "to": new},
                lambda: it.set_depth(old))

    def _apply_retune(self, win: Dict) -> Tuple[List, Dict,
                                                Optional[Callable]]:
        tr = self.trainer
        if tr is None:
            return [], {"kind": "none",
                        "reason": "compute_bound but no trainer "
                                  "registered"}, None
        candidates, entry, source = self._retune_candidates(win)
        prev = dict(tr.autotune_entry) if tr.autotune_entry else {}
        try:
            tr.retune(entry, site="director.recompile")
        except Exception as e:  # noqa: BLE001 — audit, never propagate
            return candidates, {"kind": "none", "reason":
                                f"retune failed: {e!r:.200}"}, None
        return (candidates,
                {"kind": "trainer.retune", "site": "director.recompile",
                 "source": source,
                 "from": (prev.get("config") or {}).get("env") or {},
                 "to": (entry.get("config") or {}).get("env") or {}},
                lambda: tr.retune(prev or {}, site="director.recompile"))

    def _retune_candidates(self, win: Dict) -> Tuple[List, Dict, str]:
        """The rescored candidate table: re-run the trace-only autotune
        search with the window's measured attribution folded into the
        roofline score. A family outside the search space falls back to
        re-staging the banked entry (the cutover is still real — a fresh
        pjit build — and still audited)."""
        measured = self._measured_fractions(win)
        tr = self.trainer
        fam = getattr(tr, "_autotune_key", None)
        try:
            from benchmark import autotune as _bench
        except Exception:  # noqa: BLE001 — tools tree absent in prod
            _bench = None
        if _bench is not None and fam in getattr(_bench, "FAMILY_SPACES",
                                                 {}):
            try:
                res = _bench.search(fam, budget=self.search_budget,
                                    measured=measured)
                table = [{"config": r["config"],
                          "score": round(r["score"], 4),
                          "feasible": r["feasible"]}
                         for r in sorted(res["rows"],
                                         key=lambda r: -r["score"])[:3]]
                entry = {"config": _bench.winner_config(fam, res["winner"]),
                         "score": res["winner_score"],
                         "meta": {"measured": measured}}
                return table, entry, "rescored_search"
            except Exception as e:  # noqa: BLE001 — fall back, audited
                fallback_note = repr(e)[:200]
        else:
            fallback_note = f"family {fam!r} not in the search space"
        entry = dict(tr.autotune_entry or {}) or {"config": {"env": {}}}
        table = [{"config": entry.get("config") or {},
                  "score": entry.get("score"), "source": "banked",
                  "note": fallback_note, "measured": measured}]
        return table, entry, "banked"

    @staticmethod
    def _measured_fractions(win: Dict) -> Optional[Dict[str, float]]:
        cats = win.get("categories") or {}
        wall = float(win.get("wall_ms") or 0.0)
        if wall <= 0:
            return None
        def frac(c):
            return round(max(0.0, min(1.0, float(cats.get(c, 0.0)) / wall)),
                         6)
        return {"compute": frac("compute"), "collective": frac("collective"),
                "input_wait": frac("input_wait"), "host": frac("host")}

    # ------------------------------------------------------------------
    # serve-side breach (slo.burn)
    # ------------------------------------------------------------------
    def _on_burn(self, ev) -> None:
        f = dict(ev.fields or {})
        slo = f.get("slo")
        if f.get("recovered"):
            with self._lock:
                self._serve_acted.discard(slo)
            return
        if self.router is None or ev.severity != "error":
            return
        kind = POLICY["serve_breach"]
        with self._lock:
            if slo in self._serve_acted or kind in self._vetoed:
                return
            # one remediation per SLO per breach episode — re-armed only
            # by the recovery event, so a still-burning alert can't stack
            self._serve_acted.add(slo)
        r = self.router
        to_shed = 8 if r.shed_depth <= 0 else max(2, r.shed_depth // 2)
        to_hedge = self.hedge_ms if r.hedge_ms <= 0 else r.hedge_ms
        prev = r.set_overload_policy(hedge_ms=to_hedge, shed_depth=to_shed)
        self._decide(trigger={"slo": slo, "burn": f.get("burn"),
                              "bad_fraction": f.get("bad_fraction")},
                     candidates=[{"shed_depth": to_shed,
                                  "hedge_ms": to_hedge}],
                     action={"kind": kind, "site": "serve.Router",
                             "from": prev,
                             "to": {"hedge_ms": r.hedge_ms,
                                    "shed_depth": r.shed_depth}})

    # ------------------------------------------------------------------
    # the audit trail
    # ------------------------------------------------------------------
    def _decide(self, trigger: Dict, candidates: List,
                action: Dict) -> None:
        with self._lock:
            self._n += 1
            self._decisions += 1
            dec = {"id": self._n, "ts": round(time.time(), 6),
                   "trigger": trigger, "candidates": candidates,
                   "action": action, "reverted": False,
                   "hysteresis": {"cooldown_windows": self.cooldown,
                                  "cooldown_left": self._cooldown_left,
                                  "streak_needed": self.windows_needed,
                                  "vetoed": sorted(self._vetoed),
                                  "held": sorted(self._held)}}
            self._ring.append(dec)
        from . import events as _events
        from . import metrics as _metrics
        applied = action.get("kind") not in (None, "none")
        _events.emit("director.decision",
                     severity="warning" if applied else "info", **dec)
        _metrics.counter("mxtpu_director_decisions_total",
                         "Flight-director decisions (audited, ring-backed)",
                         action=str(action.get("kind"))).inc()
        if action.get("kind") == "revert":
            _metrics.counter("mxtpu_director_reverts_total",
                             "Flight-director revert-if-worse firings"
                             ).inc()

    def _publish_gauges(self) -> None:
        from . import metrics as _metrics
        with self._lock:
            streak, cd, div = (self._streak, self._cooldown_left,
                               self._last_div)
        _metrics.gauge("mxtpu_director_breach_streak",
                       "Consecutive breached goodput windows").set(streak)
        _metrics.gauge("mxtpu_director_cooldown_left",
                       "Windows the director still holds post-action"
                       ).set(cd)
        if div is not None:
            _metrics.gauge("mxtpu_director_last_divergence_pct",
                           "MFU divergence of the last window the "
                           "director saw").set(div)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            pending = (None if self._pending is None else
                       {k: v for k, v in self._pending.items()
                        if k != "undo"})
            return {
                "enabled": True, "installed": True,
                "config": {"divergence_pct": self.divergence_pct,
                           "windows": self.windows_needed,
                           "cooldown": self.cooldown,
                           "revert_margin_pct": self.revert_margin_pct,
                           "max_depth": self.max_depth,
                           "search_budget": self.search_budget},
                "targets": {"trainer": self.trainer is not None,
                            "prefetch": self.prefetch is not None,
                            "router": self.router is not None},
                "state": {"streak": self._streak,
                          "cooldown_left": self._cooldown_left,
                          "stable_class": self._stable_class,
                          "last_divergence_pct": self._last_div,
                          "pending": pending,
                          "vetoed": sorted(self._vetoed),
                          "held": sorted(self._held),
                          "serve_acted": sorted(
                              s for s in self._serve_acted
                              if s is not None),
                          "decisions_total": self._decisions,
                          "reverts_total": self._reverts},
                "decisions": [dict(d) for d in self._ring],
            }


# ---------------------------------------------------------------------------
# module-level singleton (what telemetry.snapshot()/flight bundles embed)
# ---------------------------------------------------------------------------

def install(trainer=None, prefetch=None, router=None,
            **knobs) -> Optional[FlightDirector]:
    """Start the loop over the given remediation targets. One env read
    and ``None`` while ``MXTPU_DIRECTOR`` is off. Installing again
    replaces the previous director (its ring is dropped — snapshot first
    if the audit trail matters)."""
    global _DIRECTOR
    if not enabled():
        return None
    if _DIRECTOR is not None:
        _DIRECTOR.close()
    _DIRECTOR = FlightDirector(trainer=trainer, prefetch=prefetch,
                               router=router, **knobs).start()
    return _DIRECTOR


def get() -> Optional[FlightDirector]:
    """The installed director singleton (``None`` while uninstalled)."""
    return _DIRECTOR


def uninstall() -> None:
    global _DIRECTOR
    if _DIRECTOR is not None:
        _DIRECTOR.close()
        _DIRECTOR = None


def snapshot() -> Dict[str, Any]:
    """The embeddable audit surface: config + hysteresis state + the
    decision ring (``telemetry.snapshot()["director"]``, flight bundles,
    ``tools/postmortem.py``)."""
    d = _DIRECTOR
    if d is None:
        return {"enabled": enabled(), "installed": False, "decisions": []}
    return d.snapshot()


def reset() -> None:
    """Drop the singleton and the configure() override (test isolation —
    mirrors ``goodput.reset``)."""
    global _ON_OVERRIDE
    uninstall()
    _ON_OVERRIDE = None

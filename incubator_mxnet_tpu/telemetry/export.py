"""Telemetry sinks — JSON-lines file, Prometheus scrape, chrome trace.

Three render targets for the same spine (bus + registry + compile ledger):

- :class:`JsonlSink` — every event as one strict-JSON line in a rotating
  file (``MXTPU_TELEMETRY_JSONL`` / ``MXTPU_TELEMETRY_JSONL_MAX_MB``);
  the CI ``telemetry-smoke`` job replays the stream through
  ``tools/telemetry_check.py`` and fails on any malformed line or
  post-warmup compile event.
- :func:`prometheus_text` — the metrics registry in Prometheus text
  exposition format, plus synthetic ``mxtpu_events_total{kind=...}``
  series from the bus counts. The serve
  :class:`~incubator_mxnet_tpu.serve.server.Server` answers
  ``{"cmd": "prometheus"}`` with exactly this string.
- :func:`chrome_trace` — a chrome://tracing / Perfetto JSON document
  merging the profiler's recent wall-time spans (``profiler`` records the
  raw start/duration pairs) with the bus events as instant markers, so
  one timeline shows step phases, serve stages, AND the faults/compiles
  that punctuated them.

Strict JSON everywhere: :func:`sanitize` maps non-finite floats to null
before serialization and every ``json.dumps`` here passes
``allow_nan=False`` — an empty histogram must not leak an ``Infinity``
token into a parser (the bug :func:`profiler.span_records` had).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ..lockcheck import make_lock

__all__ = ["sanitize", "dumps_strict", "JsonlSink", "install_jsonl",
           "install_from_env", "uninstall_all", "prometheus_text",
           "chrome_trace", "otel_spans"]


def sanitize(obj):
    """Recursively make ``obj`` strict-JSON serializable: non-finite
    floats (NaN/±inf) become None, tuples become lists, unknown objects
    become their repr."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj if obj == obj and obj not in (float("inf"),
                                                 float("-inf")) else None
    if isinstance(obj, dict):
        return {str(k): sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [sanitize(v) for v in obj]
    try:  # numpy scalars quack like floats/ints
        return sanitize(float(obj)) if hasattr(obj, "dtype") else repr(obj)
    except (TypeError, ValueError):
        return repr(obj)


def dumps_strict(obj, **kw) -> str:
    """``json.dumps`` with ``allow_nan=False`` over sanitized input — the
    one serializer every telemetry surface goes through."""
    return json.dumps(sanitize(obj), allow_nan=False, **kw)


def _reject_nonfinite(tok):
    raise ValueError(f"non-strict JSON token {tok!r}")


def loads_strict(s: str):
    """The loads half of the strict-JSON contract: rejects
    ``NaN``/``Infinity`` tokens a lenient parser would accept, so a
    consumer cannot read back what :func:`dumps_strict` could never have
    written. (The stdlib-only tools under ``tools/`` carry their own
    copies by design.)"""
    return json.loads(s, parse_constant=_reject_nonfinite)


class JsonlSink:
    """Bus subscriber writing one strict-JSON line per event, with
    size-based rotation (``path`` -> ``path.1``, one generation — bounded
    disk like the rings bound memory). Thread-safe; install with
    ``telemetry.subscribe(sink)`` or :func:`install_jsonl`.

    Multi-host: the configured ``path`` belongs to the elected primary
    (the MX902 invariant — one owner per shared file); every other host
    writes the SAME stream to its own namespaced file
    (``path.p<index>``, ``dist.process_namespace``). N hosts → N
    disjoint, individually valid streams: per-host forensics with zero
    shared-file races, and a host-loss postmortem still has the dead
    host's events up to its last flush."""

    def __init__(self, path: str, max_mb: Optional[float] = None):
        from ..util import getenv
        self.path = path
        self.max_bytes = int(float(
            getenv("MXTPU_TELEMETRY_JSONL_MAX_MB")
            if max_mb is None else max_mb) * 1024 * 1024)
        self._lock = make_lock("JsonlSink._lock")
        self._fh = None
        self._started = False
        self._primary: Optional[bool] = None
        self._out_path: Optional[str] = None
        self.lines = 0

    def elected(self) -> bool:
        """Host-0 election (the MX902 invariant): under SPMD every
        process emits the same events, but only the elected host may own
        the *configured* path — the rest own their namespaced one (see
        :meth:`stream_path`). Always True single-process
        (``parallel.dist.is_primary`` is a no-op election there), cached
        at the first event so the per-event cost is one attribute read."""
        if self._primary is None:
            try:
                from ..parallel.dist import is_primary
                self._primary = bool(is_primary())
            except Exception:  # noqa: BLE001 — no dist runtime ⇒ one host
                self._primary = True
        return self._primary

    def stream_path(self) -> str:
        """This process's actual output file: the configured ``path`` on
        the elected primary (and always single-process), ``path.p<idx>``
        on every other host. Cached with the election."""
        if self._out_path is None:
            out = self.path
            if not self.elected():
                try:
                    from ..parallel.dist import process_namespace
                    ns = process_namespace()
                except Exception:  # noqa: BLE001 — no dist runtime
                    ns = ""
                if ns:
                    out = f"{self.path}.{ns}"
            self._out_path = out
        return self._out_path

    def __call__(self, event) -> None:
        path = self.stream_path()
        line = dumps_strict(event.to_dict(), sort_keys=True)
        with self._lock:
            try:
                if self._fh is None:
                    d = os.path.dirname(os.path.abspath(path))
                    os.makedirs(d, exist_ok=True)
                    # first open truncates: seq numbers restart per
                    # process, so appending to a previous run's file would
                    # read as corruption (duplicate seqs) to
                    # tools/telemetry_check.py; reopens within one run
                    # (after rotation/close) append. The path is
                    # per-process by construction (stream_path), so the
                    # write needs no further election.
                    self._fh = open(path,  # mxlint: disable=MX902
                                    "a" if self._started else "w",
                                    encoding="utf-8")
                    self._started = True
                self._fh.write(line + "\n")
                self._fh.flush()
                self.lines += 1
                if self.max_bytes and self._fh.tell() >= self.max_bytes:
                    self._rotate()
            except Exception:
                # self-heal: a failed write/rotate must not wedge the
                # stream forever on a half-dead handle — drop the handle
                # so the NEXT event reopens (append), and let the bus
                # count this one (it isolates subscriber errors)
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except Exception:  # noqa: BLE001 — already broken
                        pass
                    self._fh = None
                raise

    def _rotate(self) -> None:
        self._fh.close()
        self._fh = None
        path = self.stream_path()
        # the rotated name is per-process too (stream_path) — one owner
        # per file, statically unprovable from here
        os.replace(path, path + ".1")  # mxlint: disable=MX902

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


_INSTALLED: Dict[str, JsonlSink] = {}
_INSTALL_LOCK = make_lock("export._INSTALL_LOCK")


def install_jsonl(path: str, max_mb: Optional[float] = None) -> JsonlSink:
    """Create + subscribe a :class:`JsonlSink` (idempotent per path —
    locked, so two threads racing the first emission cannot double-
    install and duplicate every line)."""
    from . import events as _events
    with _INSTALL_LOCK:
        sink = _INSTALLED.get(path)
        if sink is None:
            sink = _INSTALLED[path] = JsonlSink(path, max_mb=max_mb)
            _events.subscribe(sink)
    return sink


def install_from_env() -> Optional[JsonlSink]:
    """Install the sinks ``MXTPU_TELEMETRY_*`` env vars ask for (called
    automatically on the first emission)."""
    from ..util import getenv
    path = getenv("MXTPU_TELEMETRY_JSONL")
    if path:
        return install_jsonl(path)
    return None


def uninstall_all() -> None:
    """Close + unsubscribe every installed sink (``telemetry.reset``)."""
    from . import events as _events
    with _INSTALL_LOCK:
        sinks = list(_INSTALLED.values())
        _INSTALLED.clear()
    for sink in sinks:
        _events.unsubscribe(sink)
        sink.close()
    # the next emission re-consults MXTPU_TELEMETRY_* (a reset must not
    # leave the env-configured stream silently dark for the process rest)
    _events._reset_env_sinks_flag()


def prometheus_text(exemplars: bool = False) -> str:
    """The full scrape: metrics registry + per-kind event totals +
    subscriber-error count. The default is a strict 0.0.4 exposition
    (no OpenMetrics exemplar suffixes) — what a scrape endpoint
    advertising ``text/plain; version=0.0.4`` must serve;
    ``exemplars=True`` opts into the OpenMetrics form with trace-id
    exemplars on the ``<name>_observations_total`` companion counters."""
    from . import events as _events
    from . import metrics as _metrics
    out = [_metrics.prometheus_text(exemplars=exemplars).rstrip("\n")]

    def _family(total_name: str) -> str:
        # OpenMetrics counter families drop the _total their samples
        # carry; 0.0.4 conventionally types the sample name itself
        return (_metrics.om_family(total_name, "counter") if exemplars
                else total_name)

    counts = _events.counts()
    if counts:
        out.append(f"# TYPE {_family('mxtpu_events_total')} counter")
        for kind in sorted(counts):
            out.append(f'mxtpu_events_total{{kind="{kind}"}} '
                       f"{counts[kind]}")
    # the first subscriber error registers this series in the registry
    # (rendered above); the synthetic zero line below only fills the gap
    # before then, so the series exists from the first scrape without
    # ever duplicating
    if not any(i.name == "mxtpu_telemetry_subscriber_errors_total"
               for i in _metrics.REGISTRY.instruments()):
        out.append(f"# TYPE "
                   f"{_family('mxtpu_telemetry_subscriber_errors_total')} "
                   f"counter")
        out.append("mxtpu_telemetry_subscriber_errors_total "
                   f"{_events.BUS.subscriber_errors}")
    return "\n".join(out) + "\n"


def otel_spans() -> List[Dict]:
    """The trace ring in OpenTelemetry-style span dicts (``traceId`` /
    ``spanId`` / ``parentSpanId`` / nanosecond timestamps) — the export
    form ``serve_bench --trace-out`` writes and ``tools/telemetry_check.py
    --require-rooted-traces`` validates. JSON-ready after
    :func:`sanitize`."""
    from . import trace as _trace
    out = []
    for r in _trace.spans():
        t0_ns = int(r["ts"] * 1e9)
        rec = {"traceId": r["trace_id"], "spanId": r["span_id"],
               "parentSpanId": r.get("parent_id") or "",
               "name": r["name"], "kind": r["kind"],
               "startTimeUnixNano": t0_ns,
               "endTimeUnixNano": t0_ns + int(r["dur_ms"] * 1e6),
               "attributes": dict(r.get("attrs", {}))}
        for k in ("thread", "step", "request_id"):
            if r.get(k) is not None:
                rec["attributes"][k] = r[k]
        out.append(rec)
    return out


def chrome_trace(include_events: bool = True) -> str:
    """chrome://tracing JSON merging the profiler's recent raw spans
    (``ph: "X"`` complete events) with bus events (``ph: "i"`` instants,
    one track per kind). Span timestamps all come from the profiler's
    single anchored clock, so a child scope's interval is contained in
    its parent's — nested scopes *nest* on the rendered timeline rather
    than interleaving — and the parent/depth/step metadata rides in
    ``args``. Load in chrome://tracing or ui.perfetto.dev."""
    from .. import profiler
    trace = []
    for rec in profiler.recent_spans():
        args = {"depth": rec.depth}
        if rec.parent is not None:
            args["parent"] = rec.parent
        if rec.step is not None:
            args["step"] = rec.step
        if rec.trace is not None:
            args["trace_id"], args["span_id"] = rec.trace
        trace.append({"name": rec.name, "cat": rec.kind, "ph": "X",
                      "ts": round(rec.t_start * 1e6, 1),
                      "dur": round(rec.dur_ms * 1e3, 1),
                      "pid": 1, "tid": 1, "args": args})
    if include_events:
        from . import events as _events
        for ev in _events.events():
            args = dict(ev.fields)
            if ev.step is not None:
                args["step"] = ev.step
            if ev.request_id is not None:
                args["request_id"] = ev.request_id
            if ev.trace_id is not None:
                args["trace_id"] = ev.trace_id
                args["span_id"] = ev.span_id
            trace.append({"name": f"{ev.kind}", "cat": ev.severity,
                          "ph": "i", "s": "p",
                          "ts": round(ev.ts * 1e6, 1),
                          "pid": 1, "tid": 2, "args": sanitize(args)})
    return dumps_strict({"traceEvents": trace,
                         "displayTimeUnit": "ms"})

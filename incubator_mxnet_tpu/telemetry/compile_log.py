"""Recompile ledger — every XLA compile in the process, one table.

Reference counterpart: the reference's ``CachedOp`` captured once per
(shape, train-mode) bucket and cache misses were visible in the engine
profile. On a jit runtime a recompile is the *dominant silent failure
mode* — seconds of latency, growing device memory, no exception anywhere
(PyGraph, arXiv 2503.19779; the XLA fusion study, arXiv 2301.13062, makes
the measure-don't-guess argument). Three jit caches already exist
(``CompiledModel`` buckets, ``ShardedTrainer.step``, the hybridize
``_call_cached_op`` cache) and each kept private counters; this ledger is
where they all report, so **"zero unexpected recompiles" is assertable
anywhere** — not just inside serve.

Every :func:`note` records the triggering (shape, dtype) signature, the
wall time the compile cost (when the call site measures it), the call
site, and whether the site considers itself still warming up. Post-warmup
compiles are the bug signal: ``post_warmup_compiles() == 0`` is the
steady-state contract the serve bench, the telemetry CI smoke job, and
``assert_zero_post_warmup()`` all enforce. Each note also publishes a
``compile`` event on the bus (with the current step/request correlation
ids) and bumps ``mxtpu_compiles_total{phase=...}``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

from ..lockcheck import make_lock

__all__ = ["CompileRecord", "note", "mark_warmed", "is_warmed", "records",
           "summary", "post_warmup_compiles", "assert_zero_post_warmup",
           "clear", "MAX_RECORDS"]

#: ledger ring size — a recompile storm must not grow host memory unbounded
MAX_RECORDS = 4096


class CompileRecord:
    """One compile event: where, what signature, how long, which phase."""

    __slots__ = ("site", "signature", "wall_ms", "warmup", "ts", "step")

    def __init__(self, site: str, signature: str, wall_ms: Optional[float],
                 warmup: bool, ts: float, step: Optional[int]):
        self.site = site
        self.signature = signature
        self.wall_ms = wall_ms
        self.warmup = warmup
        self.ts = ts
        self.step = step

    def to_dict(self) -> Dict:
        return {"site": self.site, "signature": self.signature,
                "wall_ms": self.wall_ms, "warmup": self.warmup,
                "ts": round(self.ts, 6), "step": self.step}

    def __repr__(self):
        phase = "warmup" if self.warmup else "POST-WARMUP"
        ms = f", {self.wall_ms:.1f}ms" if self.wall_ms is not None else ""
        return f"CompileRecord({self.site}, {phase}{ms}, {self.signature})"


_LOCK = make_lock("compile_log._LOCK")
_RECORDS: deque = deque(maxlen=MAX_RECORDS)
_TOTALS = {"warmup": 0, "post_warmup": 0}
_BY_SITE: Dict[str, Dict[str, int]] = {}
_WARMED: set = set()


def mark_warmed(site: str) -> None:
    """Declare ``site`` past its warmup phase: compiles noted there
    without an explicit ``warmup=`` flag count as post-warmup from now on
    (``CompiledModel.warmup()`` does the equivalent internally; call this
    after your own warmup loop for hybridize/step sites)."""
    with _LOCK:
        _WARMED.add(site)


def is_warmed(site: str) -> bool:
    with _LOCK:
        return site in _WARMED


def note(site: str, signature, wall_ms: Optional[float] = None,
         warmup: Optional[bool] = None) -> CompileRecord:
    """Record one compile at ``site``. ``signature`` is any repr-able
    shape/dtype description; ``warmup=False`` marks it unexpected (the
    site believed it was past its warmup phase). ``warmup=None`` derives
    the phase from :func:`mark_warmed` state. Publishes a ``compile``
    bus event and the ``mxtpu_compiles_total`` counter as side effects."""
    if warmup is None:
        warmup = not is_warmed(site)
    rec = CompileRecord(site, repr(signature)[:300],
                        None if wall_ms is None else round(wall_ms, 3),
                        bool(warmup), time.time(),
                        None)
    from . import events as _events
    rec.step = _events.current_step()
    phase = "warmup" if rec.warmup else "post_warmup"
    with _LOCK:
        _RECORDS.append(rec)
        _TOTALS[phase] += 1
        ent = _BY_SITE.setdefault(site, {"warmup": 0, "post_warmup": 0})
        ent[phase] += 1
    from . import metrics as _metrics
    _metrics.counter("mxtpu_compiles_total",
                     "XLA compile events recorded by the telemetry ledger",
                     site=site, phase=phase).inc()
    _events.emit("compile",
                 severity="info" if rec.warmup else "warning",
                 site=site, signature=rec.signature, wall_ms=rec.wall_ms,
                 warmup=rec.warmup)
    return rec


def records(site: Optional[str] = None) -> List[CompileRecord]:
    with _LOCK:
        out = list(_RECORDS)
    return [r for r in out if site is None or r.site == site]


def summary() -> Dict:
    """The ledger rollup ``telemetry.snapshot()`` inlines."""
    with _LOCK:
        recent = [r.to_dict() for r in list(_RECORDS)[-5:]]
        return {"total": _TOTALS["warmup"] + _TOTALS["post_warmup"],
                "warmup": _TOTALS["warmup"],
                "post_warmup": _TOTALS["post_warmup"],
                "by_site": {k: dict(v) for k, v in _BY_SITE.items()},
                "recent": recent}


def post_warmup_compiles(site: Optional[str] = None) -> int:
    with _LOCK:
        if site is not None:
            return _BY_SITE.get(site, {}).get("post_warmup", 0)
        return _TOTALS["post_warmup"]


def assert_zero_post_warmup(site: Optional[str] = None) -> None:
    """Raise ``MXNetError`` if any post-warmup compile was recorded
    (optionally at one site) — the steady-state contract, assertable from
    anywhere. Gated on the exact counters (which never age out), with the
    bounded record ring supplying whatever detail is still held."""
    n = post_warmup_compiles(site)
    if n:
        bad = [r for r in records(site) if not r.warmup]
        detail = ("\n".join(f"  {r!r}" for r in bad[-10:]) if bad else
                  "  (records aged out of the ring; counters are exact)")
        from ..base import MXNetError
        raise MXNetError(
            f"{n} unexpected (post-warmup) XLA compile(s):\n" + detail)


def clear() -> None:
    with _LOCK:
        _RECORDS.clear()
        _TOTALS["warmup"] = _TOTALS["post_warmup"] = 0
        _BY_SITE.clear()
        _WARMED.clear()

"""Device-memory ledger — runtime residency observability.

Reference counterpart: ``MXGetGPUMemoryInformation64`` and the GPU
memory-pool env knobs — numbers you could only read, never correlate.
Here the ledger is the runtime twin of the static liveness scan in
``analysis/hlo/cost.py`` (``peak_live_bytes``): the scan predicts what a
graph *must* hold; this module measures what the process *does* hold —
``jax.live_arrays()`` residency, PjRt ``device.memory_stats()`` where
the backend exposes them, and per-site attribution from registered
providers (``trainer.step`` parameter/optimizer state,
``serve.compiled`` weights, the kvstore's parameter table) — published
as ``mxtpu_memory_*`` gauges on every :func:`sample`.

Three jobs:

- **Ledger**: :func:`sample` (manual or via the :func:`start` background
  sampler, interval ``MXTPU_MEMORY_SAMPLE_S``) reads live-array bytes +
  device stats + site providers, sets the gauges, and appends to a
  bounded history ring; :func:`snapshot` renders the whole state for
  ``telemetry.snapshot()`` and flight bundles.
- **Leak watchdog**: a steady state whose live bytes grow monotonically
  across a full sample window (default 8 samples, >=1 MiB growth) emits
  one damped ``memory.leak`` warning event — the signal
  ``telemetry_check --forbid memory.leak`` gates on in CI. Chaos twin:
  ``fault.inject``'s ``leak`` knob retains device arrays at the
  ``trainer.step`` site so the watchdog is testable deterministically.
- **OOM forensics**: :func:`oom_guard` / :func:`record_oom` turn a
  ``RESOURCE_EXHAUSTED`` crash into exactly ONE flight-recorder bundle
  (reason ``resource_exhausted``) whose memory section holds the live
  ledger beside the static peaks staging noted via
  :func:`note_static_peak` — rendered by ``tools/postmortem.py``.

Budget: ``MXTPU_HBM_BUDGET`` (bytes; K/M/G suffixes) is the one chip
capacity every consumer shares — the MX709 static pass, the serve
staging preflight, the autotune feasibility constraint, and this
ledger's gauges/"free" arithmetic (``context.tpu_memory_info`` falls
back to it when PjRt exposes no stats).
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, Optional

from ..lockcheck import make_lock

__all__ = ["hbm_budget", "live_bytes", "device_bytes", "device_stats",
           "register_site", "note_static_peak", "static_peaks",
           "sample", "segment", "snapshot", "start", "stop",
           "start_from_env", "is_oom", "record_oom", "oom_guard", "reset"]

_LOCK = make_lock("telemetry.memory._LOCK")
#: (name, seq) -> zero-arg provider returning resident bytes for a site.
#: Providers registered off bound methods are held via WeakMethod so the
#: ledger never keeps a dead trainer/model alive; dead refs drop on the
#: next sample.
_SITES: Dict[tuple, Callable[[], Optional[int]]] = {}
_SEQ = itertools.count()
_STATIC_PEAKS: Dict[str, int] = {}
_HISTORY: deque = deque(maxlen=256)
_STATE: Dict[str, Any] = {"thread": None, "stop": None,
                          "leak_level": None, "oom_bundles": 0}

#: leak-watchdog window: this many consecutive samples of monotonic
#: non-decreasing live bytes with at least _LEAK_MIN_BYTES total growth
#: flag a steady-state leak (damped: re-flags only after ANOTHER
#: _LEAK_MIN_BYTES past the flagged level)
_LEAK_WINDOW = 8
_LEAK_MIN_BYTES = 1 << 20


def hbm_budget() -> Optional[int]:
    """``MXTPU_HBM_BUDGET`` in bytes, or ``None`` when unset — a
    re-export of :func:`~..util.hbm_budget_bytes` (the ONE budget read
    every gate shares) at the ledger surface."""
    from ..util import hbm_budget_bytes
    return hbm_budget_bytes()


def _sample_interval() -> float:
    from ..util import getenv
    try:
        return float(getenv("MXTPU_MEMORY_SAMPLE_S") or 0.0)
    except (TypeError, ValueError):
        return 0.0


# -- raw reads ---------------------------------------------------------------

def live_bytes() -> tuple:
    """``(bytes, count)`` over ``jax.live_arrays()`` — every device
    buffer the process holds a reference to. Per-array failures (a
    buffer deleted mid-walk) are skipped, not raised."""
    try:
        import jax
        arrays = jax.live_arrays()
    except Exception:  # noqa: BLE001 — ledger must never be the fault
        return 0, 0
    total = n = 0
    for a in arrays:
        try:
            total += int(a.nbytes)
            n += 1
        except Exception:  # noqa: BLE001 — deleted/donated buffer
            continue
    return total, n


def device_bytes(device) -> int:
    """Live-array bytes resident on ONE concrete jax device (the
    ``context.tpu_memory_info`` fallback when PjRt has no stats)."""
    try:
        import jax
        arrays = jax.live_arrays()
    except Exception:  # noqa: BLE001
        return 0
    total = 0
    for a in arrays:
        try:
            devs = a.devices() if callable(getattr(a, "devices", None)) \
                else {getattr(a, "device", None)}
            if device in devs:
                total += int(a.nbytes)
        except Exception:  # noqa: BLE001
            continue
    return total


def device_stats() -> Dict[str, Dict]:
    """PjRt ``memory_stats()`` per local device, where exposed (TPU/GPU
    backends; the CPU backend usually returns nothing)."""
    out: Dict[str, Dict] = {}
    try:
        import jax
        devs = jax.local_devices()
    except Exception:  # noqa: BLE001
        return out
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001
            stats = None
        if stats:
            out[str(d)] = {k: stats[k] for k in sorted(stats)}
    return out


# -- per-site attribution ----------------------------------------------------

def register_site(name: str, fn: Callable[[], Optional[int]]):
    """Register a zero-arg provider reporting ``name``'s resident bytes
    (``trainer.step`` registers its parameter+optimizer leaves,
    ``serve.compiled`` its weight buffers, ``kvstore`` its parameter
    table). Bound methods are held weakly — a collected owner silently
    drops off the ledger. Returns a zero-arg unregister callable."""
    if hasattr(fn, "__self__"):
        ref: Callable = weakref.WeakMethod(fn)
    else:
        def ref(f=fn):
            return f
    key = (str(name), next(_SEQ))
    with _LOCK:
        _SITES[key] = ref

    def unregister():
        with _LOCK:
            _SITES.pop(key, None)
    return unregister


def _site_bytes() -> Dict[str, int]:
    with _LOCK:
        items = list(_SITES.items())
    out: Dict[str, int] = {}
    dead = []
    for key, ref in items:
        fn = ref()
        if fn is None:
            dead.append(key)
            continue
        try:
            b = fn()
        except Exception:  # noqa: BLE001 — a broken provider is not a fault
            continue
        if b:
            out[key[0]] = out.get(key[0], 0) + int(b)
    if dead:
        with _LOCK:
            for key in dead:
                _SITES.pop(key, None)
    return out


def note_static_peak(site: str, peak_bytes: int) -> None:
    """Record a statically-predicted peak (the liveness scan's number)
    so OOM bundles show the prediction beside the measured ledger —
    staging notes the serve ladder here, the trainer its step graph."""
    with _LOCK:
        _STATIC_PEAKS[str(site)] = int(peak_bytes)


def static_peaks() -> Dict[str, int]:
    with _LOCK:
        return dict(_STATIC_PEAKS)


# -- the ledger --------------------------------------------------------------

def _read() -> Dict:
    """One pure residency reading (no gauges, no history, no watchdog)
    — the side-effect-free half :func:`sample` and :func:`snapshot`
    share."""
    total, count = live_bytes()
    rec: Dict[str, Any] = {"ts": time.time(),
                           "live_bytes": int(total),
                           "live_arrays": int(count),
                           "sites": _site_bytes()}
    budget = hbm_budget()
    if budget:
        rec["budget"] = int(budget)
    dstats = device_stats()
    if dstats:
        rec["device_bytes_in_use"] = int(sum(
            s.get("bytes_in_use", 0) for s in dstats.values()))
        rec["device_bytes_limit"] = int(sum(
            s.get("bytes_limit", 0) for s in dstats.values()))
    return rec


def sample() -> Dict:
    """Take one ledger sample: read residency, publish the
    ``mxtpu_memory_*`` gauges, append to the history ring, and run the
    leak watchdog. Returns the sample dict (strict-JSON safe). This is
    the ONE entry that feeds the watchdog window — read-only surfaces
    (:func:`snapshot`, flight bundles) never pollute its cadence."""
    from . import metrics as _metrics
    rec = _read()
    sites = rec["sites"]
    _metrics.gauge("mxtpu_memory_live_bytes",
                   "Total live jax-array bytes held by this process"
                   ).set(float(rec["live_bytes"]))
    _metrics.gauge("mxtpu_memory_live_arrays",
                   "Live jax arrays held by this process"
                   ).set(float(rec["live_arrays"]))
    for site, b in sorted(sites.items()):
        _metrics.gauge("mxtpu_memory_site_bytes",
                       "Resident bytes attributed to one runtime site",
                       site=site).set(float(b))
    with _LOCK:
        # a site that vanished (collected provider, freed buffers) must
        # read 0, not its last non-zero value, on every later scrape
        gone = _STATE.setdefault("published_sites", set()) - set(sites)
        _STATE["published_sites"].update(sites)
    for site in sorted(gone):
        _metrics.gauge("mxtpu_memory_site_bytes",
                       "Resident bytes attributed to one runtime site",
                       site=site).set(0.0)
    if rec.get("budget"):
        _metrics.gauge("mxtpu_memory_budget_bytes",
                       "Configured HBM budget (MXTPU_HBM_BUDGET)"
                       ).set(float(rec["budget"]))
    if rec.get("device_bytes_in_use") is not None:
        _metrics.gauge("mxtpu_memory_device_bytes_in_use",
                       "PjRt bytes_in_use summed over local devices"
                       ).set(float(rec["device_bytes_in_use"]))
        _metrics.gauge("mxtpu_memory_device_bytes_limit",
                       "PjRt bytes_limit summed over local devices"
                       ).set(float(rec["device_bytes_limit"]))
    with _LOCK:
        _HISTORY.append(rec)
        window = list(_HISTORY)[-_LEAK_WINDOW:]
        leak = _leak_verdict(window)
        if leak is not None:
            _STATE["leak_level"] = leak["live_bytes"]
    if leak is not None:
        from . import events as _events
        _events.emit("memory.leak", severity="warning", **leak)
        _metrics.counter("mxtpu_memory_leak_events_total",
                         "Steady-state memory-growth warnings").inc()
    return rec


def _leak_verdict(window) -> Optional[Dict]:
    """Leak decision over the newest sample window (caller holds the
    lock): monotonic non-decreasing live bytes across a FULL window with
    >= ``_LEAK_MIN_BYTES`` total growth. Damped — after flagging, the
    level must grow another ``_LEAK_MIN_BYTES`` to re-flag; a drop
    below the flagged level re-arms."""
    if len(window) < _LEAK_WINDOW:
        return None
    vals = [w["live_bytes"] for w in window]
    level = _STATE["leak_level"]
    if any(b < a for a, b in zip(vals, vals[1:])):
        if level is not None and vals[-1] < level:
            _STATE["leak_level"] = None      # re-arm after a real drop
        return None
    growth = vals[-1] - vals[0]
    if growth < _LEAK_MIN_BYTES:
        return None
    if level is not None and vals[-1] < level + _LEAK_MIN_BYTES:
        return None                          # already flagged hereabouts
    return {"live_bytes": vals[-1], "growth_bytes": int(growth),
            "window_samples": len(vals),
            "window_s": round(window[-1]["ts"] - window[0]["ts"], 3)}


def segment() -> Dict:
    """The lightweight per-step-report view: current residency + site
    attribution (no device walk of stats, no history) — embedded as the
    ``memory`` segment of ``profiler.step_report``."""
    total, count = live_bytes()
    return {"live_bytes": int(total), "live_arrays": int(count),
            "sites": _site_bytes()}


def snapshot() -> Dict:
    """Everything the ledger knows — the ``memory`` section of flight
    bundles and ``telemetry.snapshot()``. A READ: the fresh residency
    reading here bypasses the gauges, the history ring, and the leak
    watchdog, so snapshot-driven pollers (monitoring loops, repeated
    flight dumps) can never shrink the watchdog's sample window or
    emit events as a side effect."""
    rec = _read()
    with _LOCK:
        hist = list(_HISTORY)[-32:]
        doc = {"current": rec,
               "budget": rec.get("budget"),
               "static_peaks": dict(_STATIC_PEAKS),
               "history": hist,
               "leak": {"flagged_level": _STATE["leak_level"],
                        "window_samples": _LEAK_WINDOW,
                        "min_growth_bytes": _LEAK_MIN_BYTES},
               "sampler_running": (_STATE["thread"] is not None
                                   and _STATE["thread"].is_alive()),
               "oom_bundles": _STATE["oom_bundles"]}
    doc["device"] = device_stats()
    return doc


# -- background sampler ------------------------------------------------------

def _run(interval_s: float, stop_ev: threading.Event) -> None:
    while not stop_ev.wait(interval_s):
        try:
            sample()
        except Exception:  # noqa: BLE001 — the sampler must not die loudly
            continue


def start(interval_s: Optional[float] = None) -> Optional[threading.Thread]:
    """Start the background sampler (named daemon thread
    ``mx-memory-ledger``). ``interval_s=None`` reads
    ``MXTPU_MEMORY_SAMPLE_S``; a non-positive interval means "ledger
    off" and returns None. Idempotent while a sampler is alive."""
    if interval_s is None:
        interval_s = _sample_interval()
    if not interval_s or interval_s <= 0:
        return None
    with _LOCK:
        t = _STATE["thread"]
        if t is not None and t.is_alive():
            return t
        stop_ev = threading.Event()
        t = threading.Thread(target=_run, args=(float(interval_s), stop_ev),
                             name="mx-memory-ledger", daemon=True)
        _STATE["thread"], _STATE["stop"] = t, stop_ev
    t.start()
    return t


def start_from_env() -> Optional[threading.Thread]:
    """Start the sampler iff ``MXTPU_MEMORY_SAMPLE_S`` > 0 (the
    serve_bench / CI memory-smoke entry)."""
    return start(None)


def stop() -> None:
    with _LOCK:
        t, ev = _STATE["thread"], _STATE["stop"]
        _STATE["thread"] = _STATE["stop"] = None
    if ev is not None:
        ev.set()
    if t is not None and t.is_alive():
        t.join(timeout=5.0)


# -- OOM forensics -----------------------------------------------------------

#: substrings marking a device allocator failure across jax/XLA versions
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM when allocating")


def is_oom(exc: BaseException) -> bool:
    """Whether an exception is a device out-of-memory (XLA surfaces
    these as ``RESOURCE_EXHAUSTED`` RuntimeErrors)."""
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _OOM_MARKERS)


def record_oom(exc: BaseException, site: Optional[str] = None,
               **context) -> Optional[str]:
    """One OOM → one flight bundle (reason ``resource_exhausted``): the
    bundle's memory section carries the live ledger beside the noted
    static peaks, so the post-mortem shows prediction and measurement
    on one page. Deduped on the exception object — an OOM re-raised
    through nested :func:`oom_guard` layers writes exactly one bundle.
    Returns the bundle path (None when the recorder is off)."""
    if getattr(exc, "_mxtpu_oom_recorded", False):
        return None
    try:
        exc._mxtpu_oom_recorded = True
    except Exception:  # noqa: BLE001 — slotted exceptions: dedupe best-effort
        pass
    from . import events as _events
    from . import flight as _flight
    from . import metrics as _metrics
    err = str(exc)
    _events.emit("memory.oom", severity="error", site=site,
                 error=err[:400], **context)
    _metrics.counter("mxtpu_memory_oom_total",
                     "Device RESOURCE_EXHAUSTED crashes recorded",
                     site=site or "unknown").inc()
    path = _flight.dump("resource_exhausted", site=site,
                        error=err[:400], **context)
    with _LOCK:
        _STATE["oom_bundles"] += 1
    return path


@contextlib.contextmanager
def oom_guard(site: str, **context):
    """Wrap a dispatch site (``trainer.step``, ``serve.compiled``): a
    ``RESOURCE_EXHAUSTED`` escaping the block is recorded
    (:func:`record_oom`) and re-raised unchanged. Non-OOM exceptions
    pass through untouched; the happy path costs one try/except."""
    try:
        yield
    except BaseException as e:  # noqa: BLE001 — classify, record, re-raise
        if is_oom(e):
            record_oom(e, site=site, **context)
        raise


def reset() -> None:
    """Reset history, leak state, OOM count and static peaks (tests).
    Registered site providers survive — they belong to live objects."""
    with _LOCK:
        _HISTORY.clear()
        _STATIC_PEAKS.clear()
        _STATE["leak_level"] = None
        _STATE["oom_bundles"] = 0

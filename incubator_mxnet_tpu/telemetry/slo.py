"""SLO specs + multi-window burn-rate monitoring over the metrics registry.

Reference counterpart: none — the reference had no metrics plane, let
alone objectives on top of one. This repo's registry (PR 4) already
carries everything an on-call needs — serve latency histograms, shed and
failover counters, train step timing — but raw series answer "what is
the p99 *now*", not "are we eating the month's error budget *fast
enough to page*". This module closes that gap the SRE-workbook way:

- an :class:`SLO` is a **declarative spec** naming registry series — a
  good/bad counter ratio (shed rate, failover rate) or a latency
  histogram + threshold (serve p99, train step budget) — plus an
  objective (e.g. 0.99 = at most 1% bad);
- an :class:`SLOMonitor` samples the cumulative series on every
  :meth:`~SLOMonitor.evaluate` call, keeps a time-stamped history ring,
  and computes the **burn rate** over multiple windows: burn 1.0 means
  "exactly spending the budget", 14.4 over an hour means "the 30-day
  budget is gone in 2 days" (the classic page threshold);
- a breach — every window over its threshold at once, the multi-window
  AND that suppresses both stale and blip alerts — emits an
  ``slo.burn`` event (severity ``error``) and flips the
  ``mxtpu_slo_breach`` gauge; every evaluation refreshes the
  ``mxtpu_slo_burn_rate`` gauges, so the scrape shows burn trajectory
  continuously, not only at alert time.

``serve_bench`` and the chaos drill consult :meth:`SLOMonitor.gate` as
a pass/fail gate: an HA drill that "recovers" while silently shedding
10% of traffic fails its availability SLO even though every individual
assertion passed.

Latency SLOs are reservoir-estimated: the histogram keeps a uniform
sample of the full stream, so "bad" (above-threshold) counts are
``seen x above-threshold reservoir fraction`` — exact for the ratio the
alert needs, without per-request threshold counters on the hot path.

Short histories degrade gracefully: a window longer than the recorded
history evaluates over what exists (a bench's 10-second run can still
gate on its 60-second window spec), and one-sample histories burn 0.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..lockcheck import make_lock

__all__ = ["SLO", "SLOMonitor", "default_slos", "default_windows"]


def default_windows() -> Tuple[Tuple[float, float], ...]:
    """``MXTPU_SLO_WINDOWS`` (``"sec:burn,sec:burn"``; default
    ``60:14.4,300:6`` — scaled-down analogues of the workbook's
    1h/6h pair for jobs that live minutes, not months)."""
    from ..util import getenv
    spec = getenv("MXTPU_SLO_WINDOWS") or "60:14.4,300:6"
    out = []
    try:
        for part in spec.split(","):
            w, b = part.strip().split(":")
            out.append((float(w), float(b)))
    except ValueError as e:
        raise ValueError(f"bad MXTPU_SLO_WINDOWS {spec!r}: {e}") from e
    return tuple(out)


class SLO:
    """One objective over registry series.

    ``kind="ratio"``: ``bad``/``total`` name cumulative counters (str or
    sequence of str; values are summed across every labelset of each
    name). Bad fraction over a window = Δbad / Δtotal.

    ``kind="latency"``: ``series`` names a histogram; a sample above
    ``threshold_ms`` is bad. ``total`` defaults to the histogram's own
    count.

    ``objective`` is the good-fraction target (0.99 = 1% error budget).
    ``windows`` overrides :func:`default_windows` per SLO.
    """

    def __init__(self, name: str, objective: float, kind: str = "ratio",
                 bad: Sequence[str] = (), total: Sequence[str] = (),
                 series: Optional[str] = None,
                 threshold_ms: Optional[float] = None,
                 windows: Optional[Sequence[Tuple[float, float]]] = None,
                 description: str = ""):
        if kind not in ("ratio", "latency"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        if kind == "latency" and (series is None or threshold_ms is None):
            raise ValueError("latency SLO needs series= and threshold_ms=")
        if kind == "ratio" and (not bad or not total):
            raise ValueError("ratio SLO needs bad= and total= series")
        self.name = name
        self.objective = float(objective)
        self.kind = kind
        self.bad = (bad,) if isinstance(bad, str) else tuple(bad)
        self.total = (total,) if isinstance(total, str) else tuple(total)
        self.series = series
        self.threshold_ms = threshold_ms
        self.windows = tuple(windows) if windows is not None else None
        self.description = description

    @property
    def budget(self) -> float:
        """The error budget: the bad fraction the objective tolerates."""
        return 1.0 - self.objective

    # -- cumulative (bad, total) sampling --------------------------------
    def sample(self, registry) -> Tuple[float, float]:
        """Current cumulative ``(bad, total)`` from the registry. Pure
        read; missing series read as 0 (a job that never registered the
        serve tier simply has no serve traffic)."""
        if self.kind == "ratio":
            return (self._sum(registry, self.bad),
                    self._sum(registry, self.total))
        bad = total = 0.0
        for inst in registry.instruments():
            if inst.name != self.series or inst.kind != "histogram":
                continue
            seen, samples = inst.reservoir_snapshot()
            if not samples:
                continue
            frac = (sum(1 for s in samples if s > self.threshold_ms)
                    / len(samples))
            bad += seen * frac
            total += seen
        return bad, total

    @staticmethod
    def _sum(registry, names: Tuple[str, ...]) -> float:
        tot = 0.0
        for inst in registry.instruments():
            if inst.name in names and inst.kind in ("counter", "gauge"):
                tot += inst.value
        return tot

    def __repr__(self):
        tgt = (f"p under {self.threshold_ms}ms" if self.kind == "latency"
               else f"{'+'.join(self.bad)}/{'+'.join(self.total)}")
        return f"SLO({self.name!r}, {self.objective:g} of {tgt})"


class SLOMonitor:
    """Evaluates a set of :class:`SLO`\\ s against the live registry.

    Call :meth:`evaluate` on a cadence (the serve bench calls it per
    progress tick; a trainer can hang it off the step loop or
    :meth:`start` a background thread). Every call appends one
    cumulative sample per SLO to a bounded history ring and recomputes
    windowed burn rates from the deltas — the registry itself stays
    cumulative and monotonic.
    """

    def __init__(self, slos: Optional[Sequence[SLO]] = None,
                 registry=None):
        from . import metrics as _metrics
        self.registry = registry if registry is not None \
            else _metrics.REGISTRY
        self.slos: List[SLO] = list(default_slos() if slos is None
                                    else slos)
        self._lock = make_lock("SLOMonitor._lock")
        #: per-SLO history of (mono_ts, bad, total), oldest first
        self._hist: Dict[str, List[Tuple[float, float, float]]] = {}
        self._last: Dict[str, Dict] = {}
        self._default_windows = default_windows()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- evaluation ------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """Sample every SLO, compute per-window burn rates, emit alerts.

        Returns one report dict per SLO: ``{slo, objective, kind,
        bad, total, bad_fraction, budget, burn (per window), breach,
        budget_remaining}``. Breaches emit ``slo.burn`` (severity
        ``error``); recoveries emit it once at ``info``."""
        from . import events as _events
        from . import metrics as _metrics
        now = time.monotonic() if now is None else now
        reports = []
        for slo in self.slos:
            bad, total = slo.sample(self.registry)
            windows = slo.windows or self._default_windows
            with self._lock:
                hist = self._hist.setdefault(slo.name, [])
                hist.append((now, bad, total))
                # keep just past the longest window (plus slack for
                # uneven cadences)
                horizon = now - max(w for w, _ in windows) * 2
                while len(hist) > 2 and hist[1][0] <= horizon:
                    hist.pop(0)
                snap = list(hist)
                prev_breach = self._last.get(slo.name, {}).get("breach",
                                                              False)
            burns = {}
            breach = bool(windows)
            for win_s, threshold in windows:
                burn = self._burn(snap, now - win_s, slo.budget)
                burns[f"{win_s:g}s"] = {"burn": round(burn, 4),
                                        "threshold": threshold,
                                        "over": burn > threshold}
                if not burn > threshold:
                    breach = False
            frac = bad / total if total else 0.0
            rep = {"slo": slo.name, "kind": slo.kind,
                   "objective": slo.objective,
                   "description": slo.description,
                   "bad": round(bad, 3), "total": round(total, 3),
                   "bad_fraction": round(frac, 6),
                   "budget": round(slo.budget, 6),
                   "budget_remaining": round(1.0 - frac / slo.budget, 4)
                   if slo.budget else None,
                   "burn": burns, "breach": breach}
            if slo.kind == "latency":
                rep["threshold_ms"] = slo.threshold_ms
            reports.append(rep)
            with self._lock:
                self._last[slo.name] = rep
            # gauges refresh every evaluation — burn trajectory is a
            # scrapeable series, not only an alert-time artifact
            for wname, b in burns.items():
                self.registry.gauge(
                    "mxtpu_slo_burn_rate",
                    "SLO error-budget burn rate per window",
                    slo=slo.name, window=wname).set(b["burn"])
            self.registry.gauge(
                "mxtpu_slo_breach",
                "1 while the SLO's multi-window burn alert "
                "is firing", slo=slo.name).set(float(breach))
            self.registry.gauge(
                "mxtpu_slo_bad_fraction",
                "Cumulative bad-event fraction",
                slo=slo.name).set(frac)
            if breach:
                _events.emit("slo.burn", severity="error", slo=slo.name,
                             objective=slo.objective,
                             bad_fraction=round(frac, 6), burn=burns)
            elif prev_breach:
                _events.emit("slo.burn", severity="info", slo=slo.name,
                             recovered=True, burn=burns)
        return reports

    @staticmethod
    def _burn(hist: List[Tuple[float, float, float]], t_from: float,
              budget: float) -> float:
        """Burn rate over [t_from, newest]: windowed bad fraction divided
        by the budget. The window anchor is the newest sample at or
        before ``t_from`` (falling back to the oldest recorded — a short
        history evaluates over what exists)."""
        if len(hist) < 2 or budget <= 0:
            return 0.0
        anchor = hist[0]
        for ent in hist:
            if ent[0] <= t_from:
                anchor = ent
            else:
                break
        t1, bad1, total1 = hist[-1]
        _, bad0, total0 = anchor
        dt_total = total1 - total0
        if dt_total <= 0:
            return 0.0
        frac = max(0.0, bad1 - bad0) / dt_total
        return frac / budget

    def report(self) -> Dict:
        """Latest evaluation per SLO (empty before the first
        :meth:`evaluate`)."""
        with self._lock:
            return {name: dict(rep) for name, rep in self._last.items()}

    def gate(self) -> Tuple[bool, Dict]:
        """(ok, report) — the serve_bench / chaos-drill pass/fail hook.
        Runs one fresh evaluation; ok is False when any SLO breaches."""
        reports = self.evaluate()
        return (not any(r["breach"] for r in reports),
                {r["slo"]: r for r in reports})

    # -- optional background cadence -------------------------------------
    def start(self, period_s: float = 10.0) -> "SLOMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(period_s):
                try:
                    self.evaluate()
                except Exception:  # noqa: BLE001 — the monitor must not
                    pass           # take down what it watches

        self._thread = threading.Thread(target=loop, name="mx-slo-monitor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=2)
        self._thread = None


def default_slos() -> List[SLO]:
    """The built-in objectives for the train+serve tier. Thresholds come
    from ``MXTPU_SLO_SERVE_P99_MS`` / ``MXTPU_SLO_STEP_MS`` /
    ``MXTPU_SLO_OBJECTIVE`` so a deployment tunes numbers, not code."""
    from ..util import getenv

    def _f(name, fallback):
        try:
            return float(getenv(name))
        except (TypeError, ValueError):
            return fallback

    obj = min(0.99999, max(0.5, _f("MXTPU_SLO_OBJECTIVE", 0.99)))
    return [
        SLO("serve-latency", objective=obj, kind="latency",
            series="mxtpu_serve_latency_ms",
            threshold_ms=_f("MXTPU_SLO_SERVE_P99_MS", 250.0),
            description="serve requests complete under the latency "
                        "threshold"),
        # USER-VISIBLE outcomes only: the router's terminal counters.
        # Replica-level rejects/failed batches (mxtpu_serve_*) are NOT
        # bad here — a queue-full bounce or chaos-killed batch that the
        # router's failover completes was never visible to the caller,
        # and counting it would breach this SLO on a run with zero lost
        # requests. Single-process (router-less) tiers surface those
        # same events AS the caller-visible error, but emit no router
        # series — the availability SLO is a statement about the tier
        # that owns admission.
        SLO("serve-availability", objective=obj, kind="ratio",
            bad=("mxtpu_router_sheds_total",
                 "mxtpu_router_deadline_exceeded_total",
                 "mxtpu_router_failed_total"),
            # requests_total counts every arrival pre-admission, so it
            # already contains the bad outcomes — a true fraction
            total=("mxtpu_router_requests_total",),
            description="requests neither shed, failed, nor timed out"),
        SLO("serve-failover-rate", objective=obj, kind="ratio",
            bad=("mxtpu_router_failovers_total",),
            total=("mxtpu_router_requests_total",),
            description="requests served without a failover retry"),
        SLO("train-step-time", objective=obj, kind="latency",
            series="mxtpu_train_step_ms",
            threshold_ms=_f("MXTPU_SLO_STEP_MS", 60000.0),
            description="training steps complete inside the step-time "
                        "budget"),
        # decode streaming: inter-token latency is the user-perceived
        # cadence of a generation — two latency objectives over the same
        # histogram series, a tight median and a loose tail
        SLO("decode-itl-p50", objective=0.5, kind="latency",
            series="mxtpu_decode_itl_ms",
            threshold_ms=_f("MXTPU_SLO_ITL_P50_MS", 100.0),
            description="median inter-token latency of decode streams "
                        "stays under the p50 threshold"),
        SLO("decode-itl-p99", objective=obj, kind="latency",
            series="mxtpu_decode_itl_ms",
            threshold_ms=_f("MXTPU_SLO_ITL_P99_MS", 500.0),
            description="tail inter-token latency of decode streams "
                        "stays under the p99 threshold"),
    ]

"""Structured event bus — the spine of ``mx.telemetry``.

Reference counterpart: none. The reference observed itself through the
C++ profiler and scattered ``LOG(INFO)`` lines; every subsystem here grew
its own island (profiler spans, serve metrics, watchdog warnings, chaos
logs). This bus is the one place they all publish *machine-readable*
events into, so "what is this job doing right now" is a single
``telemetry.snapshot()`` — the PyGraph position (arXiv 2503.19779)
generalized: on a jit runtime the interesting failures (recompiles,
capture misses, silent stalls) leave no exception, only a timeline.

Design:

- ``emit(kind, **fields)`` appends one :class:`Event` carrying a global
  monotonic sequence number, wall + monotonic timestamps, a severity, and
  the current **correlation ids** (training step / serving request id)
  taken from a thread-local context unless passed explicitly. Emission is
  a lock + deque append — cheap enough for per-request call sites.
- per-kind **ring buffers** (``MXTPU_TELEMETRY_RING`` entries each) bound
  memory on a long-lived server; aggregate counts keep counting past the
  ring, so drops are visible, never silent.
- **subscribers** (the export sinks) observe every event at emit time; a
  raising subscriber is counted and skipped, never allowed to break the
  emitting subsystem.
- ``MXTPU_TELEMETRY=0`` turns ``emit`` into a no-op (one dict lookup);
  the first real emission auto-installs env-configured sinks
  (``export.install_from_env``).

Event kinds in the wired runtime: ``train.step``, ``guard``, ``watchdog``,
``chaos``, ``kvstore``, ``serve.admit`` / ``serve.batch`` /
``serve.execute`` / ``serve.reply`` / ``serve.reject`` / ``serve.load`` /
``serve.drain`` / ``serve.prewarm``, ``router.health`` /
``router.failover`` / ``router.shed`` / ``router.hedge`` /
``router.weight_sync`` (the HA serve tier), ``compile``,
``amp.loss_scale``. Kinds are open — any string works.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..lockcheck import make_lock

__all__ = ["Event", "EventBus", "BUS", "emit", "events", "counts",
           "clear", "subscribe", "unsubscribe", "enabled", "enable",
           "step_scope", "request_scope", "current_step",
           "current_request"]

#: severity ladder (events carry one; sinks/filters may threshold)
SEVERITIES = ("debug", "info", "warning", "error")


class Event:
    """One telemetry record. Immutable by convention; ``to_dict()`` is the
    wire form every sink serializes (strict-JSON safe after
    :func:`~incubator_mxnet_tpu.telemetry.export.sanitize`)."""

    __slots__ = ("seq", "kind", "severity", "ts", "mono", "step",
                 "request_id", "trace_id", "span_id", "fields")

    def __init__(self, seq: int, kind: str, severity: str, ts: float,
                 mono: float, step: Optional[int],
                 request_id: Optional[str], fields: Dict,
                 trace_id: Optional[str] = None,
                 span_id: Optional[str] = None):
        self.seq = seq
        self.kind = kind
        self.severity = severity
        self.ts = ts            # wall clock (epoch seconds) — sink ordering
        self.mono = mono        # monotonic — duration math
        self.step = step        # training-step correlation id
        self.request_id = request_id  # serving-request correlation id
        self.trace_id = trace_id      # distributed-trace correlation
        self.span_id = span_id        # (active span when emitted)
        self.fields = fields

    def to_dict(self) -> Dict:
        d = {"seq": self.seq, "kind": self.kind, "severity": self.severity,
             "ts": round(self.ts, 6), "mono": round(self.mono, 6)}
        if self.step is not None:
            d["step"] = self.step
        if self.request_id is not None:
            d["request_id"] = self.request_id
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
            d["span_id"] = self.span_id
        if self.fields:
            d["fields"] = self.fields
        return d

    def __repr__(self):
        corr = (f", step={self.step}" if self.step is not None else "") + \
            (f", request={self.request_id}" if self.request_id else "")
        return f"Event(#{self.seq} {self.kind}/{self.severity}{corr})"


# -- correlation context (thread-local) -------------------------------------
_CTX = threading.local()


def current_step() -> Optional[int]:
    return getattr(_CTX, "step", None)


def current_request() -> Optional[str]:
    return getattr(_CTX, "request_id", None)


class step_scope:
    """Bind a training-step id to every event emitted on this thread::

        with telemetry.step_scope(trainer.num_update):
            ...  # chaos/guard/kvstore events inherit the step id
    """

    def __init__(self, step: int):
        self._step = step
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_CTX, "step", None)
        _CTX.step = self._step
        return self

    def __exit__(self, *exc):
        _CTX.step = self._prev


class request_scope:
    """Bind a serving-request correlation id (thread-local), mirroring
    :class:`step_scope`."""

    def __init__(self, request_id: str):
        self._rid = request_id
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_CTX, "request_id", None)
        _CTX.request_id = self._rid
        return self

    def __exit__(self, *exc):
        _CTX.request_id = self._prev


# -- the bus ----------------------------------------------------------------
class EventBus:
    """Bounded, thread-safe, per-kind ring buffers + subscriber fan-out."""

    #: consecutive failures after which a subscriber is muted
    MAX_SUBSCRIBER_FAILURES = 8
    #: first mute window (seconds); doubles per further failed probe,
    #: capped at 60s — muted, never evicted, so a sink that heals (the
    #: JSONL sink reopening after a full disk drains) gets its stream back
    SUBSCRIBER_MUTE_BASE_S = 1.0

    def __init__(self, ring: Optional[int] = None):
        from ..util import getenv
        self.ring = int(ring if ring is not None
                        else getenv("MXTPU_TELEMETRY_RING"))
        self._lock = make_lock("EventBus._lock")
        self._rings: Dict[str, deque] = {}
        self._counts: Dict[str, int] = {}
        self._seq = itertools.count(1)
        self._subscribers: List[Callable[[Event], None]] = []
        #: subscriber exceptions swallowed (a sink must never break the
        #: emitting subsystem)
        self.subscriber_errors = 0
        #: per-subscriber consecutive-failure streaks (id(sub) keyed)
        self._sub_failures: Dict[int, int] = {}
        #: id(sub) -> monotonic deadline before which the sub is skipped
        self._sub_muted: Dict[int, float] = {}

    def emit(self, kind: str, severity: str = "info",
             step: Optional[int] = None, request_id: Optional[str] = None,
             **fields) -> Optional[Event]:
        if severity not in SEVERITIES:
            raise ValueError(f"unknown severity {severity!r}; "
                             f"choose from {SEVERITIES}")
        # events born on worker threads carry the thread name: a serve
        # flush, a PS handler, and the watchdog all publish into one
        # stream, and "which thread said this" is the first question a
        # concurrency timeline gets asked
        tname = threading.current_thread().name
        if tname != "MainThread" and "thread" not in fields:
            fields["thread"] = tname
        from . import trace as _trace
        tctx = _trace.current()
        ev = Event(next(self._seq), kind, severity, time.time(),
                   time.monotonic(),
                   step if step is not None else current_step(),
                   request_id if request_id is not None
                   else current_request(),
                   fields,
                   trace_id=tctx.trace_id if tctx is not None else None,
                   span_id=tctx.span_id if tctx is not None else None)
        with self._lock:
            ring = self._rings.get(kind)
            if ring is None:
                ring = self._rings[kind] = deque(maxlen=self.ring)
            ring.append(ev)
            self._counts[kind] = self._counts.get(kind, 0) + 1
            subs = list(self._subscribers)
        # subscribers run OUTSIDE the lock: a slow sink must not
        # serialize emitters, and a sink that emits must not deadlock
        for sub in subs:
            until = self._sub_muted.get(id(sub))
            if until is not None and time.monotonic() < until:
                continue               # muted: skip, probe again later
            try:
                sub(ev)
            except Exception:  # noqa: BLE001 — sinks must not break emitters
                self._note_subscriber_error(sub)
            else:
                # per-sub membership first (GIL-safe read, same pattern
                # as the mute check above) so a healthy sink's success
                # never takes the lock even while ANOTHER sink is wedged
                if id(sub) in self._sub_failures or id(sub) in self._sub_muted:
                    # reset the streak under the lock, and only when no
                    # mute window is ACTIVE: a stale success from a
                    # thread descheduled before the sink wedged must not
                    # cancel the mute another thread just engaged (an
                    # expired window means this success was the healing
                    # probe, so unmuting is correct)
                    with self._lock:
                        until = self._sub_muted.get(id(sub))
                        if until is None or time.monotonic() >= until:
                            self._sub_failures.pop(id(sub), None)
                            self._sub_muted.pop(id(sub), None)
        return ev

    def _note_subscriber_error(self, sub) -> None:
        """Isolate one failing subscriber: count it (attribute + the
        ``mxtpu_telemetry_subscriber_errors_total`` registry counter so
        the scrape can alert on it), and MUTE a sink that fails many
        times in a row — a wedged sink must not tax every future emit on
        the trainer/serve threads, let alone break them. Muting is a
        backoff, not an eviction: the sub is probed again after the
        window (doubling per failed probe, capped at 60s), so a sink
        that heals — the JSONL sink reopening once a full disk drains —
        gets its stream back instead of staying dark for the process
        lifetime."""
        with self._lock:
            self.subscriber_errors += 1
            n = self._sub_failures.get(id(sub), 0) + 1
            self._sub_failures[id(sub)] = n
            muted = n >= self.MAX_SUBSCRIBER_FAILURES
            if muted:
                # exponent capped BEFORE pow: a sink that never heals
                # keeps failing probes for the process lifetime, and
                # 2.0**1024 would raise OverflowError out of the very
                # isolation path that must not throw
                window = min(
                    60.0, self.SUBSCRIBER_MUTE_BASE_S
                    * (2.0 ** min(n - self.MAX_SUBSCRIBER_FAILURES, 16)))
                self._sub_muted[id(sub)] = time.monotonic() + window
            first_mute = muted and n == self.MAX_SUBSCRIBER_FAILURES
        try:
            from . import metrics as _metrics
            _metrics.counter(
                "mxtpu_telemetry_subscriber_errors_total",
                "Event-bus subscriber exceptions swallowed (the flush "
                "path never propagates them)").inc()
        except Exception:  # noqa: BLE001 — error accounting must not
            pass           # itself become an error source
        if first_mute:
            import warnings
            warnings.warn(
                f"[telemetry] subscriber {sub!r} muted after "
                f"{self.MAX_SUBSCRIBER_FAILURES} consecutive failures; "
                "it will be probed again with backoff (events emitted "
                "while muted are lost to it)")

    def events(self, kind: Optional[str] = None,
               n: Optional[int] = None) -> List[Event]:
        """Newest-last events — one kind's ring, or every ring merged by
        sequence number. ``n`` keeps only the newest n."""
        with self._lock:
            if kind is not None:
                out = list(self._rings.get(kind, ()))
            else:
                out = sorted((e for r in self._rings.values() for e in r),
                             key=lambda e: e.seq)
        return out[-n:] if n else out

    def counts(self) -> Dict[str, int]:
        """Total emitted per kind (keeps counting past the ring cap)."""
        with self._lock:
            return dict(self._counts)

    def dropped(self) -> Dict[str, int]:
        """Events emitted but no longer in the ring, per kind."""
        with self._lock:
            return {k: self._counts[k] - len(self._rings.get(k, ()))
                    for k in self._counts}

    def subscribe(self, fn: Callable[[Event], None]) -> Callable:
        with self._lock:
            self._subscribers.append(fn)
        return fn

    def unsubscribe(self, fn: Callable[[Event], None]) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)
            # id() keys can be recycled by the allocator once fn is
            # collected — a later subscriber at the same address must
            # not inherit this one's failure streak or mute window
            self._sub_failures.pop(id(fn), None)
            self._sub_muted.pop(id(fn), None)

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()
            self._counts.clear()


#: the process-wide bus every wired subsystem publishes into
BUS = EventBus()

_ENABLED: Optional[bool] = None
_ENV_SINKS_INSTALLED = False
_ENV_SINKS_LOCK = make_lock("events._ENV_SINKS_LOCK")


def _reset_env_sinks_flag() -> None:
    """Re-arm env-sink installation (``export.uninstall_all`` calls this
    so a reset bus re-installs ``MXTPU_TELEMETRY_JSONL`` on next emit)."""
    global _ENV_SINKS_INSTALLED
    with _ENV_SINKS_LOCK:
        _ENV_SINKS_INSTALLED = False


def enabled() -> bool:
    """Master switch: ``MXTPU_TELEMETRY`` env (cached) unless overridden
    by :func:`enable`."""
    global _ENABLED
    if _ENABLED is None:
        from ..util import getenv
        _ENABLED = getenv("MXTPU_TELEMETRY") not in ("0", "false", "off")
    return _ENABLED


def enable(on: bool = True) -> None:
    """Programmatic override of the env switch (tests, notebooks)."""
    global _ENABLED
    _ENABLED = bool(on)


def emit(kind: str, severity: str = "info", step: Optional[int] = None,
         request_id: Optional[str] = None, **fields) -> Optional[Event]:
    """Publish one event on the global :data:`BUS` (no-op when telemetry
    is disabled). The first real emission installs env-configured sinks
    (``MXTPU_TELEMETRY_JSONL``)."""
    if not enabled():
        return None
    global _ENV_SINKS_INSTALLED
    if not _ENV_SINKS_INSTALLED:
        # double-checked under a lock: two threads racing the first
        # emission must not both run install (a double-installed sink
        # writes every line twice)
        with _ENV_SINKS_LOCK:
            if not _ENV_SINKS_INSTALLED:
                _ENV_SINKS_INSTALLED = True
                from . import export
                try:
                    export.install_from_env()
                except Exception as e:  # noqa: BLE001 — a telemetry
                    # config typo (bad path / MAX_MB) must not crash the
                    # emitting subsystem's first step/request
                    import warnings
                    warnings.warn(f"[telemetry] env sink install failed "
                                  f"({type(e).__name__}: {e}); the "
                                  "JSONL stream is disabled for this run")
    return BUS.emit(kind, severity=severity, step=step,
                    request_id=request_id, **fields)


def events(kind: Optional[str] = None, n: Optional[int] = None):
    return BUS.events(kind, n)


#: package-level alias (``telemetry.events`` is this module, so the
#: package re-exports the listing function under this name)
get_events = events


def counts() -> Dict[str, int]:
    return BUS.counts()


def clear() -> None:
    BUS.clear()


def subscribe(fn: Callable[[Event], None]) -> Callable:
    return BUS.subscribe(fn)


def unsubscribe(fn: Callable[[Event], None]) -> None:
    BUS.unsubscribe(fn)

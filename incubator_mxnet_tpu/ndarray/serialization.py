"""dmlc-stream NDArray serialization — upstream ``.params`` compatibility.

Reference: ``src/ndarray/ndarray.cc (NDArray::Save/Load)`` +
``MXNDArraySave`` (src/c_api/c_api.cc) and SURVEY §5.4 ("keep `.params` file
import for ecosystem weight compatibility"). Wire layout (all little-endian):

File (kMXAPINDArrayListMagic list container)::

    uint64  0x112 (list magic)      uint64  0 (reserved)
    uint64  n_arrays                n_arrays × <NDArray record>
    uint64  n_names                 n_names × (uint64 len + utf-8 bytes)

NDArray record (V2 0xF993FAC9 / V3 0xF993FACA; V1 0xF993FAC8 and the
pre-magic legacy layout are load-only)::

    uint32  version magic
    int32   storage type (0 = dense; sparse records are load-rejected)
    uint32  ndim   +  int64 × ndim          (TShape, dim_t = int64 in 1.x)
    int32   dev_type   int32   dev_id       (Context::Save)
    int32   type flag (kFloat32=0 ... kBfloat16=12)
    raw     data bytes, C-contiguous

Writing always emits V2 dense records, so files produced here load into
upstream MXNet 1.x (`mx.nd.load`) and vice versa. The previous pickle
container is still read transparently (magic mismatch → pickle fallback).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Sequence, Union

import numpy as onp

from ..base import MXNetError

__all__ = ["dmlc_save", "dmlc_load", "DMLC_LIST_MAGIC", "NotDmlcFile"]


class NotDmlcFile(MXNetError):
    """The file is not a dmlc .params container at all (magic mismatch /
    too short for the header) — the only condition that may fall back to
    another loader. Real parse errors inside a genuine container raise
    plain MXNetError and must surface."""

DMLC_LIST_MAGIC = 0x112
_ND_V1 = 0xF993FAC8
_ND_V2 = 0xF993FAC9
_ND_V3 = 0xF993FACA

# mshadow type flags (include/mxnet/base.h TypeFlag)
_FLAG_TO_DTYPE = {
    0: "float32", 1: "float64", 2: "float16", 3: "uint8", 4: "int32",
    5: "int8", 6: "int64", 7: "bool", 8: "int16", 9: "uint16",
    10: "uint32", 11: "uint64", 12: "bfloat16",
}
_DTYPE_TO_FLAG = {v: k for k, v in _FLAG_TO_DTYPE.items()}


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes
        return onp.dtype(ml_dtypes.bfloat16)
    return onp.dtype(name)


def _write_ndarray(f, arr: onp.ndarray) -> None:
    name = "bfloat16" if arr.dtype.name == "bfloat16" else arr.dtype.name
    if name not in _DTYPE_TO_FLAG:
        raise MXNetError(f"dtype {name} has no dmlc type flag")
    arr = onp.ascontiguousarray(arr)
    if arr.ndim == 0:
        # upstream has no 0-d arrays (ndim==0 marks a "none" record that
        # carries no ctx/dtype/data) — promote scalars the way nd.array does
        arr = arr.reshape(1)
    f.write(struct.pack("<I", _ND_V2))
    f.write(struct.pack("<i", 0))                       # kDefaultStorage
    f.write(struct.pack("<I", arr.ndim))
    f.write(struct.pack(f"<{arr.ndim}q", *arr.shape))
    f.write(struct.pack("<ii", 1, 0))                   # Context: cpu(0)
    f.write(struct.pack("<i", _DTYPE_TO_FLAG[name]))
    f.write(arr.tobytes())


def _read_exact(f, n: int) -> bytes:
    # Corrupt-size guard for LARGE reads only (a crafted record can declare
    # a 2^45-element shape): never allocate more than the file can supply.
    # Small field reads skip the fstat — f.read() itself bounds them.
    if n > (1 << 20):
        import os as _os
        try:
            remaining = _os.fstat(f.fileno()).st_size - f.tell()
        except (OSError, AttributeError):
            remaining = None
        if remaining is not None and n > remaining:
            raise MXNetError("truncated dmlc NDArray stream")
    b = f.read(n)
    if len(b) != n:
        raise MXNetError("truncated dmlc NDArray stream")
    return b


def _read_ndarray(f) -> onp.ndarray:
    (magic,) = struct.unpack("<I", _read_exact(f, 4))
    if magic in (_ND_V2, _ND_V3):
        (stype,) = struct.unpack("<i", _read_exact(f, 4))
        if stype not in (0,):  # dense only; sparse = load-rejected
            raise MXNetError(
                f"sparse storage type {stype} in .params is not supported "
                "on the TPU build (dense-convert it in the source framework)")
        (ndim,) = struct.unpack("<I", _read_exact(f, 4))
        if ndim == 0:  # upstream "none" record: nothing else follows
            return onp.zeros((0,), "float32")
        shape = struct.unpack(f"<{ndim}q", _read_exact(f, 8 * ndim))
    elif magic == _ND_V1:
        (ndim,) = struct.unpack("<I", _read_exact(f, 4))
        if ndim == 0:
            return onp.zeros((0,), "float32")
        shape = struct.unpack(f"<{ndim}q", _read_exact(f, 8 * ndim))
    else:
        # legacy pre-magic layout: the uint32 just read IS ndim (uint32 dims)
        ndim = magic
        if ndim > 32:
            raise MXNetError("unrecognized NDArray record magic "
                             f"0x{magic:08x}")
        shape = struct.unpack(f"<{ndim}I", _read_exact(f, 4 * ndim))
    (dev_type, _dev_id) = struct.unpack("<ii", _read_exact(f, 8))
    (flag,) = struct.unpack("<i", _read_exact(f, 4))
    if flag not in _FLAG_TO_DTYPE:
        raise MXNetError(f"unknown dmlc type flag {flag}")
    dt = _np_dtype(_FLAG_TO_DTYPE[flag])
    n = 1
    for s in shape:
        n *= int(s)
    data = _read_exact(f, n * dt.itemsize)
    return onp.frombuffer(data, dtype=dt).reshape(shape).copy()


def _native_flags(arrays):
    """Per-array mshadow type flags for the native writer, or None when an
    array needs the Python path (unmapped dtype)."""
    flags = []
    for a in arrays:
        name = "bfloat16" if a.dtype.name == "bfloat16" else a.dtype.name
        if name not in _DTYPE_TO_FLAG:
            return None
        flags.append(_DTYPE_TO_FLAG[name])
    return flags


def dmlc_save(fname: str,
              arrays: Sequence[onp.ndarray],
              names: Sequence[str]) -> None:
    """Write the kMXAPINDArrayListMagic container (upstream `.params`).

    Uses the C++ writer (``native.params_save`` — NDArray::Save parity) when
    the shim is available; the Python path below is the fallback and the
    format's executable spec. Both emit byte-identical V2 containers
    (interop-tested).

    Atomicity: both writers target a same-directory temp file that is
    ``os.replace``\\ d into place only after a successful flush+fsync, so a
    crash mid-save (power loss, SIGKILL, a raised exception) can never
    leave a truncated ``.params`` file where a previous good one stood —
    the invariant ``Block.save_parameters`` and ``fault.checkpoint`` build
    on. The temp file lives beside the target (rename must not cross
    filesystems) and is removed on failure."""
    import os
    arrays = [onp.ascontiguousarray(a if a.ndim else a.reshape(1))
              for a in arrays]
    from .. import native
    from ..fault import inject as _inject
    flags = _native_flags(arrays)
    # the native writer handles all-named or all-unnamed saves; a partial
    # names list (error case surfaced at load) stays on the python writer
    if len(names) not in (0, len(arrays)):
        flags = None
    tmp = f"{fname}.tmp-{os.getpid()}"
    try:
        if flags is not None and native.available():
            wrote = True
            try:
                native.params_save(tmp, arrays, list(names), flags)
            except MXNetError:
                wrote = False  # fall through to the Python writer
            if wrote:
                _inject.crash("nd.save")
                os.replace(tmp, fname)
                return
        with open(tmp, "wb") as f:
            f.write(struct.pack("<QQ", DMLC_LIST_MAGIC, 0))
            f.write(struct.pack("<Q", len(arrays)))
            for a in arrays:
                _write_ndarray(f, a)
            _inject.crash("nd.save")   # chaos: die with a half-written temp
            f.write(struct.pack("<Q", len(names)))
            for s in names:
                b = s.encode("utf-8")
                f.write(struct.pack("<Q", len(b)))
                f.write(b)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, fname)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def dmlc_load(fname: str):
    """Read an upstream `.params` file → (list_of_arrays, list_of_names).

    Raises MXNetError if the list magic doesn't match (caller falls back to
    the pickle container). The C++ reader handles the common V2/V3 dense
    layout; V1/legacy/sparse records drop to this Python reader.
    """
    from .. import native
    if native.available():
        try:
            raw, names, flags = native.params_load(fname)
            arrays = []
            for (shape, data), flag in zip(raw, flags):
                if flag not in _FLAG_TO_DTYPE:
                    raise MXNetError(f"unknown dmlc type flag {flag}")
                dt = _np_dtype(_FLAG_TO_DTYPE[flag])
                if not shape:  # upstream "none" record
                    arrays.append(onp.zeros((0,), "float32"))
                    continue
                arrays.append(onp.frombuffer(data, dtype=dt)
                              .reshape(shape).copy())
            return arrays, names
        except (MXNetError, ValueError):
            # V1/legacy/sparse, non-dmlc, or corrupt-record payloads: the
            # python reader below is the arbiter (it raises NotDmlcFile
            # only on container-magic mismatch, MXNetError otherwise)
            pass
    with open(fname, "rb") as f:
        head = f.read(16)
        if len(head) != 16:
            raise NotDmlcFile(f"{fname}: too short for a dmlc .params file")
        magic, _reserved = struct.unpack("<QQ", head)
        if magic != DMLC_LIST_MAGIC:
            raise NotDmlcFile(f"{fname}: not a dmlc .params file")
        (n,) = struct.unpack("<Q", _read_exact(f, 8))
        arrays = [_read_ndarray(f) for _ in range(n)]
        names: List[str] = []
        rest = f.read(8)
        if rest:
            if len(rest) != 8:
                raise MXNetError("truncated dmlc NDArray stream")
            (nn,) = struct.unpack("<Q", rest)
            for _ in range(nn):
                (ln,) = struct.unpack("<Q", _read_exact(f, 8))
                names.append(_read_exact(f, ln).decode("utf-8"))
    return arrays, names

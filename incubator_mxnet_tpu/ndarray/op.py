"""Imperative op dispatch: the TPU-era ``Imperative::Invoke``.

Reference call stack (SURVEY §3.1): generated Python op → ctypes FFI →
``MXImperativeInvokeEx`` → ``Imperative::Invoke`` → engine push → device
kernel. Here the whole stack collapses to: unwrap NDArray handles → run the
registered pure JAX function (XLA dispatches asynchronously, giving the
engine's compute/host overlap for free) → wrap outputs → append a tape node
if autograd is recording.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import numpy as onp

from .. import autograd
from ..context import Context, current_context
from .ndarray import NDArray

__all__ = ["dispatch_op", "make_nd_op"]


def dispatch_op(pure_fn: Callable, arrays: Sequence[NDArray], kwargs, ctx: Context, name: str = ""):
    """Execute ``pure_fn(*values)`` and wrap outputs; record for autograd.

    When recording, the forward runs under ``jax.vjp`` so the pullback (with
    its residuals — the activations) is captured NOW: backward() replays
    only the reverse computation, never the forward. This is the reference's
    imperative memory/compute trade (activations live on the tape until
    backward) — without it every backward would re-execute every forward.
    """
    vals = [a._data for a in arrays]
    if autograd.is_recording():
        try:
            out, vjp_fn = jax.vjp(pure_fn, *vals)
        except TypeError:
            # non-differentiable op (e.g. integer outputs): plain dispatch
            out, vjp_fn = pure_fn(*vals), None
        multi = isinstance(out, (tuple, list))
        outs = [NDArray(o, ctx=ctx) for o in (out if multi else (out,))]
        autograd._record_node(pure_fn, arrays, vals, outs, name,
                              vjp_fn=vjp_fn, multi=multi)
        return outs if multi else outs[0]
    out = pure_fn(*vals)
    multi = isinstance(out, (tuple, list))
    outs = [NDArray(o, ctx=ctx) for o in (out if multi else (out,))]
    return outs if multi else outs[0]


def make_nd_op(opdef):
    """Generate the ``mx.nd.<op>`` wrapper from a registered pure op
    (reference: python/mxnet/ndarray/register.py code-gen)."""

    fn = opdef.fn
    opname = opdef.name
    # Ops may flag tensor params whose VALUES shape the output (e.g.
    # boolean_mask's mask): these must stay concrete, so they are demoted to
    # trace constants instead of tape inputs — the op remains differentiable
    # in its other inputs while the flagged one never sees a tracer.
    static_names = getattr(fn, "static_tensor_inputs", ())
    if static_names:
        import inspect
        argnames = tuple(inspect.signature(fn).parameters)

    def nd_op(*args, out=None, **kwargs):
        # `name`/`ctx` are accepted for API parity with generated MXNet ops
        kwargs.pop("name", None)
        ctx = kwargs.pop("ctx", None)
        if static_names:
            args = tuple(
                a._data if (isinstance(a, NDArray) and i < len(argnames)
                            and argnames[i] in static_names) else a
                for i, a in enumerate(args))
            kwargs = {k: (v._data if (k in static_names
                                      and isinstance(v, NDArray)) else v)
                      for k, v in kwargs.items()}
        # Normalize: convert raw numpy/lists in tensor positions. NDArrays
        # passed by keyword (e.g. LeakyReLU(x, gamma=alpha)) are tape inputs
        # too — gradients must flow through them.
        arr_pos = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
        arr_keys = [k for k, a in kwargs.items() if isinstance(a, NDArray)]
        if not arr_pos and not arr_keys:
            raise TypeError(f"{opname} expects at least one NDArray argument")
        ctx = ctx or (args[arr_pos[0]] if arr_pos else
                      kwargs[arr_keys[0]]).context
        arrays = [args[i] for i in arr_pos] + [kwargs[k] for k in arr_keys]
        static_args = list(args)

        def pure(*vals):
            full = list(static_args)
            for i, v in zip(arr_pos, vals):
                full[i] = v
            kw = dict(kwargs)
            for k, v in zip(arr_keys, vals[len(arr_pos):]):
                kw[k] = v
            return fn(*full, **kw)

        result = dispatch_op(pure, arrays, kwargs, ctx, name=opname)
        if out is not None:
            if isinstance(out, NDArray):
                out._set_data(result._data if isinstance(result, NDArray) else result)
                return out
            for o, r in zip(out, result):
                o._set_data(r._data)
            return out
        return result

    nd_op.__name__ = opname
    nd_op.__qualname__ = opname
    nd_op.__doc__ = fn.__doc__
    return nd_op

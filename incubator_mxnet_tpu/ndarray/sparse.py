"""Sparse NDArray facade: ``row_sparse`` and ``csr`` storage types.

Reference: ``include/mxnet/ndarray.h`` storage types + ``python/mxnet/
ndarray/sparse.py``. SURVEY §7 scopes this explicitly: sparse layouts are
TPU-hostile (dynamic shapes defeat XLA tiling), so parity is a *host-side
facade* — compressed representations with correct semantics, converting to
dense at device-compute boundaries. Gradient sparsity for embeddings is
instead handled densely (XLA scatter-add is efficient on TPU).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context, current_context
from .ndarray import NDArray, array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "cast_storage", "zeros"]


class BaseSparseNDArray(NDArray):
    """Common base; behaves as its dense equivalent for compute."""

    __slots__ = ()

    def asnumpy(self):
        return super().asnumpy()


class RowSparseNDArray(BaseSparseNDArray):
    __slots__ = ("_indices",)

    def __init__(self, data, indices, shape=None, ctx=None, dtype=None):
        dense_rows = jnp.asarray(data, dtype=dtype)
        idx = jnp.asarray(indices, dtype=jnp.int32)
        if shape is None:
            shape = dense_rows.shape
        dense = jnp.zeros(tuple(shape), dense_rows.dtype).at[idx].set(dense_rows)
        super().__init__(dense, ctx=ctx)
        self._indices = idx

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._indices, ctx=self.context)

    @property
    def data(self) -> NDArray:
        return NDArray(self._data[self._indices], ctx=self.context)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, ctx=self.context)
        raise MXNetError(f"cast_storage row_sparse->{stype} unsupported")


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ("_indptr", "_col_indices", "_values")

    def __init__(self, data, indptr, indices, shape, ctx=None, dtype=None):
        vals = jnp.asarray(data, dtype=dtype)
        indptr = jnp.asarray(indptr, dtype=jnp.int32)
        col = jnp.asarray(indices, dtype=jnp.int32)
        dense = onp.zeros(tuple(shape), dtype=onp.dtype(str(vals.dtype)))
        ip = onp.asarray(indptr)
        cl = onp.asarray(col)
        vl = onp.asarray(vals)
        for r in range(shape[0]):
            for j in range(int(ip[r]), int(ip[r + 1])):
                dense[r, int(cl[j])] = vl[j]
        super().__init__(jnp.asarray(dense), ctx=ctx)
        self._indptr, self._col_indices, self._values = indptr, col, vals

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._indptr, ctx=self.context)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._col_indices, ctx=self.context)

    @property
    def data(self) -> NDArray:
        return NDArray(self._values, ctx=self.context)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, ctx=self.context)
        raise MXNetError(f"cast_storage csr->{stype} unsupported")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(data, indices, shape=shape, ctx=ctx, dtype=dtype)
    dense = onp.asarray(arg1._data if isinstance(arg1, NDArray) else arg1)
    nz = onp.where(onp.abs(dense).reshape(dense.shape[0], -1).sum(axis=1) > 0)[0]
    return RowSparseNDArray(dense[nz], nz, shape=dense.shape, ctx=ctx, dtype=dtype)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indptr, indices, shape, ctx=ctx, dtype=dtype)
    dense = onp.asarray(arg1._data if isinstance(arg1, NDArray) else arg1)
    indptr = [0]
    cols, vals = [], []
    for r in range(dense.shape[0]):
        nz = onp.nonzero(dense[r])[0]
        cols.extend(nz.tolist())
        vals.extend(dense[r][nz].tolist())
        indptr.append(len(cols))
    return CSRNDArray(onp.array(vals, dense.dtype), onp.array(indptr), onp.array(cols),
                      dense.shape, ctx=ctx, dtype=dtype)


def cast_storage(arr: NDArray, stype: str):
    if stype == "default":
        return NDArray(arr._data, ctx=arr.context)
    if stype == "row_sparse":
        return row_sparse_array(arr, ctx=arr.context)
    if stype == "csr":
        if arr.ndim != 2:
            raise MXNetError("csr requires 2-D")
        return csr_matrix(arr, ctx=arr.context)
    raise MXNetError(f"unknown stype {stype}")


def zeros(stype, shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if stype == "row_sparse":
        return row_sparse_array(onp.zeros(shape), ctx=ctx, dtype=dtype)
    if stype == "csr":
        return csr_matrix(onp.zeros(shape), ctx=ctx, dtype=dtype)
    from . import zeros as dense_zeros
    return dense_zeros(shape, ctx=ctx, dtype=dtype)

"""Sparse NDArray facade: ``row_sparse`` and ``csr`` storage types.

Reference: ``include/mxnet/ndarray.h`` storage types + ``python/mxnet/
ndarray/sparse.py``. SURVEY §7 scopes this explicitly: sparse layouts are
TPU-hostile (dynamic shapes defeat XLA tiling), so parity is a *host-side
facade* — compressed representations with correct semantics, converting to
dense at device-compute boundaries. Gradient sparsity for embeddings is
instead handled densely (XLA scatter-add is efficient on TPU).
"""
from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context, current_context
from .ndarray import NDArray, array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "cast_storage", "dot", "zeros"]


def _check_dense_budget(shape, dtype) -> None:
    """The facade MATERIALIZES the dense array — refuse silently doing so
    past a budget (VERDICT r3 weak #7: a row_sparse facade over a 23M-row
    embedding table would otherwise allocate the whole table per pull).

    ``MXTPU_SPARSE_DENSE_LIMIT`` bytes, default 2 GiB; 0 disables. See
    docs/env_vars.md."""
    limit = int(os.environ.get("MXTPU_SPARSE_DENSE_LIMIT",
                               str(2 * 1024 ** 3)))
    if limit <= 0:
        return
    n = 1
    for d in shape:
        n *= int(d)
    nbytes = n * jnp.dtype(dtype or jnp.float32).itemsize
    if nbytes > limit:
        raise MXNetError(
            f"sparse facade: materializing dense {tuple(shape)} "
            f"({nbytes / 1e9:.2f} GB) exceeds MXTPU_SPARSE_DENSE_LIMIT "
            f"({limit / 1e9:.2f} GB). This build's sparse storage is a "
            "dense facade (SURVEY §7: sparse layouts are TPU-hostile); for "
            "large embedding tables use dense parameters with XLA "
            "scatter-add gradients (the default Embedding path), or raise "
            "the limit explicitly via MXTPU_SPARSE_DENSE_LIMIT (0 "
            "disables).")


class BaseSparseNDArray(NDArray):
    """Common base; behaves as its dense equivalent for compute."""

    __slots__ = ()

    def asnumpy(self):
        return super().asnumpy()


class RowSparseNDArray(BaseSparseNDArray):
    __slots__ = ("_indices",)

    def __init__(self, data, indices, shape=None, ctx=None, dtype=None):
        dense_rows = jnp.asarray(data, dtype=dtype)
        idx = jnp.asarray(indices, dtype=jnp.int32)
        if shape is None:
            shape = dense_rows.shape
        _check_dense_budget(shape, dense_rows.dtype)
        dense = jnp.zeros(tuple(shape), dense_rows.dtype).at[idx].set(dense_rows)
        super().__init__(dense, ctx=ctx)
        self._indices = idx

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._indices, ctx=self.context)

    @property
    def data(self) -> NDArray:
        return NDArray(self._data[self._indices], ctx=self.context)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, ctx=self.context)
        raise MXNetError(f"cast_storage row_sparse->{stype} unsupported")


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ("_indptr", "_col_indices", "_values", "_row_indices")

    def __init__(self, data, indptr, indices, shape, ctx=None, dtype=None):
        vals = jnp.asarray(data, dtype=dtype)
        indptr = jnp.asarray(indptr, dtype=jnp.int32)
        col = jnp.asarray(indices, dtype=jnp.int32)
        _check_dense_budget(shape, vals.dtype)
        dense = onp.zeros(tuple(shape), dtype=onp.dtype(str(vals.dtype)))
        ip = onp.asarray(indptr)
        cl = onp.asarray(col)
        vl = onp.asarray(vals)
        # Vectorized scatter: row index of every nonzero from the indptr
        # runs. Duplicate (row, col) entries accumulate — same contract as
        # the nnz-structured dot() below.
        rows = onp.repeat(onp.arange(int(shape[0])), onp.diff(ip))
        onp.add.at(dense, (rows, cl), vl)
        super().__init__(jnp.asarray(dense), ctx=ctx)
        self._indptr, self._col_indices, self._values = indptr, col, vals
        self._row_indices = jnp.asarray(rows, jnp.int32)

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._indptr, ctx=self.context)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._col_indices, ctx=self.context)

    @property
    def data(self) -> NDArray:
        return NDArray(self._values, ctx=self.context)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, ctx=self.context)
        raise MXNetError(f"cast_storage csr->{stype} unsupported")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(data, indices, shape=shape, ctx=ctx, dtype=dtype)
    dense = onp.asarray(arg1._data if isinstance(arg1, NDArray) else arg1)
    nz = onp.where(onp.abs(dense).reshape(dense.shape[0], -1).sum(axis=1) > 0)[0]
    return RowSparseNDArray(dense[nz], nz, shape=dense.shape, ctx=ctx, dtype=dtype)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indptr, indices, shape, ctx=ctx, dtype=dtype)
    dense = onp.asarray(arg1._data if isinstance(arg1, NDArray) else arg1)
    rows, cols = onp.nonzero(dense)
    vals = dense[rows, cols]
    counts = onp.bincount(rows, minlength=dense.shape[0])
    indptr = onp.concatenate([[0], onp.cumsum(counts)])
    return CSRNDArray(vals.astype(dense.dtype), indptr.astype(onp.int64),
                      cols.astype(onp.int64), dense.shape, ctx=ctx, dtype=dtype)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-dense matmul computed on the nonzero structure only.

    Reference parity: the csr kernels of ``src/operator/tensor/dot-inl.h``
    (``dot(csr, dense)`` and ``dot(csr.T, dense)``). TPU formulation: a
    gather of B rows by the nonzeros' column index followed by a
    segment-sum scatter-add — both static-shaped over nnz, so the whole
    contraction jits (no dynamic sparsity inside the compiled program).
    """
    if transpose_b:
        raise MXNetError("sparse dot: transpose_b is unsupported (reference "
                         "csr kernels are lhs-sparse only)")
    if not isinstance(lhs, CSRNDArray):
        raise MXNetError("sparse dot needs a CSR lhs; use dense dot otherwise")
    B = rhs._data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
    vals, rows, cols = lhs._values, lhs._row_indices, lhs._col_indices
    out_dtype = jnp.result_type(vals.dtype, B.dtype)
    contrib_shape = vals.shape + (1,) * (B.ndim - 1)
    if transpose_a:
        # out[k] += A[r, k] * B[r]  for every nonzero (r, k)
        out_rows = int(lhs.shape[1])
        contrib = vals.reshape(contrib_shape) * B[rows]
        out = jnp.zeros((out_rows,) + B.shape[1:], out_dtype).at[cols].add(contrib)
    else:
        # out[r] += A[r, c] * B[c]  for every nonzero (r, c)
        out_rows = int(lhs.shape[0])
        contrib = vals.reshape(contrib_shape) * B[cols]
        out = jnp.zeros((out_rows,) + B.shape[1:], out_dtype).at[rows].add(contrib)
    return NDArray(out, ctx=lhs.context)


def cast_storage(arr: NDArray, stype: str):
    if stype == "default":
        return NDArray(arr._data, ctx=arr.context)
    if stype == "row_sparse":
        return row_sparse_array(arr, ctx=arr.context)
    if stype == "csr":
        if arr.ndim != 2:
            raise MXNetError("csr requires 2-D")
        return csr_matrix(arr, ctx=arr.context)
    raise MXNetError(f"unknown stype {stype}")


def zeros(stype, shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if stype == "row_sparse":
        return row_sparse_array(onp.zeros(shape), ctx=ctx, dtype=dtype)
    if stype == "csr":
        return csr_matrix(onp.zeros(shape), ctx=ctx, dtype=dtype)
    from . import zeros as dense_zeros
    return dense_zeros(shape, ctx=ctx, dtype=dtype)

"""``mx.nd`` — the imperative NDArray namespace.

Reference parity: ``python/mxnet/ndarray/`` — the NDArray class plus every
registered op reflected into this module (register.py code-gen ≙
``make_nd_op`` over the op registry), creation ops, serialization
(``save``/``load`` — SURVEY §5.4), and the ``random`` submodule.
"""
from __future__ import annotations

import pickle
import sys
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..engine import waitall
from ..ops.registry import OPS
from .ndarray import NDArray, array, _unwrap, _dtype_of
from .op import dispatch_op, make_nd_op
from . import random  # noqa: F401
from . import sparse  # noqa: F401
# legacy flat sampling names (reference generated ops mx.nd.random_* /
# sample_multinomial / shuffle — src/operator/random/sample_op.cc)
from .random import (  # noqa: F401
    uniform as random_uniform, normal as random_normal,
    randint as random_randint, exponential as random_exponential,
    poisson as random_poisson, gamma as random_gamma,
    negative_binomial as random_negative_binomial,
    generalized_negative_binomial as random_generalized_negative_binomial,
    multinomial as sample_multinomial, shuffle,
)

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "linspace", "eye", "save", "load", "waitall", "concatenate",
           "imresize", "moveaxis", "from_numpy", "from_dlpack",
           "to_dlpack_for_read", "random_uniform", "random_normal",
           "random_randint", "random_exponential", "random_poisson",
           "random_gamma", "random_negative_binomial",
           "random_generalized_negative_binomial", "sample_multinomial",
           "shuffle"]

_this = sys.modules[__name__]

# Reflect every registered op into this namespace (mx.nd.<op>).
def refresh_ops() -> None:
    """(Re-)reflect the op registry into mx.nd — called again by modules
    that register ops after this one is imported (e.g. mx.operator)."""
    for _name, _opdef in list(OPS.items()):
        if not hasattr(_this, _name):
            setattr(_this, _name, make_nd_op(_opdef))


refresh_ops()

_dense_dot = _this.dot


def dot(lhs, rhs, transpose_a=False, transpose_b=False, **kwargs) -> NDArray:
    """dot with sparse dispatch: a CSR lhs routes to the nnz-structured
    kernel (sparse.dot), everything else to the dense MXU path."""
    from .sparse import CSRNDArray, dot as _sparse_dot
    if isinstance(lhs, CSRNDArray):
        return _sparse_dot(lhs, rhs, transpose_a=transpose_a,
                           transpose_b=transpose_b)
    return _dense_dot(lhs, rhs, transpose_a=transpose_a,
                      transpose_b=transpose_b, **kwargs)


# ---------------------------------------------------------------------------
# operator dispatch used by NDArray dunders
# ---------------------------------------------------------------------------

_SWAPPED = {"subtract": lambda a, b: b - a if False else None}


def _binary_dispatch(opname, lhs, rhs, reverse=False):
    op = getattr(_this, opname)
    if isinstance(rhs, (list, tuple)):
        rhs = array(rhs, ctx=lhs.context)
    if isinstance(rhs, onp.ndarray):
        rhs = array(rhs, ctx=lhs.context)
    a, b = (rhs, lhs) if reverse else (lhs, rhs)
    if not isinstance(a, NDArray):
        # scalar op array
        ctx = b.context

        def pure(bv):
            return OPS[opname].fn(a, bv)

        return dispatch_op(pure, [b], {}, ctx, name=opname)
    return op(a, b)


# ---------------------------------------------------------------------------
# creation ops (reference: init_op.cc)
# ---------------------------------------------------------------------------

def zeros(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(jnp.zeros(tuple(shape), _dtype_of(dtype)), ctx=ctx)


def ones(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(jnp.ones(tuple(shape), _dtype_of(dtype)), ctx=ctx)


def full(shape, val, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(jnp.full(tuple(shape), val, _dtype_of(dtype)), ctx=ctx)


def empty(shape, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx: Optional[Context] = None,
           dtype=None, infer_range=False) -> NDArray:
    ctx = ctx or current_context()
    out = jnp.arange(start, stop, step, _dtype_of(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return NDArray(out, ctx=ctx)


def linspace(start, stop, num, endpoint=True, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    ctx = ctx or current_context()
    return NDArray(jnp.linspace(start, stop, num, endpoint=endpoint, dtype=_dtype_of(dtype)), ctx=ctx)


def eye(N, M=0, k=0, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    ctx = ctx or current_context()
    return NDArray(jnp.eye(N, M if M else N, k=k, dtype=_dtype_of(dtype)), ctx=ctx)


def moveaxis(data, source, destination) -> NDArray:
    return dispatch_op(lambda d: jnp.moveaxis(d, source, destination), [data], {},
                       data.context, name="moveaxis")


def concatenate(arrays, axis=0, always_copy=True) -> NDArray:
    return _this.concat(*arrays, dim=axis)


def from_numpy(np_array, zero_copy=False) -> NDArray:
    return array(np_array)


class DLPackCarrier:
    """DLPack-protocol view over a device buffer (zero-copy interchange;
    reference: python/mxnet/dlpack.py). Modern consumers (np/torch/jax
    ``from_dlpack``) call ``__dlpack__``/``__dlpack_device__`` themselves —
    this object defers capsule creation to the consumer, which is the
    zero-copy contract (a pre-made capsule can be consumed only once)."""

    def __init__(self, arr):
        self._arr = arr

    def __dlpack__(self, **kwargs):
        return self._arr.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self._arr.__dlpack_device__()


def from_dlpack(dlpack) -> NDArray:
    """Accepts a DLPack-protocol object (anything with ``__dlpack__``) or a
    legacy PyCapsule (consumed via torch, one host copy)."""
    if hasattr(dlpack, "__dlpack__"):
        return NDArray(jnp.from_dlpack(dlpack))
    try:  # legacy capsule path: jax only accepts protocol objects
        import torch.utils.dlpack as _tdl
    except ImportError as e:
        raise MXNetError(
            "from_dlpack got a raw PyCapsule; consuming one needs torch "
            "(pass the producing array itself, or any object implementing "
            "__dlpack__, for the zero-copy path)") from e
    return NDArray(jnp.asarray(_tdl.from_dlpack(dlpack).detach().cpu().numpy()))


def to_dlpack_for_read(data: NDArray) -> DLPackCarrier:
    return DLPackCarrier(data._data)


to_dlpack_for_write = to_dlpack_for_read


def imresize(src, w, h, interp=1) -> NDArray:
    out = jax.image.resize(src._data, (h, w) + src.shape[2:],
                           method="bilinear" if interp else "nearest")
    return NDArray(out, ctx=src.context)


# ---------------------------------------------------------------------------
# serialization (reference: NDArray::Save/Load, src/ndarray/ndarray.cc;
# SURVEY §5.4). Default format: the upstream dmlc `.params` binary stream —
# files interchange with upstream MXNet 1.x mx.nd.save/load name-for-name.
# The earlier pickle container is read transparently on load.
# ---------------------------------------------------------------------------

_MAGIC = b"MXTPU_ND1\n"


def save(fname: str, data) -> None:
    from .serialization import dmlc_save
    if isinstance(data, NDArray):
        arrays, names = [data.asnumpy()], []
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [v.asnumpy() for v in data.values()]
    elif isinstance(data, (list, tuple)):
        arrays, names = [v.asnumpy() for v in data], []
    else:
        raise MXNetError("save expects NDArray, list of NDArray, or dict of str->NDArray")
    dmlc_save(fname, arrays, names)


def load(fname: str):
    from .serialization import NotDmlcFile, dmlc_load
    try:
        arrays, names = dmlc_load(fname)
    except NotDmlcFile:
        # only a container-magic mismatch falls back; parse errors inside a
        # genuine .params stream surface as-is
        return _load_pickle(fname)
    if names:
        if len(names) != len(arrays):
            raise MXNetError(f"{fname}: name/array count mismatch")
        return {n: NDArray(a) for n, a in zip(names, arrays)}
    return [NDArray(a) for a in arrays]


def _load_pickle(fname: str):
    with open(fname, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise MXNetError(f"{fname} is not a saved NDArray file")
        payload = pickle.load(f)
    if isinstance(payload, dict):
        return {k: array(v) for k, v in payload.items()}
    return [array(v) for v in payload]


def __getattr__(name):
    # mx.nd.contrib — lazy to avoid an import cycle (reference:
    # python/mxnet/ndarray/contrib.py; same module as mx.contrib.nd)
    if name == "contrib":
        from ..contrib import nd as _contrib_nd
        return _contrib_nd
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

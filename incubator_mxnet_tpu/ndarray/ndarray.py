"""NDArray: MXNet's mutable array surface over immutable XLA/PjRt buffers.

TPU-native counterpart of ``include/mxnet/ndarray.h`` + ``src/ndarray/
ndarray.cc`` and the Python frontend ``python/mxnet/ndarray/ndarray.py``.

Design (SURVEY §7 "Mutability vs XLA immutability"): an NDArray is a handle
holding a reference to an immutable ``jax.Array`` plus a version counter.
"Mutation" (``+=``, ``__setitem__``, optimizer updates) swaps the handle's
buffer for a functionally-updated one and bumps the version — the reference's
engine-var write-dependency discipline collapses into this single swap,
because XLA's async runtime already orders the underlying computations by
data dependence. ``WaitToRead`` ≙ ``block_until_ready``.

Views: basic indexing returns a *copy* (documented divergence: XLA buffers
cannot alias mutably); ``__setitem__`` provides the write path via
``.at[].set``. Autograd interplay: in-place mutation of an array recorded on
the autograd tape raises, as in the reference.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError, integer_types, numeric_types
from ..context import Context, cpu, current_context
from .. import autograd

__all__ = ["NDArray", "array", "_wrap", "_unwrap", "_dtype_of"]


def _dtype_of(dtype) -> jnp.dtype:
    if dtype is None:
        return jnp.dtype("float32")
    return jnp.dtype(dtype)


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


class NDArray:
    """A mutable n-dimensional array on a device Context."""

    __slots__ = ("_data", "_ctx", "_version", "_grad", "_grad_req",
                 "_fresh_grad_node", "_fresh_grad", "__weakref__")

    # numpy interop priority (so ndarray.__add__ defers to us)
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        if isinstance(data, NDArray):
            data = data._data
        if ctx is None:
            ctx = current_context()
        if not isinstance(data, jax.Array):
            # Host data: one hop straight onto the context's device (going
            # through jnp.asarray would land on the *default* backend first
            # and bounce — a sync round-trip when ctx is not the default).
            npdt = jnp.dtype(dtype) if dtype is not None else None
            data = jax.device_put(onp.asarray(data, dtype=npdt), ctx.jax_device)
        elif dtype is not None and data.dtype != jnp.dtype(dtype):
            data = data.astype(dtype)
        if isinstance(data, jax.core.Tracer):
            # Inside a jit trace (HybridBlock cached op): no device commit —
            # placement is the compiled executable's concern.
            self._data = data
            self._ctx = ctx
            self._version = 0
            self._grad = None
            self._grad_req = "null"
            self._fresh_grad_node = None
            self._fresh_grad = False
            return
        # Commit to the context's device if not already there.
        dev = ctx.jax_device
        devs = getattr(data, "devices", None)
        committed = getattr(data, "_committed", True)
        if devs is None or not committed or data.devices() != {dev}:
            if not (hasattr(data, "sharding") and len(getattr(data.sharding, "device_set", [1, 2])) > 1):
                data = jax.device_put(data, dev)
        self._data = data
        self._ctx = ctx
        self._version = 0
        self._grad = None
        self._grad_req = "null"
        self._fresh_grad_node = None
        # Set by autograd backward when it deposits into this array's grad
        # slot; cleared by Trainer updates (reference: NDArray fresh-grad
        # state behind MXNDArrayGetGradState).
        self._fresh_grad = False

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return onp.dtype(str(self._data.dtype))

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def size(self) -> int:
        return int(self._data.size)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def stype(self) -> str:
        return "default"

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def version(self) -> int:
        return self._version

    def __repr__(self):
        return f"\n{onp.asarray(self.asnumpy())}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an NDArray with multiple elements is ambiguous.")
        return bool(self.item())

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __index__(self):
        return int(self.item())

    def item(self):
        return self._data.item()

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # ------------------------------------------------------------------
    # sync / host transfer (engine WaitToRead parity)
    # ------------------------------------------------------------------
    def wait_to_read(self) -> None:
        """Block until pending writes complete (NDArray::WaitToRead)."""
        self._data.block_until_ready()

    def asnumpy(self) -> onp.ndarray:
        """Copy to host, synchronizing (the reference's sync point)."""
        return onp.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    # ------------------------------------------------------------------
    # mutation machinery
    # ------------------------------------------------------------------
    def _check_inplace_ok(self):
        if autograd.is_recording() and self._fresh_grad_node is not None:
            raise MXNetError(
                "In-place mutation of an array recorded on the autograd tape "
                "is not allowed (reference parity: inplace on recorded arrays)"
            )

    def _set_data(self, new_data) -> None:
        """Swap the underlying buffer (the 'mutation' primitive)."""
        self._check_inplace_ok()
        if not isinstance(new_data, jax.Array):
            new_data = jnp.asarray(new_data, self._data.dtype)
        self._data = new_data
        self._version += 1

    def _assign(self, value) -> None:
        """x[:] = value semantics."""
        v = _unwrap(value)
        v = jnp.broadcast_to(jnp.asarray(v, self._data.dtype), self.shape)
        self._set_data(v)

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def _index_for_jnp(self, key):
        if isinstance(key, NDArray):
            return _unwrap(key).astype(jnp.int32) if jnp.issubdtype(_unwrap(key).dtype, jnp.floating) else _unwrap(key)
        if isinstance(key, tuple):
            return tuple(self._index_for_jnp(k) if isinstance(k, NDArray) else k for k in key)
        return key

    def __getitem__(self, key) -> "NDArray":
        from .op import dispatch_op
        key = self._index_for_jnp(key)
        if isinstance(key, (int, onp.integer)):
            fn = lambda d: d[key]
        else:
            fn = lambda d: d[key]
        return dispatch_op(fn, (self,), {}, self._ctx, name="getitem")

    def __setitem__(self, key, value) -> None:
        key = self._index_for_jnp(key)
        v = _unwrap(value)
        if isinstance(v, (list, tuple)) or isinstance(v, onp.ndarray):
            v = jnp.asarray(v)
        if key is Ellipsis or key == slice(None):
            self._assign(value)
            return
        if isinstance(v, jax.Array) or isinstance(v, numeric_types):
            self._set_data(self._data.at[key].set(jnp.asarray(v, self._data.dtype) if not isinstance(v, numeric_types) else v))
        else:
            self._set_data(self._data.at[key].set(v))

    # ------------------------------------------------------------------
    # context / dtype moves
    # ------------------------------------------------------------------
    def as_in_context(self, context: Context) -> "NDArray":
        if context == self._ctx:
            return self
        return self.copyto(context)

    as_in_ctx = as_in_context

    def copyto(self, other: Union[Context, "NDArray"]) -> "NDArray":
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device), ctx=other)
        if isinstance(other, NDArray):
            if other is self:
                raise MXNetError("cannot copy an array onto itself")
            other._set_data(jax.device_put(self._data.astype(other._data.dtype), other._ctx.jax_device))
            return other
        raise TypeError(f"copyto does not support type {type(other)}")

    def copy(self) -> "NDArray":
        return NDArray(self._data, ctx=self._ctx)

    def astype(self, dtype, copy: bool = True) -> "NDArray":
        dt = jnp.dtype(dtype)
        if not copy and dt == self._data.dtype:
            return self
        from .op import dispatch_op
        return dispatch_op(lambda d: d.astype(dt), (self,), {}, self._ctx, name="astype")

    def tostype(self, stype: str) -> "NDArray":
        if stype == "default":
            return self
        from .sparse import cast_storage
        return cast_storage(self, stype)

    def detach(self) -> "NDArray":
        out = NDArray(self._data, ctx=self._ctx)
        return out

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype: Optional[str] = None) -> None:
        self._grad = NDArray(jnp.zeros(self.shape, self._data.dtype), ctx=self._ctx)
        self._grad_req = grad_req

    def backward(self, out_grad=None, retain_graph: bool = False, train_mode: bool = True) -> None:
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------------
    # shape ops as methods (delegate to the op namespace)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs) -> "NDArray":
        from . import reshape as _reshape
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if "shape" in kwargs:
            shape = kwargs["shape"]
        return _reshape(self, shape=shape)

    def reshape_like(self, other: "NDArray") -> "NDArray":
        return self.reshape(other.shape)

    def transpose(self, axes=None) -> "NDArray":
        from . import transpose as _transpose
        return _transpose(self, axes=axes)

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    def flatten(self) -> "NDArray":
        from . import flatten as _flatten
        return _flatten(self)

    def expand_dims(self, axis: int) -> "NDArray":
        from . import expand_dims as _ed
        return _ed(self, axis=axis)

    def squeeze(self, axis=None) -> "NDArray":
        from . import squeeze as _sq
        return _sq(self, axis=axis)

    def broadcast_to(self, shape) -> "NDArray":
        from . import broadcast_to as _bt
        return _bt(self, shape=shape)

    def broadcast_like(self, other) -> "NDArray":
        return self.broadcast_to(other.shape)

    def slice(self, begin, end, step=None) -> "NDArray":
        from . import slice as _slice
        return _slice(self, begin=begin, end=end, step=step)

    def slice_axis(self, axis, begin, end) -> "NDArray":
        from . import slice_axis as _sa
        return _sa(self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip") -> "NDArray":
        from . import take as _take
        return _take(self, indices, axis=axis, mode=mode)

    def pick(self, index, axis=-1, keepdims=False) -> "NDArray":
        from . import pick as _pick
        return _pick(self, index, axis=axis, keepdims=keepdims)

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32") -> "NDArray":
        from . import one_hot as _oh
        return _oh(self, depth=depth, on_value=on_value, off_value=off_value, dtype=dtype)

    def clip(self, a_min=None, a_max=None) -> "NDArray":
        from . import clip as _clip
        return _clip(self, a_min=a_min, a_max=a_max)

    def abs(self) -> "NDArray":
        from . import abs as _abs
        return _abs(self)

    def sign(self) -> "NDArray":
        from . import sign as _sign
        return _sign(self)

    def sqrt(self) -> "NDArray":
        from . import sqrt as _sqrt
        return _sqrt(self)

    def square(self) -> "NDArray":
        from . import square as _square
        return _square(self)

    def exp(self) -> "NDArray":
        from . import exp as _exp
        return _exp(self)

    def log(self) -> "NDArray":
        from . import log as _log
        return _log(self)

    def relu(self) -> "NDArray":
        from . import relu as _relu
        return _relu(self)

    def sigmoid(self) -> "NDArray":
        from . import sigmoid as _sigmoid
        return _sigmoid(self)

    def tanh(self) -> "NDArray":
        from . import tanh as _tanh
        return _tanh(self)

    def softmax(self, axis=-1) -> "NDArray":
        from . import softmax as _softmax
        return _softmax(self, axis=axis)

    def log_softmax(self, axis=-1) -> "NDArray":
        from . import log_softmax as _ls
        return _ls(self, axis=axis)

    def sum(self, axis=None, keepdims=False) -> "NDArray":
        from . import sum as _sum
        return _sum(self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False) -> "NDArray":
        from . import mean as _mean
        return _mean(self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False) -> "NDArray":
        from . import max as _max
        return _max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False) -> "NDArray":
        from . import min as _min
        return _min(self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False) -> "NDArray":
        from . import prod as _prod
        return _prod(self, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False) -> "NDArray":
        from . import argmax as _am
        return _am(self, axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False) -> "NDArray":
        from . import argmin as _am
        return _am(self, axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False) -> "NDArray":
        from . import norm as _norm
        return _norm(self, ord=ord, axis=axis, keepdims=keepdims)

    def dot(self, other) -> "NDArray":
        from . import dot as _dot
        return _dot(self, other)

    def to_dlpack_for_read(self):
        """DLPack-protocol view over the device buffer (zero-copy
        interchange; reference: python/mxnet/dlpack.py)."""
        from . import to_dlpack_for_read as _to
        return _to(self)

    to_dlpack_for_write = to_dlpack_for_read

    def as_nd_ndarray(self):
        return self

    def asnumpy_or_none(self):
        return self.asnumpy()

    # ------------------------------------------------------------------
    # arithmetic operators
    # ------------------------------------------------------------------
    def _binary(self, other, opname, reverse=False):
        from . import _binary_dispatch
        return _binary_dispatch(opname, self, other, reverse)

    def __add__(self, other):
        return self._binary(other, "add")

    def __radd__(self, other):
        return self._binary(other, "add", True)

    def __sub__(self, other):
        return self._binary(other, "subtract")

    def __rsub__(self, other):
        return self._binary(other, "subtract", True)

    def __mul__(self, other):
        return self._binary(other, "multiply")

    def __rmul__(self, other):
        return self._binary(other, "multiply", True)

    def __truediv__(self, other):
        return self._binary(other, "divide")

    def __rtruediv__(self, other):
        return self._binary(other, "divide", True)

    def __floordiv__(self, other):
        return self._binary(other, "floor_divide")

    def __rfloordiv__(self, other):
        return self._binary(other, "floor_divide", True)

    def __mod__(self, other):
        return self._binary(other, "mod")

    def __rmod__(self, other):
        return self._binary(other, "mod", True)

    def __pow__(self, other):
        return self._binary(other, "power")

    def __rpow__(self, other):
        return self._binary(other, "power", True)

    def __neg__(self):
        from . import negative
        return negative(self)

    def __abs__(self):
        return self.abs()

    def __eq__(self, other):
        if other is None:
            return False
        return self._binary(other, "equal")

    def __ne__(self, other):
        if other is None:
            return True
        return self._binary(other, "not_equal")

    def __lt__(self, other):
        return self._binary(other, "lesser")

    def __le__(self, other):
        return self._binary(other, "lesser_equal")

    def __gt__(self, other):
        return self._binary(other, "greater")

    def __ge__(self, other):
        return self._binary(other, "greater_equal")

    __hash__ = object.__hash__

    # in-place: swap buffer
    def __iadd__(self, other):
        self._set_data(self._data + jnp.asarray(_unwrap(other), self._data.dtype))
        return self

    def __isub__(self, other):
        self._set_data(self._data - jnp.asarray(_unwrap(other), self._data.dtype))
        return self

    def __imul__(self, other):
        self._set_data(self._data * jnp.asarray(_unwrap(other), self._data.dtype))
        return self

    def __itruediv__(self, other):
        self._set_data(self._data / jnp.asarray(_unwrap(other), self._data.dtype))
        return self


def array(source_array, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (mx.nd.array parity: python
    lists default to float32; numpy/NDArray sources keep their dtype)."""
    if isinstance(source_array, NDArray):
        dt = dtype or source_array.dtype
        return NDArray(source_array._data, ctx=ctx or source_array.context, dtype=dt)
    if dtype is None:
        if isinstance(source_array, (onp.ndarray, jax.Array)):
            dtype = source_array.dtype
            # TPU/x32: downcast 64-bit host arrays.
            if onp.dtype(dtype) == onp.float64:
                dtype = onp.float32
            elif onp.dtype(dtype) == onp.int64:
                dtype = onp.int32
        else:
            dtype = onp.float32
    return NDArray(jnp.asarray(onp.asarray(source_array), dtype=jnp.dtype(dtype)), ctx=ctx)


def _wrap(value, ctx: Context) -> NDArray:
    return NDArray(value, ctx=ctx)

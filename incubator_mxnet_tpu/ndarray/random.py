"""Random sampling ops (``mx.nd.random.*`` / ``mx.random`` parity).

Reference: ``src/operator/random/sample_op.cc`` + ``python/mxnet/ndarray/
random.py``. Each draw advances the per-Context stateful key stream
(../random.py) and closes over the drawn subkey, so a recorded tape replay
is deterministic (pure w.r.t. the snapshot).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import random as _rng
from ..context import Context, current_context
from .ndarray import NDArray, _unwrap
from .op import dispatch_op

__all__ = [
    "uniform", "normal", "randn", "randint", "exponential", "gamma",
    "poisson", "negative_binomial", "generalized_negative_binomial",
    "multinomial", "shuffle", "bernoulli",
]


def _ctx(ctx) -> Context:
    return ctx if ctx is not None else current_context()


def _dt(dtype):
    if dtype is None or dtype == "None":
        return jnp.float32
    return jnp.dtype(dtype)


def _maybe_param_shape(shape, *params):
    if shape is None:
        for p in params:
            if isinstance(p, NDArray):
                return p.shape
        return (1,)
    if isinstance(shape, int):
        shape = (shape,)
    return tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    ctx = _ctx(ctx)
    shape = _maybe_param_shape(shape, low, high)
    key = _rng.next_key(ctx)
    arrays = [a for a in (low, high) if isinstance(a, NDArray)]

    def pure(*vals):
        lo = vals[0] if isinstance(low, NDArray) else low
        hi = (vals[-1] if isinstance(high, NDArray) else high)
        u = jax.random.uniform(key, shape, _dt(dtype))
        return lo + u * (hi - lo)

    res = dispatch_op(pure, arrays, {}, ctx, name="random_uniform")
    if out is not None:
        out._set_data(res._data)
        return out
    return res


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    ctx = _ctx(ctx)
    shape = _maybe_param_shape(shape, loc, scale)
    key = _rng.next_key(ctx)
    arrays = [a for a in (loc, scale) if isinstance(a, NDArray)]

    def pure(*vals):
        mu = vals[0] if isinstance(loc, NDArray) else loc
        sd = (vals[-1] if isinstance(scale, NDArray) else scale)
        return mu + jax.random.normal(key, shape, _dt(dtype)) * sd

    res = dispatch_op(pure, arrays, {}, ctx, name="random_normal")
    if out is not None:
        out._set_data(res._data)
        return out
    return res


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None, **kwargs):
    return normal(loc=loc, scale=scale, shape=shape or (1,), dtype=dtype, ctx=ctx)


def randint(low, high, shape=(1,), dtype="int32", ctx=None, out=None, **kwargs):
    ctx = _ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    key = _rng.next_key(ctx)
    val = jax.random.randint(key, tuple(shape), int(low), int(high), jnp.dtype(dtype))
    res = NDArray(val, ctx=ctx)
    if out is not None:
        out._set_data(res._data)
        return out
    return res


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    ctx = _ctx(ctx)
    shape = _maybe_param_shape(shape, scale)
    key = _rng.next_key(ctx)
    arrays = [a for a in (scale,) if isinstance(a, NDArray)]

    def pure(*vals):
        lam = vals[0] if isinstance(scale, NDArray) else scale
        return jax.random.exponential(key, shape, _dt(dtype)) * lam

    res = dispatch_op(pure, arrays, {}, ctx, name="random_exponential")
    return res


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    ctx = _ctx(ctx)
    shape = _maybe_param_shape(shape, alpha, beta)
    key = _rng.next_key(ctx)
    arrays = [a for a in (alpha, beta) if isinstance(a, NDArray)]

    def pure(*vals):
        a = vals[0] if isinstance(alpha, NDArray) else alpha
        b = (vals[-1] if isinstance(beta, NDArray) else beta)
        return jax.random.gamma(key, a, shape, _dt(dtype)) * b

    return dispatch_op(pure, arrays, {}, ctx, name="random_gamma")


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    ctx = _ctx(ctx)
    shape = _maybe_param_shape(shape, lam)
    key = _rng.next_key(ctx)
    lam_v = _unwrap(lam) if isinstance(lam, NDArray) else lam
    val = jax.random.poisson(key, lam_v, tuple(shape)).astype(_dt(dtype))
    return NDArray(val, ctx=ctx)


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None, **kwargs):
    ctx = _ctx(ctx)
    shape = _maybe_param_shape(shape, k, p)
    key1, key2 = jax.random.split(_rng.next_key(ctx))
    kv = _unwrap(k) if isinstance(k, NDArray) else k
    pv = _unwrap(p) if isinstance(p, NDArray) else p
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    lam = jax.random.gamma(key1, kv, tuple(shape)) * (1.0 - pv) / pv
    val = jax.random.poisson(key2, lam).astype(_dt(dtype))
    return NDArray(val, ctx=ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None, ctx=None, **kwargs):
    ctx = _ctx(ctx)
    shape = _maybe_param_shape(shape, mu, alpha)
    muv = _unwrap(mu) if isinstance(mu, NDArray) else mu
    av = _unwrap(alpha) if isinstance(alpha, NDArray) else alpha
    key1, key2 = jax.random.split(_rng.next_key(ctx))
    r = 1.0 / av
    p = r / (r + muv)
    lam = jax.random.gamma(key1, r, tuple(shape)) * (1.0 - p) / p
    val = jax.random.poisson(key2, lam).astype(_dt(dtype))
    return NDArray(val, ctx=ctx)


def multinomial(data, shape=1, get_prob=False, dtype="int32", **kwargs):
    """Sample from categorical distributions given probabilities ``(K,)`` or
    ``(N, K)``; ``shape`` draws per distribution (int or tuple, preserved in
    the output: 1-D data → ``shape``, 2-D data → ``(N,) + shape``; the
    default int 1 squeezes the sample axis like the reference)."""
    ctx = data.context
    key = _rng.next_key(ctx)
    dims = (shape,) if isinstance(shape, int) else tuple(shape)
    n = 1
    for d in dims:
        n *= int(d)
    squeeze = isinstance(shape, int) and shape == 1
    logits = jnp.log(jnp.maximum(data._data, 1e-30))
    if data._data.ndim == 1:
        flat = jax.random.categorical(key, logits, shape=(n,))     # (n,)
        out = flat.reshape(()) if squeeze else flat.reshape(dims)
    else:
        N = data.shape[0]
        flat = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                      shape=(N, n))                # (N, n)
        out = flat[:, 0] if squeeze else flat.reshape((N,) + dims)
    res = NDArray(out.astype(jnp.dtype(dtype)), ctx=ctx)
    if get_prob:
        # logp must flow through the autograd tape (dispatch_op) — the
        # reference's documented use is REINFORCE, where the caller
        # backprops -logp * reward into the probabilities. The sampled
        # indices are a closed-over constant; only `data` carries gradient.
        idx = flat.astype(jnp.int32)

        def pure(d):
            lg = jnp.log(jnp.maximum(d, 1e-30))
            if d.ndim > 1:
                picked = jnp.take_along_axis(lg, idx, axis=-1)     # (N, n)
                return picked[:, 0] if squeeze \
                    else picked.reshape((d.shape[0],) + dims)
            picked = lg[idx]                                       # (n,)
            return picked.reshape(()) if squeeze else picked.reshape(dims)

        logp = dispatch_op(pure, [data], {}, ctx, name="sample_multinomial")
        return res, logp
    return res


def shuffle(data, **kwargs):
    ctx = data.context
    key = _rng.next_key(ctx)
    perm = jax.random.permutation(key, data.shape[0])

    def pure(d):
        return d[perm]

    return dispatch_op(pure, [data], {}, ctx, name="shuffle")


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, **kwargs):
    ctx = _ctx(ctx)
    shape = _maybe_param_shape(shape, prob)
    key = _rng.next_key(ctx)
    pv = _unwrap(prob) if isinstance(prob, NDArray) else prob
    return NDArray(jax.random.bernoulli(key, pv, tuple(shape)).astype(jnp.dtype(dtype)), ctx=ctx)


def seed(seed_state, ctx="all"):
    """Alias of mx.random.seed (reference: mx.nd.random.seed)."""
    _rng.seed(seed_state, ctx=ctx)

"""INT8 quantization subsystem (reference: ``src/operator/quantization/`` +
``python/mxnet/contrib/quantization.py`` — SURVEY §2.4).

Three pieces, mirroring the reference's pipeline:

1. **Calibration collectors** — run float inference over a calibration set
   recording per-layer input ranges: ``calib_mode='naive'`` keeps min/max;
   ``'entropy'`` builds histograms and picks the KL-divergence-optimal
   threshold (the reference's ``_LayerHistogramCollector`` /
   ``_get_optimal_threshold`` algorithm).
2. **Graph pass** — the reference rewrites the nnvm graph
   (``quantize_graph_pass.cc``); compiled execution here is jit-traced from
   the Block tree, so the equivalent pass swaps ``Dense`` / ``Conv2D``
   children for :class:`QuantizedDense` / :class:`QuantizedConv2D` whose
   weights are pre-quantized int8 and whose forward runs the int8 MXU ops
   (``ops/quantization.py``) with requantize/dequantize glue. The swap is
   in-place on the block tree and fully hybridizable — XLA sees one int8
   graph, which IS the quantized-graph pass in a trace-based world.
3. **User API** — :func:`quantize_net` (gluon; reference
   ``quantize_net_v2``), with per-layer exclusion and both calib modes.

Dequantized outputs stay within ~1% of fp32 for typical nets (tested in
``tests/test_quantization.py``).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as onp

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from .. import ndarray as nd

__all__ = ["quantize_net", "QuantizedDense", "QuantizedConv2D",
           "LayerRangeCollector", "Observer", "optimal_threshold"]


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def _smooth_distribution(p: onp.ndarray, eps: float = 1e-4) -> onp.ndarray:
    """Laplace-style smoothing so KL(p||q) is finite (reference:
    contrib/quantization.py _smooth_distribution)."""
    is_zeros = (p == 0).astype(onp.float32)
    is_nonzeros = (p != 0).astype(onp.float32)
    n_zeros = int(is_zeros.sum())
    n_nonzeros = p.size - n_zeros
    if n_nonzeros == 0:
        return None
    eps1 = eps * n_zeros / n_nonzeros
    hist = p.astype(onp.float32)
    hist += eps * is_zeros + (-eps1) * is_nonzeros
    return hist


def optimal_threshold(hist: onp.ndarray, hist_edges: onp.ndarray,
                      num_quantized_bins: int = 255) -> float:
    """KL-divergence-optimal |threshold| from a symmetric histogram
    (reference: _get_optimal_threshold, the classic TensorRT-style search).
    """
    num_bins = hist.size
    assert num_bins % 2 == 1, "use an odd bin count (symmetric around 0)"
    zero_bin = num_bins // 2
    hist = hist.astype(onp.float64)
    csum = onp.concatenate([[0.0], onp.cumsum(hist)])
    thresholds = []
    divergences = []
    # p grows outward from the zero bin; all inner work is vectorized
    # (bucket sums via cumsum, expansion via repeat) so the search is
    # O(candidates · bins) instead of the reference's python-loop square.
    for i in range(num_quantized_bins // 2 + 1, zero_bin + 1):
        p_start, p_stop = zero_bin - i, zero_bin + i + 1
        thresholds.append(hist_edges[p_stop])
        sliced = hist[p_start:p_stop]
        p = sliced.copy()
        p[0] += csum[p_start]                      # left outliers
        p[-1] += csum[-1] - csum[p_stop]           # right outliers
        # quantize p's support down to num_quantized_bins buckets
        edges = onp.round(onp.linspace(0, sliced.size, num_quantized_bins + 1)
                          ).astype(onp.int64)
        starts = edges[:-1]
        widths = onp.diff(edges)
        q = onp.add.reduceat(sliced, starts)
        q[widths == 0] = 0.0
        nz_cnt = onp.add.reduceat((sliced != 0).astype(onp.float64), starts)
        nz_cnt[widths == 0] = 0.0
        # expand q back over p's support, mass split over nonzero slots
        per_slot = onp.divide(q, nz_cnt, out=onp.zeros_like(q),
                              where=nz_cnt > 0)
        q_exp = onp.repeat(per_slot, widths) * (sliced != 0)
        ps = _smooth_distribution(p / max(p.sum(), 1e-30))
        qs = _smooth_distribution(q_exp / max(q_exp.sum(), 1e-30))
        if ps is None or qs is None:
            divergences.append(onp.inf)
            continue
        divergences.append(float(
            onp.sum(ps * onp.log(onp.maximum(ps, 1e-30) /
                                 onp.maximum(qs, 1e-30)))))
    if not divergences:
        return float(hist_edges[-1])
    return float(thresholds[int(onp.argmin(divergences))])


class LayerRangeCollector:
    """Collects per-layer input calibration statistics via forward hooks.

    naive: running min/max. entropy: 8001-bin symmetric histogram per layer,
    threshold picked by :func:`optimal_threshold` at the end.
    """

    def __init__(self, mode: str = "naive", num_bins: int = 8001):
        if mode not in ("naive", "entropy"):
            raise MXNetError(f"unknown calib_mode {mode!r}")
        self.mode = mode
        self.num_bins = num_bins
        self.minmax: Dict[str, Tuple[float, float]] = {}
        self.hists: Dict[str, Tuple[onp.ndarray, onp.ndarray]] = {}

    def collect(self, name: str, x: onp.ndarray) -> None:
        amin, amax = float(x.min()), float(x.max())
        if name in self.minmax:
            lo, hi = self.minmax[name]
            self.minmax[name] = (min(lo, amin), max(hi, amax))
        else:
            self.minmax[name] = (amin, amax)
        if self.mode == "entropy":
            th = max(abs(amin), abs(amax), 1e-8)
            if name in self.hists:
                hist, edges = self.hists[name]
                old_th = edges[-1]
                if th > old_th:
                    # rebuild on the wider range, re-binning the old mass
                    centers = (edges[:-1] + edges[1:]) / 2
                    new_hist, new_edges = onp.histogram(
                        centers, bins=self.num_bins, range=(-th, th),
                        weights=hist)
                    h, _ = onp.histogram(x.ravel(), bins=self.num_bins,
                                         range=(-th, th))
                    self.hists[name] = (new_hist + h, new_edges)
                else:
                    h, _ = onp.histogram(x.ravel(), bins=self.num_bins,
                                         range=(-old_th, old_th))
                    self.hists[name] = (hist + h, edges)
            else:
                h, edges = onp.histogram(x.ravel(), bins=self.num_bins,
                                         range=(-th, th))
                self.hists[name] = (h, edges)

    def ranges(self) -> Dict[str, Tuple[float, float]]:
        if self.mode == "naive":
            return dict(self.minmax)
        out = {}
        for name, (hist, edges) in self.hists.items():
            th = optimal_threshold(hist, edges)
            out[name] = (-th, th)
        return out


class Observer:
    """Calibration observer over ``telemetry.numerics`` hist-mode tables —
    the bridge from live-traffic numerics telemetry to the int8
    calibrate→quantize pipeline (ROADMAP item 4).

    A ``MXTPU_NUMERICS=hist`` run accumulates one log2-magnitude
    histogram per tagged site *inside* the compiled graphs (bucket ``i``
    counts ``|x|`` in ``[2^(lo_exp+i), 2^(lo_exp+i+1))``);
    ``numerics.calibration_table()`` exports them, and this class turns
    that table into per-site symmetric quantization ranges by
    percentile-clipping the magnitude distribution (the TensorRT-style
    outlier cut on a coarser, merge-friendly support than
    :class:`LayerRangeCollector`'s linear histogram — magnitude buckets
    add across steps, models, and processes).

    Round-trip contract (tested): ``Observer(table).to_table() ==
    table`` — the observer is a faithful container, so calibration data
    survives export → file → import unchanged. ::

        obs = quantization.Observer(numerics.calibration_table())
        obs.ranges()                # {"act:encoder_out": (-2.9, 2.9)}
        obs.to_table()              # strict-JSON, banked beside ckpts
    """

    def __init__(self, table: Optional[Dict[str, dict]] = None):
        self._sites: Dict[str, dict] = {}
        for site, rec in (table or {}).items():
            self.update(site, rec["counts"], lo_exp=rec["lo_exp"],
                        amin=rec.get("min", 0.0), amax=rec.get("max", 0.0),
                        samples=rec.get("samples", 1))

    def update(self, site: str, counts, lo_exp: int,
               amin: float = 0.0, amax: float = 0.0,
               samples: int = 1) -> None:
        """Merge one magnitude histogram into ``site`` (fixed edges:
        histograms from different steps/processes add per-bucket)."""
        counts = [float(c) for c in counts]
        c = self._sites.get(site)
        if c is None:
            self._sites[site] = {"counts": counts, "lo_exp": int(lo_exp),
                                 "min": float(amin), "max": float(amax),
                                 "samples": int(samples)}
            return
        if int(lo_exp) != c["lo_exp"] or len(counts) != len(c["counts"]):
            raise MXNetError(
                f"observer site {site!r}: incompatible histogram support "
                f"(lo_exp {lo_exp} vs {c['lo_exp']}, bins {len(counts)} "
                f"vs {len(c['counts'])})")
        c["counts"] = [a + b for a, b in zip(c["counts"], counts)]
        c["min"] = min(c["min"], float(amin))
        c["max"] = max(c["max"], float(amax))
        c["samples"] += int(samples)

    def sites(self) -> List[str]:
        return sorted(self._sites)

    def threshold(self, site: str, percentile: float = 99.99) -> float:
        """The |x| clipping threshold covering ``percentile`` % of the
        observed magnitude mass: walk the histogram from the top until
        the excluded tail would exceed the allowance, return that
        bucket's upper edge (clamped into the observed [~, max|x|])."""
        c = self._sites[site]
        counts, lo = c["counts"], c["lo_exp"]
        total = sum(counts)
        absmax = max(abs(c["min"]), abs(c["max"]))
        if total <= 0:
            return absmax or 1.0
        # (100 - p)/100, NOT 1 - p/100: the subtraction in percent
        # space is exact for the round percentiles callers pass, so a
        # bucket holding exactly the tail allowance is dropped
        allow = total * (100.0 - percentile) / 100.0
        dropped = 0.0
        cut = len(counts)                 # index of first EXCLUDED bucket
        for i in range(len(counts) - 1, -1, -1):
            if dropped + counts[i] > allow:
                break
            dropped += counts[i]
            cut = i
        th = float(2.0 ** (lo + cut))     # upper edge of the last kept
        if absmax > 0:
            th = min(th, absmax)
        return th

    def ranges(self, percentile: float = 99.99
               ) -> Dict[str, Tuple[float, float]]:
        """Symmetric per-site quantization ranges ``(-t, t)`` — the
        ``in_range`` shape :func:`quantize_net`'s swapped layers take."""
        return {site: (-self.threshold(site, percentile),
                       self.threshold(site, percentile))
                for site in self._sites}

    def to_table(self) -> Dict[str, dict]:
        """Render back to the ``numerics.calibration_table()`` shape
        (strict-JSON; byte round-trips a table fed to the ctor)."""
        return {site: {"counts": list(c["counts"]),
                       "lo_exp": int(c["lo_exp"]),
                       "bins": len(c["counts"]),
                       "min": float(c["min"]), "max": float(c["max"]),
                       "samples": int(c["samples"])}
                for site, c in sorted(self._sites.items())}


# ---------------------------------------------------------------------------
# quantized gluon layers (the swapped-in nodes of the graph pass)
# ---------------------------------------------------------------------------

def _q8(arr: onp.ndarray) -> Tuple[onp.ndarray, float, float]:
    """Symmetric int8 encode of a weight tensor; returns (q, min, max)."""
    mx_abs = float(onp.abs(arr).max()) or 1e-8
    q = onp.clip(onp.round(arr / (mx_abs / 127.0)), -127, 127).astype(onp.int8)
    return q, -mx_abs, mx_abs


class _QuantizedLayerBase:
    """Mixin holding the frozen int8 weights + calibrated ranges."""


def _make_quantized_dense(layer, in_range):
    from ..gluon.block import HybridBlock

    w = layer.weight.data().asnumpy()
    b = layer.bias.data().asnumpy() if layer.bias is not None else None
    qw, wmin, wmax = _q8(w)
    qb, bmin, bmax = _q8(b) if b is not None else (None, 0.0, 0.0)
    units, flatten = layer._units, layer._flatten
    act = layer.act

    class QuantizedDense(HybridBlock, _QuantizedLayerBase):
        """int8 Dense swapped in by quantize_net (reference:
        quantized_fully_connected + the requantize node the graph pass
        appends). Output is dequantized fp32 so surrounding float ops
        compose; XLA fuses the int8 dot + scale into one kernel."""

        def __init__(self, **kw):
            super().__init__(**kw)
            self._qw = jnp.asarray(qw)
            self._qb = jnp.asarray(qb) if qb is not None else None
            self._range = in_range

        def hybrid_forward(self, F, x):
            from ..ops import quantization as Q
            lo, hi = self._range
            data = x._data if isinstance(x, NDArray) else x
            qx, qlo, qhi = Q.quantize(data, lo, hi, out_type="int8")
            acc, omin, omax = Q.quantized_fully_connected(
                qx, self._qw, self._qb, qlo, qhi, wmin, wmax, bmin, bmax,
                num_hidden=units, no_bias=self._qb is None, flatten=flatten)
            out = Q.dequantize(acc, omin, omax)
            out = NDArray(out, ctx=x.context) if isinstance(x, NDArray) \
                else out
            return act(out) if act is not None else out

    return QuantizedDense(prefix=layer.prefix.rstrip("_") + "_int8_")


def _make_quantized_conv(layer, in_range):
    from ..gluon.block import HybridBlock

    w = layer.weight.data().asnumpy()
    b = layer.bias.data().asnumpy() if layer.bias is not None else None
    qw, wmin, wmax = _q8(w)
    qb, bmin, bmax = _q8(b) if b is not None else (None, 0.0, 0.0)
    kwargs = dict(layer._kwargs)
    act = layer.act

    class QuantizedConv2D(HybridBlock, _QuantizedLayerBase):
        """int8 Conv2D swapped in by quantize_net (reference:
        quantized_conv + requantize). NCHW only, matching the reference's
        quantized conv support envelope."""

        def __init__(self, **kw):
            super().__init__(**kw)
            self._qw = jnp.asarray(qw)
            self._qb = jnp.asarray(qb) if qb is not None else None
            self._range = in_range

        def hybrid_forward(self, F, x):
            from ..ops import quantization as Q
            lo, hi = self._range
            data = x._data if isinstance(x, NDArray) else x
            qx, qlo, qhi = Q.quantize(data, lo, hi, out_type="int8")
            acc, omin, omax = Q.quantized_conv(
                qx, self._qw, self._qb, qlo, qhi, wmin, wmax, bmin, bmax,
                stride=kwargs["stride"], pad=kwargs["pad"],
                dilate=kwargs["dilate"], num_filter=kwargs["num_filter"],
                no_bias=self._qb is None, layout=kwargs["layout"])
            out = Q.dequantize(acc, omin, omax)
            out = NDArray(out, ctx=x.context) if isinstance(x, NDArray) \
                else out
            return act(out) if act is not None else out

    return QuantizedConv2D(prefix=layer.prefix.rstrip("_") + "_int8_")


# ---------------------------------------------------------------------------
# the graph pass + user API
# ---------------------------------------------------------------------------

def _quantizable(block) -> bool:
    from ..gluon import nn
    return isinstance(block, (nn.Dense, nn.Conv2D))


def _iter_quantizable(block, prefix=""):
    for name, child in list(block._children.items()):
        if _quantizable(child):
            yield block, name, child
        else:
            yield from _iter_quantizable(child)


def quantize_net(net, calib_data=None, calib_mode: str = "naive",
                 quantized_dtype: str = "int8",
                 exclude_layers: Sequence[str] = (),
                 num_calib_batches: Optional[int] = None):
    """Quantize a gluon network to int8 in place (returns the same block;
    reference: ``mx.contrib.quantization.quantize_net_v2``).

    ``calib_data``: iterable of input batches (NDArray, or tuples for
    multi-input nets). ``calib_mode='naive'`` records min/max;
    ``'entropy'`` selects KL-optimal thresholds. ``exclude_layers``: layer
    name substrings to keep in float (reference: excluded_sym_names).
    """
    if quantized_dtype != "int8":
        raise MXNetError("TPU int8 path supports quantized_dtype='int8' "
                         "(uint8 activations have no MXU advantage)")
    if calib_data is None:
        raise MXNetError("quantize_net needs calib_data (reference requires "
                         "a calibration dataset for calib_mode != 'none')")

    # Calibration must run EAGERLY: a live jit cache would replay the
    # compiled graph (hooks never fire / see tracers). Deactivate hybridize
    # across the tree for the calibration passes and re-enable after the
    # swap with caches cleared (the float graphs are stale then anyway).
    from ..gluon.block import HybridBlock
    hybridized = []

    def _walk(b):
        yield b
        for c in b._children.values():
            yield from _walk(c)

    for b in _walk(net):
        if isinstance(b, HybridBlock) and getattr(b, "_active", False):
            hybridized.append(b)
            b._active = False

    # -- 1. calibration: hook every quantizable layer's input ------------
    collector = LayerRangeCollector(mode=calib_mode)
    handles = []
    targets = list(_iter_quantizable(net))
    for parent, name, layer in targets:
        def pre_hook(blk, inputs, _name=layer.name):
            x = inputs[0]
            collector.collect(_name, onp.asarray(
                x.asnumpy() if isinstance(x, NDArray) else x))
        handles.append(layer.register_forward_pre_hook(pre_hook))
    n = 0
    for batch in calib_data:
        args = batch if isinstance(batch, (list, tuple)) else (batch,)
        net(*args)
        n += 1
        if num_calib_batches is not None and n >= num_calib_batches:
            break
    for h in handles:
        h.detach()
    ranges = collector.ranges()

    # -- 2. graph pass: swap layers for int8 versions ---------------------
    for parent, name, layer in targets:
        if any(tag in layer.name for tag in exclude_layers):
            continue
        if layer.name not in ranges:
            continue  # never saw data (dead branch) — keep float
        rng = ranges[layer.name]
        from ..gluon import nn
        if isinstance(layer, nn.Dense):
            qlayer = _make_quantized_dense(layer, rng)
        else:
            qlayer = _make_quantized_conv(layer, rng)
        parent.register_child(qlayer, name)
        setattr_name = None
        for attr, val in vars(parent).items():
            if val is layer:
                setattr_name = attr
                break
        if setattr_name:
            object.__setattr__(parent, setattr_name, qlayer)

    # drop stale float executables; restore hybridize state
    for b in _walk(net):
        if isinstance(b, HybridBlock):
            b._clear_cached_op()
    for b in hybridized:
        b._active = True
    return net

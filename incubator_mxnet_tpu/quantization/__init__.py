"""INT8 quantization subsystem (reference: ``src/operator/quantization/`` +
``python/mxnet/contrib/quantization.py`` — SURVEY §2.4).

Three pieces, mirroring the reference's pipeline:

1. **Calibration collectors** — run float inference over a calibration set
   recording per-layer input ranges: ``calib_mode='naive'`` keeps min/max;
   ``'entropy'`` builds histograms and picks the KL-divergence-optimal
   threshold (the reference's ``_LayerHistogramCollector`` /
   ``_get_optimal_threshold`` algorithm).
2. **Graph pass** — the reference rewrites the nnvm graph
   (``quantize_graph_pass.cc``); compiled execution here is jit-traced from
   the Block tree, so the equivalent pass swaps ``Dense`` / ``Conv2D``
   children for :class:`QuantizedDense` / :class:`QuantizedConv2D` whose
   weights are pre-quantized int8 and whose forward runs the int8 MXU ops
   (``ops/quantization.py``) with requantize/dequantize glue. The swap is
   in-place on the block tree and fully hybridizable — XLA sees one int8
   graph, which IS the quantized-graph pass in a trace-based world.
3. **User API** — :func:`quantize_net` (gluon; reference
   ``quantize_net_v2``), with per-layer exclusion and both calib modes.

Dequantized outputs stay within ~1% of fp32 for typical nets (tested in
``tests/test_quantization.py``).
"""
from __future__ import annotations

import copy
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as onp

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from .. import ndarray as nd

__all__ = ["quantize_net", "quantize_model", "observe_net",
           "QuantizedDense", "QuantizedConv2D",
           "LayerRangeCollector", "Observer", "optimal_threshold"]


def _quant_percentile(percentile: Optional[float] = None) -> float:
    """The calibration percentile: explicit argument, else the
    ``MXTPU_QUANT_PERCENTILE`` env knob, else 99.99 (the TensorRT-style
    default that clips outliers instead of letting one spike stretch the
    whole int8 encoding)."""
    if percentile is not None:
        return float(percentile)
    return float(os.environ.get("MXTPU_QUANT_PERCENTILE", "") or 99.99)


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def _smooth_distribution(p: onp.ndarray, eps: float = 1e-4) -> onp.ndarray:
    """Laplace-style smoothing so KL(p||q) is finite (reference:
    contrib/quantization.py _smooth_distribution)."""
    is_zeros = (p == 0).astype(onp.float32)
    is_nonzeros = (p != 0).astype(onp.float32)
    n_zeros = int(is_zeros.sum())
    n_nonzeros = p.size - n_zeros
    if n_nonzeros == 0:
        return None
    eps1 = eps * n_zeros / n_nonzeros
    hist = p.astype(onp.float32)
    hist += eps * is_zeros + (-eps1) * is_nonzeros
    return hist


def optimal_threshold(hist: onp.ndarray, hist_edges: onp.ndarray,
                      num_quantized_bins: int = 255) -> float:
    """KL-divergence-optimal |threshold| from a symmetric histogram
    (reference: _get_optimal_threshold, the classic TensorRT-style search).
    """
    num_bins = hist.size
    assert num_bins % 2 == 1, "use an odd bin count (symmetric around 0)"
    zero_bin = num_bins // 2
    hist = hist.astype(onp.float64)
    csum = onp.concatenate([[0.0], onp.cumsum(hist)])
    thresholds = []
    divergences = []
    # p grows outward from the zero bin; all inner work is vectorized
    # (bucket sums via cumsum, expansion via repeat) so the search is
    # O(candidates · bins) instead of the reference's python-loop square.
    for i in range(num_quantized_bins // 2 + 1, zero_bin + 1):
        p_start, p_stop = zero_bin - i, zero_bin + i + 1
        thresholds.append(hist_edges[p_stop])
        sliced = hist[p_start:p_stop]
        p = sliced.copy()
        p[0] += csum[p_start]                      # left outliers
        p[-1] += csum[-1] - csum[p_stop]           # right outliers
        # quantize p's support down to num_quantized_bins buckets
        edges = onp.round(onp.linspace(0, sliced.size, num_quantized_bins + 1)
                          ).astype(onp.int64)
        starts = edges[:-1]
        widths = onp.diff(edges)
        q = onp.add.reduceat(sliced, starts)
        q[widths == 0] = 0.0
        nz_cnt = onp.add.reduceat((sliced != 0).astype(onp.float64), starts)
        nz_cnt[widths == 0] = 0.0
        # expand q back over p's support, mass split over nonzero slots
        per_slot = onp.divide(q, nz_cnt, out=onp.zeros_like(q),
                              where=nz_cnt > 0)
        q_exp = onp.repeat(per_slot, widths) * (sliced != 0)
        ps = _smooth_distribution(p / max(p.sum(), 1e-30))
        qs = _smooth_distribution(q_exp / max(q_exp.sum(), 1e-30))
        if ps is None or qs is None:
            divergences.append(onp.inf)
            continue
        divergences.append(float(
            onp.sum(ps * onp.log(onp.maximum(ps, 1e-30) /
                                 onp.maximum(qs, 1e-30)))))
    if not divergences:
        return float(hist_edges[-1])
    return float(thresholds[int(onp.argmin(divergences))])


class LayerRangeCollector:
    """Collects per-layer input calibration statistics via forward hooks.

    naive: running min/max. entropy: 8001-bin symmetric histogram per layer,
    threshold picked by :func:`optimal_threshold` at the end.
    """

    def __init__(self, mode: str = "naive", num_bins: int = 8001):
        if mode not in ("naive", "entropy"):
            raise MXNetError(f"unknown calib_mode {mode!r}")
        self.mode = mode
        self.num_bins = num_bins
        self.minmax: Dict[str, Tuple[float, float]] = {}
        self.hists: Dict[str, Tuple[onp.ndarray, onp.ndarray]] = {}

    def collect(self, name: str, x: onp.ndarray) -> None:
        amin, amax = float(x.min()), float(x.max())
        if name in self.minmax:
            lo, hi = self.minmax[name]
            self.minmax[name] = (min(lo, amin), max(hi, amax))
        else:
            self.minmax[name] = (amin, amax)
        if self.mode == "entropy":
            th = max(abs(amin), abs(amax), 1e-8)
            if name in self.hists:
                hist, edges = self.hists[name]
                old_th = edges[-1]
                if th > old_th:
                    # rebuild on the wider range, re-binning the old mass
                    centers = (edges[:-1] + edges[1:]) / 2
                    new_hist, new_edges = onp.histogram(
                        centers, bins=self.num_bins, range=(-th, th),
                        weights=hist)
                    h, _ = onp.histogram(x.ravel(), bins=self.num_bins,
                                         range=(-th, th))
                    self.hists[name] = (new_hist + h, new_edges)
                else:
                    h, _ = onp.histogram(x.ravel(), bins=self.num_bins,
                                         range=(-old_th, old_th))
                    self.hists[name] = (hist + h, edges)
            else:
                h, edges = onp.histogram(x.ravel(), bins=self.num_bins,
                                         range=(-th, th))
                self.hists[name] = (h, edges)

    def ranges(self) -> Dict[str, Tuple[float, float]]:
        if self.mode == "naive":
            return dict(self.minmax)
        out = {}
        for name, (hist, edges) in self.hists.items():
            th = optimal_threshold(hist, edges)
            out[name] = (-th, th)
        return out


class Observer:
    """Calibration observer over ``telemetry.numerics`` hist-mode tables —
    the bridge from live-traffic numerics telemetry to the int8
    calibrate→quantize pipeline (ROADMAP item 4).

    A ``MXTPU_NUMERICS=hist`` run accumulates one log2-magnitude
    histogram per tagged site *inside* the compiled graphs (bucket ``i``
    counts ``|x|`` in ``[2^(lo_exp+i), 2^(lo_exp+i+1))``);
    ``numerics.calibration_table()`` exports them, and this class turns
    that table into per-site symmetric quantization ranges by
    percentile-clipping the magnitude distribution (the TensorRT-style
    outlier cut on a coarser, merge-friendly support than
    :class:`LayerRangeCollector`'s linear histogram — magnitude buckets
    add across steps, models, and processes).

    Round-trip contract (tested): ``Observer(table).to_table() ==
    table`` — the observer is a faithful container, so calibration data
    survives export → file → import unchanged. ::

        obs = quantization.Observer(numerics.calibration_table())
        obs.ranges()                # {"act:encoder_out": (-2.9, 2.9)}
        obs.to_table()              # strict-JSON, banked beside ckpts
    """

    def __init__(self, table: Optional[Dict[str, dict]] = None):
        self._sites: Dict[str, dict] = {}
        for site, rec in (table or {}).items():
            self.update(site, rec["counts"], lo_exp=rec["lo_exp"],
                        amin=rec.get("min", 0.0), amax=rec.get("max", 0.0),
                        samples=rec.get("samples", 1))

    def update(self, site: str, counts, lo_exp: int,
               amin: float = 0.0, amax: float = 0.0,
               samples: int = 1) -> None:
        """Merge one magnitude histogram into ``site`` (fixed edges:
        histograms from different steps/processes add per-bucket)."""
        counts = [float(c) for c in counts]
        c = self._sites.get(site)
        if c is None:
            self._sites[site] = {"counts": counts, "lo_exp": int(lo_exp),
                                 "min": float(amin), "max": float(amax),
                                 "samples": int(samples)}
            return
        if int(lo_exp) != c["lo_exp"] or len(counts) != len(c["counts"]):
            raise MXNetError(
                f"observer site {site!r}: incompatible histogram support "
                f"(lo_exp {lo_exp} vs {c['lo_exp']}, bins {len(counts)} "
                f"vs {len(c['counts'])})")
        c["counts"] = [a + b for a, b in zip(c["counts"], counts)]
        c["min"] = min(c["min"], float(amin))
        c["max"] = max(c["max"], float(amax))
        c["samples"] += int(samples)

    def sites(self) -> List[str]:
        return sorted(self._sites)

    def threshold(self, site: str, percentile: float = 99.99) -> float:
        """The |x| clipping threshold covering ``percentile`` % of the
        observed magnitude mass: walk the histogram from the top until
        the excluded tail would exceed the allowance, return that
        bucket's upper edge (clamped into the observed [~, max|x|])."""
        c = self._sites[site]
        counts, lo = c["counts"], c["lo_exp"]
        total = sum(counts)
        absmax = max(abs(c["min"]), abs(c["max"]))
        if total <= 0:
            return absmax or 1.0
        # (100 - p)/100, NOT 1 - p/100: the subtraction in percent
        # space is exact for the round percentiles callers pass, so a
        # bucket holding exactly the tail allowance is dropped
        allow = total * (100.0 - percentile) / 100.0
        dropped = 0.0
        cut = len(counts)                 # index of first EXCLUDED bucket
        for i in range(len(counts) - 1, -1, -1):
            if dropped + counts[i] > allow:
                break
            dropped += counts[i]
            cut = i
        th = float(2.0 ** (lo + cut))     # upper edge of the last kept
        if absmax > 0:
            th = min(th, absmax)
        return th

    def ranges(self, percentile: float = 99.99
               ) -> Dict[str, Tuple[float, float]]:
        """Symmetric per-site quantization ranges ``(-t, t)`` — the
        ``in_range`` shape :func:`quantize_net`'s swapped layers take."""
        return {site: (-self.threshold(site, percentile),
                       self.threshold(site, percentile))
                for site in self._sites}

    def to_table(self) -> Dict[str, dict]:
        """Render back to the ``numerics.calibration_table()`` shape
        (strict-JSON; byte round-trips a table fed to the ctor)."""
        return {site: {"counts": list(c["counts"]),
                       "lo_exp": int(c["lo_exp"]),
                       "bins": len(c["counts"]),
                       "min": float(c["min"]), "max": float(c["max"]),
                       "samples": int(c["samples"])}
                for site, c in sorted(self._sites.items())}


# ---------------------------------------------------------------------------
# quantized gluon layers (the swapped-in nodes of the graph pass)
# ---------------------------------------------------------------------------

def _q8(arr: onp.ndarray) -> Tuple[onp.ndarray, float, float]:
    """Symmetric int8 encode of a weight tensor; returns (q, min, max)."""
    mx_abs = float(onp.abs(arr).max()) or 1e-8
    q = onp.clip(onp.round(arr / (mx_abs / 127.0)), -127, 127).astype(onp.int8)
    return q, -mx_abs, mx_abs


class _QuantizedLayerBase:
    """Mixin marking a swapped-in int8 layer (weights live as gluon
    ``Constant`` parameters, calibrated ranges as python floats)."""


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


def _make_quantized_dense(layer, in_range):
    from ..gluon.block import HybridBlock

    w = layer.weight.data().asnumpy()
    b = layer.bias.data().asnumpy() if layer.bias is not None else None
    qw, wmin, wmax = _q8(w)
    qb, bmin, bmax = _q8(b) if b is not None else (None, 0.0, 0.0)
    units, flatten = layer._units, layer._flatten
    act = layer.act

    class QuantizedDense(HybridBlock, _QuantizedLayerBase):
        """int8 Dense swapped in by quantize_net (reference:
        quantized_fully_connected + the requantize node the graph pass
        appends). Output is dequantized fp32 so surrounding float ops
        compose; XLA fuses the int8 dot + scale into one kernel.

        The int8 weights are gluon ``Constant`` parameters, NOT python
        closures: they trace as real graph arguments, so
        ``analysis.hlo`` prices them at 1 byte/element in
        ``param_bytes``/``peak_live_bytes`` (the ~4x reduction the
        quantization exists to buy) and never trips the MX705
        baked-constant check on large layers."""

        def __init__(self, **kw):
            super().__init__(**kw)
            self.qweight = self.params.get_constant("qweight", qw)
            if qb is not None:
                self.qbias = self.params.get_constant("qbias", qb)
            self._range = in_range

        def hybrid_forward(self, F, x, qweight, qbias=None):
            from ..ops import quantization as Q
            lo, hi = self._range
            qx, qlo, qhi = Q.quantize(_unwrap(x), lo, hi, out_type="int8")
            acc, omin, omax = Q.quantized_fully_connected(
                qx, _unwrap(qweight),
                _unwrap(qbias) if qbias is not None else None,
                qlo, qhi, wmin, wmax, bmin, bmax,
                num_hidden=units, no_bias=qbias is None, flatten=flatten)
            out = Q.dequantize(acc, omin, omax)
            out = NDArray(out, ctx=x.context) if isinstance(x, NDArray) \
                else out
            return act(out) if act is not None else out

    qlayer = QuantizedDense(prefix=layer.prefix.rstrip("_") + "_int8_")
    qlayer.collect_params().initialize()
    return qlayer


def _make_quantized_conv(layer, in_range):
    from ..gluon.block import HybridBlock

    w = layer.weight.data().asnumpy()
    b = layer.bias.data().asnumpy() if layer.bias is not None else None
    qw, wmin, wmax = _q8(w)
    qb, bmin, bmax = _q8(b) if b is not None else (None, 0.0, 0.0)
    kwargs = dict(layer._kwargs)
    act = layer.act

    class QuantizedConv2D(HybridBlock, _QuantizedLayerBase):
        """int8 Conv2D swapped in by quantize_net (reference:
        quantized_conv + requantize). NCHW only, matching the reference's
        quantized conv support envelope. Weights are ``Constant``
        parameters for the same tracing/pricing reasons as
        :class:`QuantizedDense`."""

        def __init__(self, **kw):
            super().__init__(**kw)
            self.qweight = self.params.get_constant("qweight", qw)
            if qb is not None:
                self.qbias = self.params.get_constant("qbias", qb)
            self._range = in_range

        def hybrid_forward(self, F, x, qweight, qbias=None):
            from ..ops import quantization as Q
            lo, hi = self._range
            qx, qlo, qhi = Q.quantize(_unwrap(x), lo, hi, out_type="int8")
            acc, omin, omax = Q.quantized_conv(
                qx, _unwrap(qweight),
                _unwrap(qbias) if qbias is not None else None,
                qlo, qhi, wmin, wmax, bmin, bmax,
                stride=kwargs["stride"], pad=kwargs["pad"],
                dilate=kwargs["dilate"], num_filter=kwargs["num_filter"],
                no_bias=qbias is None, layout=kwargs["layout"])
            out = Q.dequantize(acc, omin, omax)
            out = NDArray(out, ctx=x.context) if isinstance(x, NDArray) \
                else out
            return act(out) if act is not None else out

    qlayer = QuantizedConv2D(prefix=layer.prefix.rstrip("_") + "_int8_")
    qlayer.collect_params().initialize()
    return qlayer


# ---------------------------------------------------------------------------
# the graph pass + user API
# ---------------------------------------------------------------------------

def _quantizable(block) -> bool:
    from ..gluon import nn
    return isinstance(block, (nn.Dense, nn.Conv2D))


def _iter_quantizable(block, prefix=""):
    for name, child in list(block._children.items()):
        if _quantizable(child):
            yield block, name, child
        else:
            yield from _iter_quantizable(child)


def _walk_blocks(b):
    yield b
    for c in b._children.values():
        yield from _walk_blocks(c)


class _eager_tree:
    """Deactivate hybridize across a block tree so forward hooks fire on
    real arrays (a live jit cache would replay the compiled graph and the
    hooks would never see data); restores the previous state on exit."""

    def __init__(self, net):
        from ..gluon.block import HybridBlock
        self._hb = HybridBlock
        self._net = net
        self._saved = []

    def __enter__(self):
        for b in _walk_blocks(self._net):
            if isinstance(b, self._hb) and getattr(b, "_active", False):
                self._saved.append(b)
                b._active = False
        return self

    def __exit__(self, *exc):
        for b in self._saved:
            b._active = True
        return False


def _ranges_for_layers(site_ranges: Dict[str, Tuple[float, float]],
                       layer_names: Sequence[str]
                       ) -> Dict[str, Tuple[float, float]]:
    """Bridge Observer site names to gluon layer names. Calibration
    tables key sites as the layer name itself (:func:`observe_net`), a
    tagged activation (``act:dense0``), or a scoped telemetry site
    (``serve/act:dense0``) — resolve each layer by exact match, then
    ``act:<name>``, then ``:<name>`` suffix, then substring."""
    out = {}
    for name in layer_names:
        rng = site_ranges.get(name) or site_ranges.get("act:" + name)
        if rng is None:
            for site in sorted(site_ranges):
                if site.endswith(":" + name) or name in site:
                    rng = site_ranges[site]
                    break
        if rng is not None:
            out[name] = rng
    return out


def observe_net(net, calib_data, num_calib_batches: Optional[int] = None,
                bins: int = 40, lo_exp: int = -24) -> Observer:
    """Run calibration batches eagerly and return an :class:`Observer`
    keyed by layer name — one log2-magnitude histogram per quantizable
    layer's input, the same bucket scheme ``telemetry.numerics`` hist
    mode uses (bucket ``i`` counts ``|x|`` in ``[2^(lo_exp+i),
    2^(lo_exp+i+1))``), so an observer built here and one built from
    ``numerics.calibration_table()`` merge and quantize identically."""
    obs = Observer()
    handles = []

    def _record(name, arr):
        a = onp.abs(arr.ravel().astype(onp.float64))
        nz = a[a > 0]
        counts = onp.zeros(bins, dtype=onp.float64)
        if nz.size:
            exp = onp.floor(onp.log2(nz)).astype(onp.int64)
            idx = onp.clip(exp - lo_exp, 0, bins - 1)
            counts = onp.bincount(idx, minlength=bins).astype(onp.float64)
        obs.update(name, counts, lo_exp,
                   amin=float(arr.min()), amax=float(arr.max()))

    with _eager_tree(net):
        for _parent, _name, layer in _iter_quantizable(net):
            def pre_hook(blk, inputs, _lname=layer.name):
                x = inputs[0]
                _record(_lname, onp.asarray(
                    x.asnumpy() if isinstance(x, NDArray) else x))
            handles.append(layer.register_forward_pre_hook(pre_hook))
        try:
            n = 0
            for batch in calib_data:
                args = batch if isinstance(batch, (list, tuple)) \
                    else (batch,)
                net(*args)
                n += 1
                if num_calib_batches is not None \
                        and n >= num_calib_batches:
                    break
        finally:
            for h in handles:
                h.detach()
    return obs


def quantize_net(net, calib_data=None, calib_mode: str = "naive",
                 quantized_dtype: str = "int8",
                 exclude_layers: Sequence[str] = (),
                 num_calib_batches: Optional[int] = None,
                 percentile: Optional[float] = None):
    """Quantize a gluon network to int8 in place (returns the same block;
    reference: ``mx.contrib.quantization.quantize_net_v2``).

    ``calib_data`` — any of:

    * an iterable of input batches (NDArray, or tuples for multi-input
      nets): forward hooks collect per-layer ranges, ``calib_mode=
      'naive'`` keeping min/max, ``'entropy'`` selecting KL-optimal
      thresholds (the legacy :class:`LayerRangeCollector` path);
    * an :class:`Observer` (from :func:`observe_net` or
      ``telemetry.numerics.calibration_table()``): its
      percentile-clipped ``ranges()`` are lowered directly — no
      calibration forward runs;
    * an Observer ``to_table()`` dict (the banked-beside-checkpoints
      form): rehydrated into an Observer first.

    All three sources converge on one site→layer range resolution
    (:func:`_ranges_for_layers`) and one swap pass. ``percentile``
    applies to the Observer paths (default: ``MXTPU_QUANT_PERCENTILE``
    env, else 99.99). ``exclude_layers``: layer name substrings to keep
    in float (reference: excluded_sym_names).
    """
    if quantized_dtype != "int8":
        raise MXNetError("TPU int8 path supports quantized_dtype='int8' "
                         "(uint8 activations have no MXU advantage)")
    if calib_data is None:
        raise MXNetError("quantize_net needs calib_data (reference requires "
                         "a calibration dataset for calib_mode != 'none')")

    from ..gluon.block import HybridBlock
    targets = list(_iter_quantizable(net))

    observer = None
    if isinstance(calib_data, Observer):
        observer = calib_data
    elif isinstance(calib_data, dict) and calib_data and all(
            isinstance(v, dict) and "counts" in v
            for v in calib_data.values()):
        observer = Observer(calib_data)

    if observer is not None:
        # -- 1a. calibrated ranges straight from the observer ------------
        ranges = _ranges_for_layers(
            observer.ranges(_quant_percentile(percentile)),
            [layer.name for _p, _n, layer in targets])
    else:
        # -- 1b. legacy path: hook every quantizable layer's input -------
        collector = LayerRangeCollector(mode=calib_mode)
        handles = []
        with _eager_tree(net):
            for parent, name, layer in targets:
                def pre_hook(blk, inputs, _name=layer.name):
                    x = inputs[0]
                    collector.collect(_name, onp.asarray(
                        x.asnumpy() if isinstance(x, NDArray) else x))
                handles.append(layer.register_forward_pre_hook(pre_hook))
            try:
                n = 0
                for batch in calib_data:
                    args = batch if isinstance(batch, (list, tuple)) \
                        else (batch,)
                    net(*args)
                    n += 1
                    if num_calib_batches is not None \
                            and n >= num_calib_batches:
                        break
            finally:
                for h in handles:
                    h.detach()
        ranges = collector.ranges()

    # -- 2. graph pass: swap layers for int8 versions ---------------------
    for parent, name, layer in targets:
        if any(tag in layer.name for tag in exclude_layers):
            continue
        if layer.name not in ranges:
            continue  # never saw data (dead branch) — keep float
        rng = ranges[layer.name]
        from ..gluon import nn
        if isinstance(layer, nn.Dense):
            qlayer = _make_quantized_dense(layer, rng)
        else:
            qlayer = _make_quantized_conv(layer, rng)
        parent.register_child(qlayer, name)
        setattr_name = None
        for attr, val in vars(parent).items():
            if val is layer:
                setattr_name = attr
                break
        if setattr_name:
            object.__setattr__(parent, setattr_name, qlayer)

    # drop stale float executables
    for b in _walk_blocks(net):
        if isinstance(b, HybridBlock):
            b._clear_cached_op()
    return net


def quantize_model(model, observer, percentile: Optional[float] = None,
                   exclude_layers: Sequence[str] = ()):
    """Lower an :class:`Observer`'s calibrated ranges into a quantized
    serving twin of a ``serve.CompiledModel``.

    Returns a NEW ``CompiledModel`` over an int8 copy of the wrapped
    block, inheriting the original's bucket table, input/output axes,
    pad values, donation intent, and ``autotune_key`` — per-bucket AOT
    warmup, donated request buffers, and banked autotune winners all
    keep working, keyed exactly as before. The original model is NOT
    touched: its block tree is deep-copied before the swap, so the
    active float version keeps serving while the quantized candidate is
    staged (and possibly rejected by the MX71x gate —
    ``analysis.hlo.verify(..., quant=True)`` at ``ModelRegistry``
    staging).

    ``observer``: an :class:`Observer` or its ``to_table()`` dict.
    ``percentile``: range-clipping percentile (default
    ``MXTPU_QUANT_PERCENTILE`` env, else 99.99).
    """
    from ..gluon.block import HybridBlock
    from ..serve.compiled import CompiledModel
    if not isinstance(model, CompiledModel):
        raise MXNetError("quantize_model takes a serve.CompiledModel "
                         f"(got {type(model).__name__}); use quantize_net "
                         "for a bare gluon block")
    if model._mode != "block":
        raise MXNetError("quantize_model needs a live-block CompiledModel; "
                         "an imported artifact's graphs are already frozen "
                         "— quantize before export")
    if not (isinstance(observer, Observer)
            or (isinstance(observer, dict) and observer)):
        raise MXNetError("quantize_model needs an Observer (or its "
                         "to_table() dict) — calibration provenance is "
                         "exactly what the MX712 staging gate checks for")

    # deep-copy the block tree with the uncopyable per-block state
    # stripped: jit caches (compiled executables, stale after the swap
    # anyway) and name scopes (threading.local); the original keeps its
    # executables untouched
    from ..gluon.block import _BlockScope
    block = model._block
    saved = []
    for b in _walk_blocks(block):
        jits = (b._jit_cache, b._cache_info) \
            if isinstance(b, HybridBlock) else None
        saved.append((b, jits, b._scope))
        if jits is not None:
            b._jit_cache, b._cache_info = {}, {}
        b._scope = None
    try:
        twin = copy.deepcopy(block)
    finally:
        for b, jits, scope in saved:
            if jits is not None:
                b._jit_cache, b._cache_info = jits
            b._scope = scope
    for b in _walk_blocks(twin):
        b._scope = _BlockScope(b)

    quantize_net(twin, calib_data=observer, percentile=percentile,
                 exclude_layers=exclude_layers)

    # the copied signature/param caches describe the float tree; drop
    # them so the CompiledModel warm-up below re-records the quantized
    # tree (int8 Constants become real traced params)
    for b in _walk_blocks(twin):
        if isinstance(b, HybridBlock):
            b._last_sig = None
            b._warmed_up = False

    example_args = [NDArray(jnp.zeros(shape, dtype=dtype))
                    for shape, dtype in model._in_avals]
    return CompiledModel(twin, model._table, model._input_axes,
                         example_args=example_args,
                         output_axes=model._output_axes,
                         pad_values=list(model._pad_values),
                         donate=model._donate_requested,
                         ctx=model._ctx,
                         autotune_key=model._autotune_key)

"""Network visualization.

Reference counterpart: ``python/mxnet/visualization.py`` —
``print_summary`` (per-layer table with output shapes and parameter
counts over the symbol graph) and ``plot_network`` (graphviz digraph).
The table walks the same topological order the Executor compiles.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as onp

__all__ = ["print_summary", "plot_network"]


def _node_params(node, shapes: Dict[str, tuple], data_names) -> int:
    """Learnable parameters attached to an op node = its variable inputs
    whose shapes were resolved, excluding the data/label inputs the caller
    provided."""
    total = 0
    for inp in node._inputs:
        if inp._op is None and inp._name in shapes \
                and inp._name not in data_names:
            total += int(onp.prod(shapes[inp._name]))
    return total


def print_summary(symbol, shape: Optional[Dict[str, tuple]] = None,
                  line_length: int = 98, positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a layer table for ``symbol`` (reference:
    ``mx.viz.print_summary``). ``shape`` maps data variable names to input
    shapes — required to resolve output shapes and parameter counts."""
    from .symbol import _infer_graph_shapes, _topo

    shapes: Dict[str, tuple] = {}
    specs_by_node: Dict[int, object] = {}
    if shape:
        shapes, _ = _infer_graph_shapes(symbol, shape, sink=specs_by_node)
    data_names = set(shape or ())

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def row(vals):
        line = ""
        for v, pos in zip(vals, positions):
            line = (line + str(v))[: pos - 1]
            line += " " * (pos - len(line))
        print(line)

    print("_" * line_length)
    row(fields)
    print("=" * line_length)

    total = 0
    for node in _topo(symbol):
        if node._op is None:
            if node._name in data_names:
                shp = shapes.get(node._name, "")
                row([f"{node._name} (input)", shp, 0, ""])
            continue
        if node._base is not None:
            continue
        out_shape = ""
        if shape:
            spec = specs_by_node.get(id(node))
            out_shape = tuple(spec.shape) if spec is not None else "?"
        n_params = _node_params(node, shapes, data_names) if shape else 0
        total += n_params
        prev = ",".join(i._name for i in node._inputs if i._op is not None
                        or i._name in data_names)
        row([f"{node._name} ({node._op})", out_shape, n_params, prev])
    print("=" * line_length)
    print(f"Total params: {total}")
    print("_" * line_length)
    return total


def plot_network(symbol, title: str = "plot", shape=None,
                 node_attrs: Optional[dict] = None):
    """Graphviz digraph of the symbol graph (reference:
    ``mx.viz.plot_network``). Requires the optional ``graphviz`` package;
    raises a clear error when it is not installed (this image has no
    network access to fetch it)."""
    try:
        import graphviz
    except ImportError as e:
        raise ImportError(
            "plot_network requires the 'graphviz' python package; it is not "
            "installed in this environment — use print_summary for a "
            "text rendering") from e
    from .symbol import _topo

    dot = graphviz.Digraph(name=title)
    attrs = {"shape": "box", "fixedsize": "false"}
    attrs.update(node_attrs or {})
    for node in _topo(symbol):
        if node._base is not None:
            continue
        label = node._name if node._op is None else f"{node._name}\n{node._op}"
        dot.node(node._name, label=label, **attrs)
        for inp in node._inputs:
            tgt = inp if inp._base is None else inp._base
            dot.edge(tgt._name, node._name)
    return dot

"""LR schedulers (reference: ``python/mxnet/lr_scheduler.py``)."""
from __future__ import annotations

import math
from typing import List, Optional

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler", "LinearWarmUp"]


class LRScheduler:
    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0.0,
                 warmup_mode="linear"):
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update: int) -> float:
        assert num_update < self.warmup_steps
        if self.warmup_mode == "linear":
            inc = (self.warmup_final_lr - self.warmup_begin_lr) * num_update / self.warmup_steps
            return self.warmup_begin_lr + inc
        return self.warmup_begin_lr + (self.warmup_final_lr - self.warmup_begin_lr) * \
            (1 - math.exp(-num_update / max(self.warmup_steps / 5.0, 1e-8)))

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError

    # -- traced twin (whole-step capture) ------------------------------
    def _jax_warmup_lr(self, t):
        """Traced ``get_warmup_lr``: ``t`` is a device int32 scalar."""
        import jax.numpy as jnp
        tf = t.astype(jnp.float32)
        span = jnp.float32(self.warmup_final_lr - self.warmup_begin_lr)
        if self.warmup_mode == "linear":
            return jnp.float32(self.warmup_begin_lr) \
                + span * tf / jnp.float32(self.warmup_steps)
        return jnp.float32(self.warmup_begin_lr) + span * (
            1.0 - jnp.exp(-tf / max(self.warmup_steps / 5.0, 1e-8)))

    def _jax_main_lr(self, t):
        """Post-warmup schedule as a traced function of the device step
        counter; subclasses implement this half of :meth:`jax_lr`."""
        raise NotImplementedError

    def jax_lr(self, t):
        """The schedule as a traced jax expression of the device-resident
        update counter — the LR-schedule *position* folded into the one
        compiled training step (ShardedTrainer's whole-step capture), so
        a scheduled run pays no per-step host LR evaluation + transfer.
        Warmup is a ``where`` select, not Python control flow: one graph
        covers the whole run. Matches :meth:`__call__` up to float32
        device arithmetic (the host twin computes in float64)."""
        import jax.numpy as jnp
        t = jnp.maximum(t, 0)
        main = self._jax_main_lr(t)
        if not self.warmup_steps:
            return main.astype(jnp.float32)
        return jnp.where(t < self.warmup_steps, self._jax_warmup_lr(t),
                         main).astype(jnp.float32)


class FactorScheduler(LRScheduler):
    def __init__(self, step: int, factor=1.0, stop_factor_lr=1e-8, base_lr=0.01, **kw):
        super().__init__(base_lr, **kw)
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update: int) -> float:
        if self.warmup_steps and num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        lr = self.base_lr * (self.factor ** (num_update // self.step))
        return max(lr, self.stop_factor_lr)

    def _jax_main_lr(self, t):
        import jax.numpy as jnp
        n = (t // self.step).astype(jnp.float32)
        lr = jnp.float32(self.base_lr) * jnp.float32(self.factor) ** n
        return jnp.maximum(lr, jnp.float32(self.stop_factor_lr))


class MultiFactorScheduler(LRScheduler):
    def __init__(self, step: List[int], factor=1.0, base_lr=0.01, **kw):
        super().__init__(base_lr, **kw)
        self.step = sorted(step)
        self.factor = factor

    def __call__(self, num_update: int) -> float:
        if self.warmup_steps and num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        n = sum(1 for s in self.step if s <= num_update)
        return self.base_lr * (self.factor ** n)

    def _jax_main_lr(self, t):
        import jax.numpy as jnp
        n = sum((t >= s).astype(jnp.float32) for s in self.step)
        return jnp.float32(self.base_lr) * jnp.float32(self.factor) ** n


class PolyScheduler(LRScheduler):
    def __init__(self, max_update: int, base_lr=0.01, pwr=2, final_lr=0.0, **kw):
        super().__init__(base_lr, **kw)
        self.max_update = max_update
        self.power = pwr
        self.final_lr = final_lr

    def __call__(self, num_update: int) -> float:
        if self.warmup_steps and num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        t = min(num_update - self.warmup_steps, self.max_update - self.warmup_steps)
        frac = 1.0 - t / max(self.max_update - self.warmup_steps, 1)
        return self.final_lr + (self.base_lr - self.final_lr) * (frac ** self.power)

    def _jax_main_lr(self, t):
        import jax.numpy as jnp
        span = max(self.max_update - self.warmup_steps, 1)
        tt = jnp.minimum((t - self.warmup_steps).astype(jnp.float32),
                         jnp.float32(span))
        frac = 1.0 - tt / jnp.float32(span)
        return jnp.float32(self.final_lr) \
            + jnp.float32(self.base_lr - self.final_lr) \
            * frac ** jnp.float32(self.power)


class CosineScheduler(LRScheduler):
    def __init__(self, max_update: int, base_lr=0.01, final_lr=0.0, **kw):
        super().__init__(base_lr, **kw)
        self.max_update = max_update
        self.final_lr = final_lr

    def __call__(self, num_update: int) -> float:
        if self.warmup_steps and num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        t = min(num_update - self.warmup_steps, self.max_update - self.warmup_steps)
        frac = t / max(self.max_update - self.warmup_steps, 1)
        return self.final_lr + (self.base_lr - self.final_lr) * 0.5 * (1 + math.cos(math.pi * frac))

    def _jax_main_lr(self, t):
        import jax.numpy as jnp
        span = max(self.max_update - self.warmup_steps, 1)
        tt = jnp.minimum((t - self.warmup_steps).astype(jnp.float32),
                         jnp.float32(span))
        frac = tt / jnp.float32(span)
        return jnp.float32(self.final_lr) \
            + jnp.float32(self.base_lr - self.final_lr) * 0.5 \
            * (1.0 + jnp.cos(jnp.float32(math.pi) * frac))


class LinearWarmUp(LRScheduler):
    """Wrap another scheduler with linear warmup (GluonNLP-style)."""

    def __init__(self, schedule: LRScheduler, start_lr: float, length: int):
        super().__init__(schedule.base_lr, warmup_steps=length, warmup_begin_lr=start_lr)
        self.schedule = schedule

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        return self.schedule(num_update)

    def _jax_main_lr(self, t):
        # the wrapped schedule applies its own warmup select (usually a
        # no-op: warmup_steps=0 on the inner schedule)
        return self.schedule.jax_lr(t)

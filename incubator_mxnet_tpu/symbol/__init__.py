"""``mx.sym`` — the symbolic graph API.

Reference parity: ``python/mxnet/symbol/symbol.py`` over nnvm Symbol compose
(``src/c_api/c_api_symbolic.cc`` — SURVEY §2.3, §3.3/3.5): ``Variable``,
op composition, ``list_arguments``, ``infer_shape``, ``tojson``/``load``,
``bind``/``simple_bind`` producing an Executor, and ``Group``.

TPU-native design: a Symbol is a tiny pure DAG over the op registry; binding
traces it into ONE jitted XLA callable (+ its vjp for backward) — the
GraphExecutor's memory planning, op fusion and engine scheduling all
collapse into that single compile. The same registry powers ``mx.nd``, so
every imperative op name composes symbolically too (the reference generates
both namespaces from one registry the same way).
"""
from __future__ import annotations

import ast
import json
import sys
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import NDArray, array
from ..ops.registry import OPS

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones", "FullyConnected", "Activation", "SoftmaxOutput",
           "GraphInferenceError"]


class GraphInferenceError(MXNetError):
    """Shape/dtype inference failed at a specific graph node.

    Wraps the raw JAX/schema error from the per-node ``jax.eval_shape``
    walk with the node's provenance — node name, op name, public attrs —
    so the failure reads as a graph location, not a tracer traceback.
    ``mx.analysis``'s ``infer_shapes`` pass converts this into an MX101
    diagnostic; ``Symbol.infer_shape`` lets it propagate to the user.
    """

    def __init__(self, node_name: str, op: Optional[str], attrs: Dict,
                 reason: str):
        self.node_name = node_name
        self.op = op
        self.attrs = attrs
        self.reason = reason
        super().__init__(
            f"shape inference failed at node '{node_name}' "
            f"(op {op!r}, attrs {attrs}): {reason}")


def _node_provenance(node: "Symbol") -> Tuple[str, Optional[str], Dict]:
    """(name, op, public attrs) triple identifying one graph node in error
    messages — shared by infer_shape and the mx.analysis shape pass."""
    attrs = {k: v for k, v in node._attrs.items() if not k.startswith("_")}
    return node._name, node._op, attrs

_this = sys.modules[__name__]

#: set while load_json rebuilds a graph — suppresses AttrScope injection
_DESERIALIZING = threading.local()


class Symbol:
    """A node in the symbolic DAG: either a variable (op None) or an op
    application. Immutable; composition builds new nodes."""

    def __init__(self, op: Optional[str], inputs: Sequence["Symbol"],
                 attrs: Optional[Dict[str, Any]] = None,
                 name: Optional[str] = None, num_outputs: int = 1,
                 output_index: int = 0, base: Optional["Symbol"] = None):
        self._op = op
        self._inputs = list(inputs)
        self._attrs = dict(attrs or {})
        # Scope attributes (mx.AttrScope — group2ctx/lr_mult annotations)
        # ride along under the _attr_ prefix; explicit node attrs win.
        # Deserialization must NOT re-apply the ambient scope: a reloaded
        # graph carries exactly the attrs it was saved with.
        if not getattr(_DESERIALIZING, "flag", False):
            from ..attribute import current_attrs
            for k, v in current_attrs().items():
                self._attrs.setdefault(k, v)
        self._name = name or _auto_name(op)
        self._num_outputs = num_outputs
        self._output_index = output_index
        self._base = base  # for multi-output slices: the producing node

    # -- identity ----------------------------------------------------------
    @property
    def name(self) -> str:
        return self._name

    def attr(self, key: str):
        """Node attribute; AttrScope-applied attributes resolve by their
        plain name (stored internally under the ``_attr_`` prefix)."""
        if key in self._attrs:
            return self._attrs[key]
        return self._attrs.get("_attr_" + key)

    def list_attr(self) -> Dict[str, Any]:
        # scope attrs (_attr_ prefixed) first, then explicit node attrs so
        # an explicit attr of the same name wins — matching attr()
        out = {}
        for k, v in self._attrs.items():
            if k.startswith("_attr_"):
                out[k[len("_attr_"):]] = v
        for k, v in self._attrs.items():
            if not k.startswith("_attr_"):
                out[k] = v
        return out

    def __repr__(self):
        return f"<Symbol {self._name}>"

    # -- composition -------------------------------------------------------
    def __call__(self, *args, **kwargs):
        raise MXNetError("Symbol composition via __call__ is a Module-era "
                         "pattern not needed here; apply ops directly")

    def _binary(self, other, opname):
        if isinstance(other, Symbol):
            return Symbol(opname, [self, other])
        return Symbol(opname, [self], attrs={"scalar": other, "_scalar_rhs": True})

    def __add__(self, other):
        return self._binary(other, "broadcast_add" if isinstance(other, Symbol) else "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return self._binary(other, "broadcast_sub" if isinstance(other, Symbol) else "_minus_scalar")

    def __mul__(self, other):
        return self._binary(other, "broadcast_mul" if isinstance(other, Symbol) else "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return self._binary(other, "broadcast_div" if isinstance(other, Symbol) else "_div_scalar")

    def __neg__(self):
        return self.__mul__(-1.0)

    # comparisons (reference: symbol.py __lt__/__gt__/... via
    # broadcast_lesser / _lesser_scalar family; result is a 0/1 float sym)
    def __lt__(self, other):
        return self._binary(other, "broadcast_lesser" if isinstance(other, Symbol) else "_lesser_scalar")

    def __le__(self, other):
        return self._binary(other, "broadcast_lesser_equal" if isinstance(other, Symbol) else "_lesser_equal_scalar")

    def __gt__(self, other):
        return self._binary(other, "broadcast_greater" if isinstance(other, Symbol) else "_greater_scalar")

    def __ge__(self, other):
        return self._binary(other, "broadcast_greater_equal" if isinstance(other, Symbol) else "_greater_equal_scalar")

    def __getitem__(self, index: int) -> "Symbol":
        if self._num_outputs == 1:
            if index != 0:
                raise MXNetError(f"{self._name} has a single output")
            return self
        return Symbol(None, [], name=f"{self._name}_output{index}",
                      base=self, output_index=index)

    # -- graph queries -----------------------------------------------------
    def get_internals(self) -> "Symbol":
        return Group(_topo(self))

    def list_arguments(self) -> List[str]:
        out, seen = [], set()
        for node in _topo(self):
            if node._op is None and node._base is None and id(node) not in seen:
                seen.add(id(node))
                out.append(node._name)
        return out

    def list_outputs(self) -> List[str]:
        if self._op == "_group":
            return [s._name + "_output" for s in self._inputs]
        return [self._name + "_output"]

    def list_auxiliary_states(self) -> List[str]:
        out = []
        for node in _topo(self):
            slots = _AUX_SLOTS.get(node._op)
            if not slots:
                continue
            for inp in node._inputs:
                if inp._op is None and inp._base is None and \
                        inp._name.endswith(slots):
                    out.append(inp._name)
        return out

    def infer_shape(self, **kwargs):
        """Shape inference: per-op jax.eval_shape walk (the nnvm InferShape
        pass for free), with parameter shapes resolved from their consumer's
        input shape + attrs — so implicitly-created weight/bias variables
        (``sym.FullyConnected(data, num_hidden=...)``) infer like the
        reference. Failures raise :class:`GraphInferenceError` carrying the
        offending node's name/op/attrs (mx.analysis reports it as MX101)."""
        args = self.list_arguments()
        shapes, out_specs = _infer_graph_shapes(self, kwargs)
        unknown = [a for a in args if a not in shapes]
        if unknown:
            raise MXNetError(f"infer_shape could not resolve {unknown}")
        out_shapes = [tuple(o.shape) for o in out_specs]
        return [tuple(shapes[a]) for a in args], out_shapes, []

    def infer_type(self, **kwargs):
        args = self.list_arguments()
        return [onp.float32] * len(args), [onp.float32] * len(self.list_outputs()), []

    # -- serialization -----------------------------------------------------
    def tojson(self) -> str:
        nodes = _topo(self)
        idx = {id(n): i for i, n in enumerate(nodes)}
        payload = {
            "nodes": [{
                "op": n._op or "null",
                "name": n._name,
                "attrs": {k: repr(_wire_attr(v))
                          for k, v in n._attrs.items()},
                "inputs": [[idx[id(i)], 0, 0] for i in n._inputs],
                "output_index": n._output_index,
                "num_outputs": n._num_outputs,
                "base": idx[id(n._base)] if n._base is not None else None,
            } for n in nodes],
            "heads": [[idx[id(self)], 0, 0]],
            "mxtpu_version": 1,
        }
        return json.dumps(payload, indent=2)

    def save(self, fname: str) -> None:
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- execution ---------------------------------------------------------
    def eval(self, ctx: Optional[Context] = None, **kwargs) -> List[NDArray]:
        args = self.list_arguments()
        vals = []
        for a in args:
            if a not in kwargs:
                raise MXNetError(f"eval missing argument {a}")
            v = kwargs[a]
            vals.append(v._data if isinstance(v, NDArray) else jnp.asarray(v))
        fn = _compile_fn(self, args)
        out = fn(*vals)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [NDArray(o, ctx=ctx or current_context()) for o in outs]

    def optimize_for(self, backend) -> "Symbol":
        """Partition this graph with a registered subgraph backend
        (reference: symbol.py optimize_for over SubgraphBackendRegistry).
        Pure: returns the rewritten Symbol."""
        from .. import subgraph as _subgraph
        return _subgraph.partition(self, backend)

    def bind(self, ctx: Context, args, args_grad=None, grad_req: str = "write",
             aux_states=None, **kwargs) -> "Executor":
        return Executor(self, ctx, args, args_grad, grad_req)

    def simple_bind(self, ctx: Optional[Context] = None, grad_req: str = "write",
                    **shapes) -> "Executor":
        ctx = ctx or current_context()
        arg_names = self.list_arguments()
        arg_shapes, _, _ = self.infer_shape(**shapes)
        rng = onp.random.RandomState(0)
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in shapes:
                # user-fed slot (data/label): zeros, overwritten per batch
                args[name] = NDArray(jnp.zeros(shape), ctx=ctx)
            else:
                # parameter: uniform Xavier-ish init (Module.init_params
                # usually overwrites this)
                # NB: can't use bare max() here — the generated-op loop below
                # reflects registry names (max/min/sum/abs/...) into this
                # module's namespace, shadowing the builtins at module scope.
                fan = int(onp.prod(shape[1:])) if len(shape) > 1 \
                    else int(shape[0])
                fan = fan if fan > 0 else 1
                scale = (6.0 / fan) ** 0.5
                args[name] = NDArray(jnp.asarray(
                    rng.uniform(-scale, scale, shape), jnp.float32), ctx=ctx)
        grads = {name: NDArray(jnp.zeros_like(a._data), ctx=ctx)
                 for name, a in args.items()} if grad_req != "null" else None
        return Executor(self, ctx, args, grads, grad_req)


def _auto_name(op: Optional[str]) -> str:
    if op is None:
        return "variable"
    from ..name import NameManager
    return NameManager.current().get(None, op.lower())


def _topo(root: Symbol) -> List[Symbol]:
    seen: Dict[int, Symbol] = {}
    order: List[Symbol] = []

    def rec(node: Symbol):
        if id(node) in seen:
            return
        seen[id(node)] = node
        if node._base is not None:
            rec(node._base)
        for i in node._inputs:
            rec(i)
        order.append(node)

    rec(root)
    return order


_SCALAR_OPS = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    # comparisons keep the operand dtype (0/1 values), matching the
    # registered `lesser`/`greater` tensor ops
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
}


# ---------------------------------------------------------------------------
# implicit parameter variables (reference: nnvm op FListInputNames — weights
# are auto-created inputs named <op>_weight etc. with shapes inferred)
# ---------------------------------------------------------------------------

def _fc_shapes(dshape, attrs):
    h = int(attrs["num_hidden"])
    in_units = int(onp.prod(dshape[1:])) if attrs.get("flatten", True) \
        else int(dshape[-1])
    out = {"weight": (h, in_units)}
    if not attrs.get("no_bias", False):
        out["bias"] = (h,)
    return out


def _conv_shapes(dshape, attrs):
    kernel = tuple(attrs["kernel"])
    nf = int(attrs["num_filter"])
    groups = int(attrs.get("num_group", 1))
    out = {"weight": (nf, dshape[1] // groups) + kernel}
    if not attrs.get("no_bias", False):
        out["bias"] = (nf,)
    return out


def _embed_shapes(dshape, attrs):
    return {"weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))}


def _bn_shapes(dshape, attrs):
    c = int(dshape[1])
    return {"gamma": (c,), "beta": (c,),
            "moving_mean": (c,), "moving_var": (c,)}


#: op -> (ordered param slot names, shape rule)
_PARAM_OPS: Dict[str, tuple] = {
    "FullyConnected": (("weight", "bias"), _fc_shapes),
    # the DENSE_ACT partitioner's fused node keeps FullyConnected's
    # implicit weight/bias creation (mx.subgraph / ops/subgraph_ops.py)
    "_sg_dense_act": (("weight", "bias"), _fc_shapes),
    "Convolution": (("weight", "bias"), _conv_shapes),
    "Embedding": (("weight",), _embed_shapes),
    "BatchNorm": (("gamma", "beta", "moving_mean", "moving_var"),
                  _bn_shapes),
}

#: param slots that are auxiliary states, not learnable arguments
#: (reference: nnvm ListAuxiliaryStates — BatchNorm's running stats)
_AUX_SLOTS = {"BatchNorm": ("moving_mean", "moving_var")}


def _infer_graph_shapes(root: Symbol, known: Dict[str, tuple], sink=None):
    """Walk the DAG once, resolving variable shapes (data from ``known``,
    params from consumer rules) and per-node output specs. When ``sink`` is
    a dict it receives every node's primary output spec keyed by ``id(node)``
    (single-pass consumer: ``visualization.print_summary``)."""
    shapes: Dict[str, tuple] = {k: tuple(v) for k, v in known.items()}
    env: Dict[int, Any] = {}
    f32 = jnp.float32

    def spec_of(node):
        v = env.get(id(node))
        # multi-output op consumed as a plain symbol -> primary output
        return v[0] if isinstance(v, (tuple, list)) else v

    for node in _topo(root):
        if node._base is not None:
            outs = env[id(node._base)]
            if not isinstance(outs, (tuple, list)) \
                    or node._output_index >= len(outs):
                n_out = len(outs) if isinstance(outs, (tuple, list)) else 1
                raise GraphInferenceError(
                    *_node_provenance(node),
                    f"output index {node._output_index} out of range: base "
                    f"'{node._base._name}' produces {n_out} output(s)")
            env[id(node)] = outs[node._output_index]
            continue
        if node._op is None:
            if node._name in shapes:
                env[id(node)] = jax.ShapeDtypeStruct(shapes[node._name], f32)
            else:
                env[id(node)] = None  # param resolved by its consumer
            continue
        if node._op == "_group":
            env[id(node)] = [spec_of(i) for i in node._inputs]
            continue
        attrs = {k: v for k, v in node._attrs.items() if not k.startswith("_")}
        ins = [spec_of(i) for i in node._inputs]
        if node._op in _PARAM_OPS and any(s is None for s in ins[1:]):
            slots, rule = _PARAM_OPS[node._op]
            if ins[0] is None:
                raise MXNetError(
                    f"{node._name}: data input shape unknown; pass it to "
                    "infer_shape/simple_bind")
            slot_shapes = rule(tuple(ins[0].shape), attrs)
            for inp, slot in zip(node._inputs[1:], slots):
                if spec_of(inp) is None and slot in slot_shapes:
                    shapes[inp._name] = slot_shapes[slot]
                    env[id(inp)] = jax.ShapeDtypeStruct(slot_shapes[slot], f32)
            ins = [spec_of(i) for i in node._inputs]
        if any(s is None for s in ins):
            bad = [i._name for i, s in zip(node._inputs, ins) if s is None]
            raise MXNetError(f"{node._name}: unresolved input shapes {bad}")
        if node._op in _SCALAR_OPS:
            try:
                env[id(node)] = jax.eval_shape(
                    lambda x, s=node._attrs["scalar"], o=node._op:
                        _SCALAR_OPS[o](x, s), ins[0])
            except Exception as e:
                raise GraphInferenceError(
                    *_node_provenance(node),
                    f"{e} [input shapes: "
                    f"{[tuple(i.shape) for i in ins]}]") from e
            continue
        opdef = OPS.get(node._op)
        if opdef is None:
            raise MXNetError(f"unknown op {node._op!r} in symbol graph")
        try:
            env[id(node)] = jax.eval_shape(
                lambda *a, _f=opdef.fn, _at=attrs: _f(*a, **_at), *ins)
        except GraphInferenceError:
            raise  # a nested subgraph walk already located the failure
        except Exception as e:
            raise GraphInferenceError(
                *_node_provenance(node),
                f"{e} [input shapes: "
                f"{[tuple(i.shape) for i in ins]}]") from e
    if sink is not None:
        for nid, v in env.items():
            spec = v[0] if isinstance(v, (list, tuple)) else v
            sink[nid] = spec
    out = env[id(root)]
    out_specs = out if isinstance(out, (list, tuple)) else [out]
    return shapes, out_specs


def _primary(v):
    """A multi-output op consumed as a plain symbol yields its primary
    output (reference: nnvm default output 0 — e.g. BatchNorm's out, with
    mean/var reachable only via explicit indexing/get_internals)."""
    return v[0] if isinstance(v, (tuple, list)) else v


def _eval_graph(root: Symbol, arg_names: List[str], vals, sink=None):
    """Topologically evaluate the DAG on concrete/traced arrays. When
    ``sink`` is a dict, every op node's primary output is also recorded
    there by name (the Monitor capture path) — one evaluator serves both so
    the capture can never diverge from the training forward."""
    env: Dict[int, Any] = {}
    name2val = dict(zip(arg_names, vals))
    for node in _topo(root):
        if node._base is not None:
            env[id(node)] = env[id(node._base)][node._output_index]
            continue
        if node._op is None:
            if node._name not in name2val:
                raise MXNetError(f"unbound variable {node._name}")
            env[id(node)] = name2val[node._name]
            continue
        if node._op == "_group":
            env[id(node)] = [_primary(env[id(i)]) for i in node._inputs]
            continue
        ins = [_primary(env[id(i)]) for i in node._inputs]
        attrs = {k: v for k, v in node._attrs.items()
                 if not k.startswith("_")}
        if node._op in _SCALAR_OPS:
            out = _SCALAR_OPS[node._op](ins[0], attrs.pop("scalar"))
        else:
            opdef = OPS.get(node._op)
            if opdef is None:
                raise MXNetError(f"unknown op {node._op!r} in symbol graph; "
                                 f"known ops: {len(OPS)} registered")
            out = opdef.fn(*ins, **attrs)
        env[id(node)] = out
        if sink is not None:
            # distinct nodes can share an auto-name (separate NameManager
            # scopes/threads) — disambiguate instead of silently clobbering
            key = node._name
            n = 2
            while key in sink:
                key = f"{node._name}#{n}"
                n += 1
            sink[key] = _primary(out)
    return env[id(root)]


def _compile_fn(root: Symbol, arg_names: List[str]):
    """Compose the DAG into one pure function of the argument arrays."""

    def fn(*vals):
        return _eval_graph(root, arg_names, vals)

    return fn


class Executor:
    """Bound computation (reference: GraphExecutor via simple_bind —
    SURVEY §3.5). forward/backward run one jitted callable + its vjp."""

    def __init__(self, symbol: Symbol, ctx: Context, args, args_grad,
                 grad_req: str = "write"):
        self._symbol = symbol
        self._ctx = ctx
        if isinstance(args, (list, tuple)):
            names = symbol.list_arguments()
            args = dict(zip(names, args))
        self.arg_dict: Dict[str, NDArray] = dict(args)
        self.grad_dict: Dict[str, NDArray] = dict(args_grad or {})
        self.aux_dict: Dict[str, NDArray] = {}
        self._grad_req = grad_req
        self._arg_names = symbol.list_arguments()
        self._fn = jax.jit(_compile_fn(symbol, self._arg_names))
        self._vjp = None
        self.outputs: List[NDArray] = []

    def forward(self, is_train: bool = False, **kwargs) -> List[NDArray]:
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(
                    v._data if isinstance(v, NDArray) else jnp.asarray(v))
        vals = [self.arg_dict[n]._data for n in self._arg_names]
        if is_train and self._grad_req != "null":
            out, vjp = jax.vjp(lambda *vs: self._fn(*vs), *vals)
            self._vjp = vjp
        else:
            out = self._fn(*vals)
            self._vjp = None
        outs = out if isinstance(out, (list, tuple)) else [out]
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        return self.outputs

    def backward(self, out_grads=None) -> None:
        if self._vjp is None:
            raise MXNetError("backward requires forward(is_train=True)")
        if out_grads is None:
            cot = tuple(jnp.ones_like(o._data) for o in self.outputs)
        else:
            if not isinstance(out_grads, (list, tuple)):
                out_grads = [out_grads]
            cot = tuple(g._data if isinstance(g, NDArray) else jnp.asarray(g)
                        for g in out_grads)
        if len(self.outputs) == 1:
            cot = cot[0]
        else:
            cot = list(cot)
        grads = self._vjp(cot)
        for name, g in zip(self._arg_names, grads):
            if name in self.grad_dict:
                tgt = self.grad_dict[name]
                if self._grad_req == "add":
                    tgt._set_data(tgt._data + g)
                else:
                    tgt._set_data(g)

    def copy_params_from(self, arg_params: Dict, aux_params: Optional[Dict] = None):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(
                    v._data if isinstance(v, NDArray) else jnp.asarray(v))

    def capture_internals(self) -> Dict[str, Any]:
        """Every op node's primary output for the current arguments, keyed
        by node name — the mx.monitor.Monitor seam. Compiled lazily as one
        extra jit program so the normal forward stays a single fused step
        (reference: Monitor hooks the engine's per-op execution callbacks)."""
        if getattr(self, "_capture_fn", None) is None:
            def cap(*vals):
                sink: Dict[str, Any] = {}
                _eval_graph(self._symbol, self._arg_names, vals, sink=sink)
                return sink

            self._capture_fn = jax.jit(cap)
        vals = [self.arg_dict[n]._data for n in self._arg_names]
        res = self._capture_fn(*vals)
        return {k: onp.asarray(v) for k, v in res.items()}


# ---------------------------------------------------------------------------
# constructors + generated op namespace
# ---------------------------------------------------------------------------

def Variable(name: str, shape=None, dtype=None, **kwargs) -> Symbol:
    return Symbol(None, [], attrs={"shape": shape, "dtype": dtype}, name=name)


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    return Symbol("_group", list(symbols), name="group",
                  num_outputs=len(list(symbols)))


def _wire_attr(v):
    """Wire-encode one attr value: Symbols (subgraph attrs of the
    control-flow ops) become nested graph payloads that survive
    repr -> ast.literal_eval; a LIST of Symbols rides as one Group payload
    so shared subgraph structure is serialized once (reference: subgraph
    attrs in the control_flow.cc JSON format)."""
    if isinstance(v, Symbol):
        return {"__sym__": json.loads(v.tojson())}
    if isinstance(v, (list, tuple)):
        if any(isinstance(x, Symbol) for x in v):
            return {"__symlist__": json.loads(Group(list(v)).tojson()),
                    "n": len(v)}
        if isinstance(v, tuple):
            # tuples must survive repr->literal_eval distinctly: shape
            # attrs compared/hased as tuples diverge if lists come back
            return tuple(_wire_attr(x) for x in v)
        return [_wire_attr(x) for x in v]
    if isinstance(v, dict):
        return {k: _wire_attr(x) for k, x in v.items()}
    return v


def _unwire_attr(v):
    if isinstance(v, dict):
        if "__sym__" in v and len(v) == 1:
            return _symbol_from_payload(v["__sym__"])
        if "__symlist__" in v:
            group = _symbol_from_payload(v["__symlist__"])
            return list(group._inputs)
        return {k: _unwire_attr(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_unwire_attr(x) for x in v]
    if isinstance(v, tuple):
        return tuple(_unwire_attr(x) for x in v)
    return v


def _symbol_from_payload(payload: dict) -> Symbol:
    # Two-phase rebuild: construct every node first, then wire inputs by
    # index. tojson emits topological order, but the loader must not rely
    # on it — a malformed file (forward reference, even a cycle) should
    # load into a graph that mx.analysis's verifier can judge (MX001),
    # not die here with an IndexError. Out-of-range indices still raise:
    # there is no node to wire to.
    nodes: List[Symbol] = []
    prev = getattr(_DESERIALIZING, "flag", False)
    _DESERIALIZING.flag = True
    try:
        for nd_ in payload["nodes"]:
            attrs = {}
            for k, v in nd_.get("attrs", {}).items():
                try:
                    # literal_eval only — .json symbol files are an
                    # untrusted load path, never execute code from them
                    attrs[k] = _unwire_attr(ast.literal_eval(v))
                except (ValueError, SyntaxError):
                    attrs[k] = v
            if nd_.get("base") is not None:
                nodes.append(None)  # multi-output slice: resolved below
            else:
                # variable nodes keep their attrs too (AttrScope lr_mult /
                # ctx_group annotations must survive the wire format)
                nodes.append(Symbol(
                    nd_["op"] if nd_["op"] != "null" else None,
                    [], attrs, name=nd_["name"],
                    num_outputs=nd_.get("num_outputs", 1)))
        def _at(idx):
            # explicit bounds check: a negative index must not silently
            # wire to the wrong node via Python wraparound
            if not isinstance(idx, int) or idx < 0 or idx >= len(nodes):
                raise MXNetError(
                    f"symbol JSON: node index {idx!r} out of range "
                    f"[0, {len(nodes)})")
            return nodes[idx]

        # Slice nodes may 'base'-reference forward (and chain); resolve
        # until a full sweep makes no progress.
        pending = [i for i, s in enumerate(nodes) if s is None]
        while pending:
            left = [i for i in pending
                    if _at(payload["nodes"][i]["base"]) is None]
            if len(left) == len(pending):
                raise MXNetError(
                    "symbol JSON: unresolvable multi-output 'base' "
                    f"references at node indices {left}")
            for i in pending:
                nd_ = payload["nodes"][i]
                base = _at(nd_["base"])
                if base is not None:
                    nodes[i] = base[nd_["output_index"]]
            pending = left
        for sym_node, nd_ in zip(nodes, payload["nodes"]):
            if nd_.get("base") is None:
                sym_node._inputs = [_at(i[0]) for i in nd_["inputs"]]
    finally:
        _DESERIALIZING.flag = prev
    return _at(payload["heads"][0][0])


def load_json(s: str) -> Symbol:
    return _symbol_from_payload(json.loads(s))


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def zeros(shape, dtype="float32", **kwargs) -> Symbol:
    return Symbol("_sym_zeros", [],
                  attrs={"shape": shape, "dtype": onp.dtype(dtype).name})


def ones(shape, dtype="float32", **kwargs) -> Symbol:
    return Symbol("_sym_ones", [],
                  attrs={"shape": shape, "dtype": onp.dtype(dtype).name})


def _make_sym_op(opname: str):
    def sym_op(*args, name: Optional[str] = None, **kwargs):
        ins = [a for a in args if isinstance(a, Symbol)]
        ins += [v for v in kwargs.values() if isinstance(v, Symbol)]
        kwargs = {k: v for k, v in kwargs.items() if not isinstance(v, Symbol)}
        node = Symbol(opname, ins, attrs=kwargs, name=name)
        if opname in _PARAM_OPS:
            # auto-create missing weight/bias variables (reference: nnvm
            # ListInputNames — mx.sym.FullyConnected(data, num_hidden=...)
            # grows fc_weight/fc_bias arguments)
            slots, _ = _PARAM_OPS[opname]
            needed = [s for s in slots
                      if not (s == "bias" and kwargs.get("no_bias", False))]
            while len(node._inputs) - 1 < len(needed):
                slot = needed[len(node._inputs) - 1]
                node._inputs.append(Variable(f"{node._name}_{slot}"))
        return node
    sym_op.__name__ = opname
    return sym_op


for _name in list(OPS):
    if not hasattr(_this, _name):
        setattr(_this, _name, _make_sym_op(_name))


def __getattr__(name):
    # mx.sym.contrib — lazy to avoid an import cycle (reference:
    # python/mxnet/symbol/contrib.py; same module as mx.contrib.sym)
    if name == "contrib":
        from ..contrib import sym as _contrib_sym
        return _contrib_sym
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Neural-network ops: conv, FC, pooling, norms, softmax, dropout, RNN.

TPU-native counterpart of ``src/operator/nn/`` (SURVEY §2.4): where the
reference dispatches to cuDNN/mshadow kernels (``cudnn_convolution-inl.h``,
``batch_norm.cu``, ``cudnn_rnn-inl.h``), these lower to ``jax.lax`` ops that
XLA tiles onto the MXU (conv/matmul) and VPU (elementwise/norm), with fusion
replacing the reference's hand-written fused kernels.

Layouts follow MXNet: NCHW for 2-D conv (NCW / NCDHW for 1-D/3-D), weights
OIHW, time-major (T, N, C) for the fused RNN op.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .registry import Field, Schema, Shape, register_op

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _tup(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


# ---------------------------------------------------------------------------
# FullyConnected (reference: fully_connected.cc — cuBLAS gemm → MXU)
# ---------------------------------------------------------------------------

@register_op("FullyConnected", aliases=("fully_connected",), schema=Schema(
    num_hidden=Field(int, None, "Number of hidden units (inferred from the "
                     "weight shape when omitted).", nullable=True),
    no_bias=Field(bool, False, "Whether to disable the bias term."),
    flatten=Field(bool, True, "Collapse all axes but the first before the "
                  "matmul (reference FullyConnectedParam::flatten)."),
))
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False, flatten=True):
    """Linear transform y = x·Wᵀ + b (reference:
    src/operator/nn/fully_connected.cc) — one MXU matmul."""
    if flatten:
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (reference: convolution.cc + cudnn wrappers)
# ---------------------------------------------------------------------------

_CONV_SPECS = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"), 3: ("NCDHW", "OIDHW", "NCDHW")}


def _conv_dims(kernel):
    return len(kernel) if not isinstance(kernel, int) else 1


@register_op("Convolution", aliases=("convolution",), schema=Schema(
    ignore=("cudnn_tune", "cudnn_off", "workspace"),
    kernel=Field(Shape, describe="Convolution kernel size, e.g. (3, 3)."),
    stride=Field(Shape, None, "Convolution stride; defaults to 1 per dim.",
                 nullable=True),
    dilate=Field(Shape, None, "Convolution dilation; defaults to 1 per dim.",
                 nullable=True),
    pad=Field(Shape, None, "Zero-padding per spatial dim; defaults to 0.",
              nullable=True),
    num_filter=Field(int, None, "Number of output channels (inferred from "
                     "the weight when omitted).", nullable=True, ge=1),
    num_group=Field(int, 1, "Grouped-convolution group count "
                    "(feature_group_count in the XLA lowering).", ge=1),
    no_bias=Field(bool, False, "Whether to disable the bias term."),
    layout=Field(str, None, "Data layout; only the reference default "
                 "NC(DHW) layouts are supported.", nullable=True,
                 choices=("NCW", "NCHW", "NCDHW")),
))
def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False, layout=None):
    """N-d convolution over NC(DHW) via lax.conv_general_dilated (reference:
    src/operator/nn/convolution.cc + cudnn wrappers, subsumed by XLA)."""
    nd = _conv_dims(kernel)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad if pad is not None else 0, nd)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_SPECS[nd])
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=None,
    )
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


_IM2COL_FIELDS = dict(
    kernel=Field(Shape, describe="Sliding-window size, e.g. (3, 3)."),
    stride=Field(Shape, None, "Window stride; defaults to 1 per dim.",
                 nullable=True),
    dilate=Field(Shape, None, "Window dilation; defaults to 1 per dim.",
                 nullable=True),
    pad=Field(Shape, None, "Zero-padding per spatial dim; defaults to 0.",
              nullable=True),
)


@register_op("im2col", schema=Schema(**_IM2COL_FIELDS))
def im2col(data, kernel=None, stride=None, dilate=None, pad=None):
    """Sliding-window patch extraction (reference: nn/im2col.cc): output
    (N, C·∏kernel, ∏out_spatial) with channel-major row order — exactly the
    layout lax.conv_general_dilated_patches produces."""
    nd = _conv_dims(kernel)
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad if pad is not None else 0, nd)
    patches = lax.conv_general_dilated_patches(
        data, filter_shape=tuple(kernel), window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate)
    return patches.reshape(patches.shape[0], patches.shape[1], -1)


@register_op("col2im", schema=Schema(
    output_size=Field(Shape, describe="Spatial shape of the output image."),
    **_IM2COL_FIELDS))
def col2im(data, output_size=None, kernel=None, stride=None, dilate=None,
           pad=None):
    """Patch scatter-accumulate, the linear transpose of :func:`im2col`
    (reference: nn/im2col.cc col2im) — derived via jax.linear_transpose from
    an abstract trace (no forward pass runs) so both ops stay consistent by
    construction; overlapping positions sum."""
    import math
    output_size = tuple(output_size)
    n, ckk, _ = data.shape
    kernel = _tup(kernel, len(output_size))
    channels = ckk // math.prod(kernel)
    img_shape = (n, channels) + output_size
    transpose = jax.linear_transpose(
        lambda img: im2col(img, kernel=kernel, stride=stride, dilate=dilate,
                           pad=pad),
        jax.ShapeDtypeStruct(img_shape, data.dtype))
    return transpose(data)[0]


@register_op("Deconvolution", aliases=("deconvolution",), schema=Schema(
    ignore=("cudnn_tune", "cudnn_off", "workspace"),
    kernel=Field(Shape, describe="Deconvolution kernel size."),
    stride=Field(Shape, None, "Stride (lhs_dilation in the XLA lowering).",
                 nullable=True),
    dilate=Field(Shape, None, "Dilation.", nullable=True),
    pad=Field(Shape, None, "Padding removed from the output.", nullable=True),
    adj=Field(Shape, None, "Output-size adjustment per spatial dim.",
              nullable=True),
    num_filter=Field(int, None, "Number of output channels.", nullable=True,
                     ge=1),
    num_group=Field(int, 1, "Group count.", ge=1),
    no_bias=Field(bool, False, "Whether to disable the bias term."),
    target_shape=Field(Shape, None, "Explicit output spatial shape.",
                       nullable=True),
    layout=Field(str, None, "Data layout.", nullable=True,
                 choices=("NCW", "NCHW", "NCDHW")),
))
def deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, num_filter=None, num_group=1, no_bias=False,
                  target_shape=None, layout=None):
    nd = _conv_dims(kernel)
    stride = _tup(stride, nd)
    pad = _tup(pad if pad is not None else 0, nd)
    adj = _tup(adj if adj is not None else 0, nd)
    # ConvTranspose = gradient of conv: lhs_dilation implements fractional stride.
    # weight layout for MXNet Deconvolution is (in, out/g, *k).
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_SPECS[nd])
    k = weight.shape[2:]
    padding = [(k[i] - 1 - pad[i], k[i] - 1 - pad[i] + adj[i]) for i in range(nd)]
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if num_group == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        ci, co = w.shape[0], w.shape[1]
        w = w.reshape(num_group, ci // num_group, co, *k)
        w = jnp.swapaxes(w, 1, 2).reshape(num_group * co, ci // num_group, *k)
    out = lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=stride,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling (reference: pooling.cc → lax.reduce_window)
# ---------------------------------------------------------------------------

@register_op("Pooling", aliases=("pooling",), schema=Schema(
    ignore=("cudnn_off", "p_value"),
    kernel=Field(Shape, None, "Pooling window size.", nullable=True),
    pool_type=Field(str, "max", "Pooling reduction.",
                    choices=("max", "avg", "sum", "lp")),
    global_pool=Field(bool, False, "Pool over the whole spatial extent."),
    stride=Field(Shape, None, "Window stride; defaults to 1 per dim.",
                 nullable=True),
    pad=Field(Shape, None, "Zero padding; defaults to 0.", nullable=True),
    pooling_convention=Field(str, "valid", "Output-size rounding rule.",
                             choices=("valid", "full", "same")),
    count_include_pad=Field(bool, True, "Average counts padded cells."),
    layout=Field(str, None, "Data layout.", nullable=True,
                 choices=("NCW", "NCHW", "NCDHW")),
))
def pooling(data, kernel=None, pool_type="max", global_pool=False, stride=None,
            pad=None, pooling_convention="valid", count_include_pad=True, layout=None):
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, 2 + nd))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd)
    pad = _tup(pad if pad is not None else 0, nd)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pooling_convention == "full":
        # ceil-mode: add extra right-padding so the last window fits
        extra = []
        for i in range(nd):
            size = data.shape[2 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            extra.append(0 if rem == 0 else stride[i] - rem)
        padding = ((0, 0), (0, 0)) + tuple((p, p + e) for p, e in zip(pad, extra))
    elif pooling_convention == "same":
        # out = ceil(in/stride): distribute the needed pad low/high (extra on
        # the high side), on top of any explicit pad.
        pads = []
        for i in range(nd):
            size = data.shape[2 + i] + 2 * pad[i]
            out = -(-size // stride[i])
            total = max((out - 1) * stride[i] + kernel[i] - size, 0)
            pads.append((pad[i] + total // 2, pad[i] + total - total // 2))
        padding = ((0, 0), (0, 0)) + tuple(pads)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1.0
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return summed / counts
    if pool_type == "lp":
        p = 2.0
        s = lax.reduce_window(jnp.abs(data) ** p, 0.0, lax.add, window, strides, padding)
        return s ** (1.0 / p)
    raise ValueError(f"unknown pool_type {pool_type}")


# ---------------------------------------------------------------------------
# Normalization (reference: batch_norm.cc, layer_norm.cc, group_norm.cc)
# ---------------------------------------------------------------------------

@register_op("BatchNorm", aliases=("batch_norm",), schema=Schema(
    ignore=("cudnn_off",),
    eps=Field(float, 1e-5, "Epsilon added to the variance.", ge=0.0),
    momentum=Field(float, 0.9, "Moving-average momentum for running stats."),
    fix_gamma=Field(bool, True, "Treat gamma as constant 1 (reference "
                    "BatchNormParam::fix_gamma)."),
    use_global_stats=Field(bool, False, "Always normalize with the running "
                           "statistics, even in training."),
    output_mean_var=Field(bool, False, "Also return the batch mean/var."),
    axis=Field(int, 1, "Channel axis."),
    training=Field(bool, False, "Training mode (batch statistics)."),
))
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-5, momentum=0.9,
               fix_gamma=True, use_global_stats=False, output_mean_var=False,
               axis=1, training=False):
    """Returns (out, batch_mean, batch_var). The layer updates running stats
    functionally from the returned batch statistics (aux-state discipline —
    see gluon/nn BatchNorm; reference mutates aux states inside the op)."""
    # statistics and normalization in fp32 (AMP discipline: the layer keeps
    # gamma/beta/running stats fp32 under cast('bfloat16')); the output drops
    # back to the activation dtype so bf16 nets stay bf16 end-to-end
    x32 = data.astype(jnp.float32)
    axes = tuple(i for i in range(data.ndim) if i != axis)
    if training and not use_global_stats:
        m = jnp.mean(x32, axis=axes)
        v = jnp.var(x32, axis=axes)
    else:
        m = moving_mean.astype(jnp.float32)
        v = moving_var.astype(jnp.float32)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    out = ((x32 - m.reshape(shape)) * lax.rsqrt(v.reshape(shape) + eps)
           * g.reshape(shape).astype(jnp.float32)
           + beta.reshape(shape).astype(jnp.float32))
    return out.astype(data.dtype), m, v


@register_op("LayerNorm", aliases=("layer_norm",), schema=Schema(
    axis=Field(int, -1, "Axis to normalize over."),
    eps=Field(float, 1e-5, "Epsilon added to the variance.", ge=0.0),
    output_mean_var=Field(bool, False, "Also return mean/var."),
))
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    # statistics in fp32 regardless of activation dtype (bf16 mean/var
    # loses ~3 decimal digits; the reference computes fp32 throughout and
    # XLA fuses the casts into the same kernel)
    x32 = data.astype(jnp.float32)
    m = jnp.mean(x32, axis=axis, keepdims=True)
    v = jnp.var(x32, axis=axis, keepdims=True)
    out = ((x32 - m) * lax.rsqrt(v + eps)).astype(data.dtype)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = out * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(m, axis), jnp.squeeze(v, axis)
    return out


@register_op("GroupNorm")
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5, **_):
    n, c = data.shape[0], data.shape[1]
    rest = data.shape[2:]
    x = data.reshape(n, num_groups, c // num_groups, *rest)
    axes = tuple(range(2, x.ndim))
    m = jnp.mean(x, axis=axes, keepdims=True)
    v = jnp.var(x, axis=axes, keepdims=True)
    x = (x - m) * lax.rsqrt(v + eps)
    x = x.reshape(data.shape)
    shape = (1, c) + (1,) * len(rest)
    return x * gamma.reshape(shape) + beta.reshape(shape)


@register_op("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3, **_):
    axes = tuple(range(2, data.ndim))
    m = jnp.mean(data, axis=axes, keepdims=True)
    v = jnp.var(data, axis=axes, keepdims=True)
    x = (data - m) * lax.rsqrt(v + eps)
    shape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


@register_op("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance", **_):
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / nrm


@register_op("RMSNorm", aliases=("rms_norm",))
def rms_norm(data, gamma, axis=-1, eps=1e-6, **_):
    """TPU-era extension (not in reference): RMSNorm for LLaMA-family models.
    Statistics in fp32 (see layer_norm)."""
    x32 = data.astype(jnp.float32)
    v = jnp.mean(jnp.square(x32), axis=axis, keepdims=True)
    return (x32 * lax.rsqrt(v + eps)).astype(data.dtype) * gamma


# ---------------------------------------------------------------------------
# Activations (reference: activation.cc, leaky_relu.cc)
# ---------------------------------------------------------------------------

_ACTS = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    # extended set (Gluon Activation accepts these in the TPU build; the
    # reference routes them through LeakyReLU/contrib ops instead)
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
}


@register_op("Activation", aliases=("activation",), schema=Schema(
    act_type=Field(str, describe="Activation function to apply.",
                   choices=("relu", "sigmoid", "tanh", "softrelu", "softsign",
                            "gelu", "gelu_tanh", "silu", "swish", "mish")),
))
def activation(data, act_type="relu"):
    return _ACTS[act_type](data)


@register_op("LeakyReLU", aliases=("leaky_relu",), schema=Schema(
    gamma=Field(object, None, "Learnable slope tensor (prelu).",
                nullable=True),
    act_type=Field(str, "leaky", "Leaky-family activation variant.",
                   choices=("leaky", "prelu", "elu", "selu", "gelu", "rrelu")),
    slope=Field(float, 0.25, "Negative slope (leaky/elu)."),
    lower_bound=Field(float, 0.125, "rrelu lower slope bound."),
    upper_bound=Field(float, 0.334, "rrelu upper slope bound."),
))
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim:
            shape = [1] * data.ndim
            if data.ndim > 1:
                shape[1] = g.size
            g = g.reshape(shape)
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, mid * data)
    raise ValueError(f"unknown act_type {act_type}")


@register_op("gelu_tanh")
def gelu_tanh(data, **_):
    return jax.nn.gelu(data, approximate=True)


@register_op("silu", aliases=("swish",))
def silu(data, **_):
    return data * jax.nn.sigmoid(data)


# ---------------------------------------------------------------------------
# Softmax family (reference: softmax.cc incl. SoftmaxWithLength)
# ---------------------------------------------------------------------------

@register_op("softmax", schema=Schema(
    length=Field(object, None, "Per-row valid lengths (SoftmaxWithLength).",
                 nullable=True),
    axis=Field(int, -1, "Axis to normalize over."),
    temperature=Field(float, None, "Softmax temperature.", nullable=True),
    use_length=Field(bool, False, "Mask positions >= length along axis."),
    dtype=Field(str, None, "Accepted for parity; output follows input dtype.",
                nullable=True),
))
def softmax(data, length=None, axis=-1, temperature=None, use_length=False, dtype=None):
    x = data / temperature if temperature not in (None, 1.0) else data
    if use_length and length is not None:
        # mask positions >= length along `axis` (SoftmaxWithLength)
        T = data.shape[axis]
        steps = jnp.arange(T)
        shape = [1] * data.ndim
        shape[axis] = T
        lshape = list(data.shape)
        lshape[axis] = 1
        mask = steps.reshape(shape) < length.reshape(lshape).astype(jnp.int32)
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def log_softmax(data, axis=-1, temperature=None, **_):
    x = data / temperature if temperature not in (None, 1.0) else data
    return jax.nn.log_softmax(x, axis=axis)


@register_op("softmin")
def softmin(data, axis=-1, **_):
    return jax.nn.softmax(-data, axis=axis)


@register_op("SoftmaxActivation")
def softmax_activation(data, mode="instance", **_):
    """Deprecated-but-present reference op (softmax_activation-inl.h):
    ``instance`` normalizes each example over all remaining dims, ``channel``
    normalizes across axis 1 at every spatial position."""
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    flat = data.reshape(data.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(data.shape)


@register_op("masked_softmax")
def masked_softmax(data, mask=None, axis=-1, temperature=1.0, **_):
    x = data / temperature
    if mask is not None:
        x = jnp.where(mask != 0, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=axis)
    if mask is not None:
        out = jnp.where(mask != 0, out, 0.0)
    return out


@register_op("SoftmaxOutput", aliases=("softmax_output",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1, multi_output=False,
                   use_ignore=False, preserve_shape=False, normalization="null",
                   out_grad=False, smooth_alpha=0.0, **_):
    """Forward = softmax; backward = (p - onehot(label)) * grad_scale,
    IGNORING the incoming head gradient (reference: softmax_output-inl.h —
    the op fuses the cross-entropy loss gradient; Module-era nets end in it
    and call backward() with no explicit loss)."""
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def _f(x, lab):
        return jax.nn.softmax(x, axis=axis)

    def _fwd(x, lab):
        p = jax.nn.softmax(x, axis=axis)
        return p, (p, lab)

    def _bwd(res, g):
        p, lab = res
        k = p.shape[axis]
        oh = jax.nn.one_hot(lab.astype(jnp.int32), k, axis=axis, dtype=p.dtype)
        if smooth_alpha:
            oh = oh * (1.0 - smooth_alpha) + smooth_alpha / k
        gx = p - oh
        if use_ignore:
            keep = (lab != ignore_label)
            gx = gx * jnp.expand_dims(keep.astype(p.dtype), axis)
            if normalization == "valid":
                gx = gx / jnp.maximum(jnp.sum(keep), 1.0)
        if normalization == "batch":
            gx = gx / p.shape[0]
        if out_grad:
            gx = gx * g
        return gx * grad_scale, jnp.zeros_like(lab)

    _f.defvjp(_fwd, _bwd)
    return _f(data, label)


@register_op("softmax_cross_entropy")
def softmax_cross_entropy(data, label, **_):
    logp = jax.nn.log_softmax(data, axis=-1)
    nll = -jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return jnp.sum(nll)


@register_op("smooth_l1")
def smooth_l1(data, scalar=1.0, **_):
    s2 = scalar * scalar
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * jnp.square(data), a - 0.5 / s2)


def _loss_output(fwd_fn, grad_fn):
    """Output-head factory (reference: regression_output-inl.h family):
    forward applies ``fwd_fn``; backward IGNORES the incoming head gradient
    and emits the fused loss gradient ``grad_fn(pred, label)`` — Module-era
    nets end in these and call backward() with no explicit loss."""

    @jax.custom_vjp
    def _f(x, lab):
        return fwd_fn(x)

    def _vfwd(x, lab):
        p = fwd_fn(x)
        return p, (p, lab)

    def _vbwd(res, g):
        p, lab = res
        return grad_fn(p, lab.astype(p.dtype)), jnp.zeros_like(lab)

    _f.defvjp(_vfwd, _vbwd)
    return _f


def _per_example_outputs(label) -> float:
    """num_output in the reference's regression heads: outputs per example
    (label.Size()/label.shape[0]); gradients are scaled by
    grad_scale/num_output so multi-output regression averages, not sums."""
    n = 1
    for d in label.shape[1:]:
        n *= int(d)
    return float(max(n, 1))


@register_op("LinearRegressionOutput", aliases=("linear_regression_output",))
def linear_regression_output(data, label, grad_scale=1.0, **_):
    """Identity forward; backward = (pred − label)·grad_scale/num_output
    (reference: src/operator/regression_output.cc LinearRegressionOutput)."""
    return _loss_output(
        lambda x: x,
        lambda p, l: (p - l) * (grad_scale / _per_example_outputs(l))
    )(data, label)


@register_op("LogisticRegressionOutput", aliases=("logistic_regression_output",))
def logistic_regression_output(data, label, grad_scale=1.0, **_):
    """Sigmoid forward; backward = (σ(x) − label)·grad_scale/num_output
    (reference: regression_output.cc LogisticRegressionOutput)."""
    return _loss_output(
        jax.nn.sigmoid,
        lambda p, l: (p - l) * (grad_scale / _per_example_outputs(l))
    )(data, label)


@register_op("MAERegressionOutput", aliases=("mae_regression_output",))
def mae_regression_output(data, label, grad_scale=1.0, **_):
    """Identity forward; backward = sign(pred − label)·grad_scale/num_output
    (reference: regression_output.cc MAERegressionOutput)."""
    return _loss_output(
        lambda x: x,
        lambda p, l: jnp.sign(p - l) * (grad_scale / _per_example_outputs(l))
    )(data, label)


@register_op("SVMOutput", aliases=("svm_output",))
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False, **_):
    """One-vs-all SVM output head (reference: src/operator/svm_output.cc):
    identity forward over class scores; backward is the hinge-loss gradient —
    L2-SVM by default, L1-SVM (linear) with ``use_linear``. Per class c the
    sign is +1 for the labeled class, −1 otherwise."""
    reg = regularization_coefficient

    def _grad(p, lab):
        k = p.shape[-1]
        y = 2.0 * jax.nn.one_hot(lab.astype(jnp.int32), k, dtype=p.dtype) - 1.0
        viol = margin - y * p          # >0 where the margin is violated
        active = (viol > 0).astype(p.dtype)
        if use_linear:
            return -reg * y * active
        return -2.0 * reg * y * viol * active

    return _loss_output(lambda x: x, _grad)(data, label)


@register_op("LRN", aliases=("lrn",), schema=Schema(
    alpha=Field(float, 1e-4, "Scale of the squared local sum."),
    beta=Field(float, 0.75, "Exponent of the normalizer."),
    knorm=Field(float, 2.0, "Additive constant."),
    nsize=Field(int, 5, "Channel window (normalization width).", ge=1),
))
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **_):
    """Across-channel local response normalization over NCHW (reference:
    src/operator/nn/lrn.cc — the AlexNet normalizer):
    ``out = x · (knorm + α/n · Σ_{local} x²)^{−β}``. The channel-window sum
    lowers to reduce_window, which XLA fuses with the pointwise tail."""
    sq = jnp.square(data).astype(jnp.float32)
    half = nsize // 2
    local = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add,
        window_dimensions=(1, nsize, 1, 1), window_strides=(1, 1, 1, 1),
        padding=((0, 0), (half, nsize - 1 - half), (0, 0), (0, 0)))
    norm = jnp.power(knorm + (alpha / nsize) * local, -beta)
    return (data.astype(jnp.float32) * norm).astype(data.dtype)


# ---------------------------------------------------------------------------
# Dropout (reference: dropout.cc — cuDNN dropout state ≙ explicit key)
# ---------------------------------------------------------------------------

@register_op("Dropout", aliases=("dropout",), schema=Schema(
    ignore=("cudnn_off",),
    p=Field(float, 0.5, "Fraction of units to drop.", ge=0.0, le=1.0),
    mode=Field(str, "training", "When to apply dropout.",
               choices=("training", "always")),
    axes=Field(Shape, (), "Axes to broadcast the drop mask over."),
    training=Field(bool, False, "Training mode (apply the mask)."),
    key=Field(object, None, "PRNG key (threaded by the RNG trace scope).",
              nullable=True),
))
def dropout(data, p=0.5, mode="training", axes=(), training=False, key=None):
    if not training or p <= 0.0 or key is None:
        return data
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


# ---------------------------------------------------------------------------
# UpSampling / resize (reference: upsampling.cc, bilinear_resize.cc)
# ---------------------------------------------------------------------------

@register_op("UpSampling")
def upsampling(data, scale=1, sample_type="nearest", num_args=1, **_):
    n, c, h, w = data.shape
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    return jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")


@register_op("contrib_BilinearResize2D", aliases=("bilinear_resize_2d",))
def bilinear_resize_2d(data, height=None, width=None, scale_height=None, scale_width=None, **_):
    n, c, h, w = data.shape
    oh = height if height else int(h * scale_height)
    ow = width if width else int(w * scale_width)
    return jax.image.resize(data, (n, c, oh, ow), method="bilinear")


# ---------------------------------------------------------------------------
# Fused RNN op (reference: rnn.cc / cudnn_rnn-inl.h → lax.scan)
# ---------------------------------------------------------------------------

def _lstm_cell(x, h, c, wx, wh, bx, bh):
    gates = jnp.matmul(x, wx.T) + jnp.matmul(h, wh.T) + bx + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


def _gru_cell(x, h, wx, wh, bx, bh):
    xr, xz, xn = jnp.split(jnp.matmul(x, wx.T) + bx, 3, axis=-1)
    hr, hz, hn = jnp.split(jnp.matmul(h, wh.T) + bh, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1 - z) * n + z * h


def _rnn_cell(x, h, wx, wh, bx, bh, act):
    return act(jnp.matmul(x, wx.T) + jnp.matmul(h, wh.T) + bx + bh)


def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}[mode]


def rnn_unpack_params(params, mode, num_layers, input_size, hidden, bidirectional):
    """Slice MXNet's flat fused-RNN parameter vector into per-layer weights.
    Layout (cuDNN order, reference rnn-inl.h): all Wx,Wh per layer/direction,
    then all bx,bh."""
    ngates = _gates(mode)
    dirs = 2 if bidirectional else 1
    shapes = []
    for layer in range(num_layers):
        isz = input_size if layer == 0 else hidden * dirs
        for _ in range(dirs):
            shapes.append((ngates * hidden, isz))   # wx
            shapes.append((ngates * hidden, hidden))  # wh
    bias_shapes = []
    for layer in range(num_layers):
        for _ in range(dirs):
            bias_shapes.append((ngates * hidden,))
            bias_shapes.append((ngates * hidden,))
    ws, off = [], 0
    for s in shapes:
        n = s[0] * (s[1] if len(s) > 1 else 1)
        ws.append(params[off:off + n].reshape(s))
        off += n
    bs = []
    for s in bias_shapes:
        bs.append(params[off:off + s[0]].reshape(s))
        off += s[0]
    return ws, bs


def rnn_param_size(mode, num_layers, input_size, hidden, bidirectional):
    ngates = _gates(mode)
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else hidden * dirs
        size += dirs * ngates * hidden * (isz + hidden + 2)
    return size


@register_op("RNN", schema=Schema(
    ignore=("lstm_state_clip_min", "lstm_state_clip_max",
            "lstm_state_clip_nan", "use_sequence_length"),
    state_size=Field(int, describe="Hidden state size.", ge=1),
    num_layers=Field(int, 1, "Number of stacked layers.", ge=1),
    mode=Field(str, "lstm", "Cell type.",
               choices=("rnn_relu", "rnn_tanh", "lstm", "gru")),
    bidirectional=Field(bool, False, "Run a reverse direction too."),
    p=Field(float, 0.0, "Inter-layer dropout (ignored at 0).", ge=0.0, le=1.0),
    state_outputs=Field(bool, False, "Also return the final states."),
    projection_size=Field(int, None, "LSTMP projection size.", nullable=True),
))
def rnn(data, parameters, state, state_cell=None, state_size=None, num_layers=1,
        mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
        projection_size=None):
    """Fused multi-layer (bi)RNN. data: (T, N, C) time-major like the
    reference. Returns out or (out, h_n[, c_n]) per state_outputs."""
    T, N, C = data.shape
    hidden = state_size
    dirs = 2 if bidirectional else 1
    ws, bs = rnn_unpack_params(parameters, mode, num_layers, C, hidden, bidirectional)
    act = jnp.tanh if mode != "rnn_relu" else (lambda x: jnp.maximum(x, 0))

    x = data
    h_states, c_states = [], []
    for layer in range(num_layers):
        outs_dir = []
        for d in range(dirs):
            wi = ws[(layer * dirs + d) * 2]
            wh = ws[(layer * dirs + d) * 2 + 1]
            bi = bs[(layer * dirs + d) * 2]
            bh = bs[(layer * dirs + d) * 2 + 1]
            h0 = state[layer * dirs + d]
            seq = x if d == 0 else jnp.flip(x, axis=0)
            if mode == "lstm":
                c0 = state_cell[layer * dirs + d]

                def step(carry, xt):
                    h, c = carry
                    h2, c2 = _lstm_cell(xt, h, c, wi, wh, bi, bh)
                    return (h2, c2), h2

                (hT, cT), out = lax.scan(step, (h0, c0), seq)
                c_states.append(cT)
            elif mode == "gru":
                def step(h, xt):
                    h2 = _gru_cell(xt, h, wi, wh, bi, bh)
                    return h2, h2

                hT, out = lax.scan(step, h0, seq)
            else:
                def step(h, xt):
                    h2 = _rnn_cell(xt, h, wi, wh, bi, bh, act)
                    return h2, h2

                hT, out = lax.scan(step, h0, seq)
            h_states.append(hT)
            if d == 1:
                out = jnp.flip(out, axis=0)
            outs_dir.append(out)
        x = jnp.concatenate(outs_dir, axis=-1) if dirs == 2 else outs_dir[0]

    outs = [x, jnp.stack(h_states, axis=0)]
    if mode == "lstm":
        outs.append(jnp.stack(c_states, axis=0))
    if state_outputs:
        return tuple(outs)
    return x


# ---------------------------------------------------------------------------
# CTC loss (reference: ctc_loss.cc — forward-backward via scan in log space)
# ---------------------------------------------------------------------------

@register_op("CTCLoss", aliases=("ctc_loss",))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False, blank_label="first", **_):
    """data: (T, N, C) activations (pre-softmax); label: (N, L) padded with
    -1 (or 0s when blank_label='last'). Returns per-example loss (N,)."""
    T, N, C = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    blank = 0 if blank_label == "first" else C - 1
    L = label.shape[1]
    lab = label.astype(jnp.int32)
    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    elif blank_label == "first":
        # blank is class 0, real labels are 1..C-1, padding is 0 or -1
        # (reference semantics: ctc_loss label packing).
        lab_len = jnp.sum(lab > 0, axis=1).astype(jnp.int32)
    else:
        # blank is class C-1, real labels are 0..C-2, padding is -1.
        lab_len = jnp.sum(lab >= 0, axis=1).astype(jnp.int32)
    # Padded entries may be -1; clamp to blank so ext never holds a negative
    # class index (those positions sit beyond 2*lab_len and cannot influence
    # the left-to-right alpha recurrence).
    lab = jnp.where(lab >= 0, lab, blank)
    t_len = (data_lengths.astype(jnp.int32) if use_data_lengths and data_lengths is not None
             else jnp.full((N,), T, jnp.int32))

    S = 2 * L + 1
    # extended label: blank, l1, blank, l2, ... blank
    ext = jnp.full((N, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    neg_inf = -1e30

    def per_example(logp_n, ext_n, ll, tl):
        # alpha: (S,)
        alpha0 = jnp.full((S,), neg_inf)
        alpha0 = alpha0.at[0].set(logp_n[0, blank])
        alpha0 = alpha0.at[1].set(jnp.where(ll > 0, logp_n[0, ext_n[1]], neg_inf))

        allow_skip = jnp.concatenate([
            jnp.array([False, False]),
            (ext_n[2:] != blank) & (ext_n[2:] != ext_n[:-2]),
        ])

        def step(alpha, t):
            a_prev1 = jnp.concatenate([jnp.array([neg_inf]), alpha[:-1]])
            a_prev2 = jnp.concatenate([jnp.array([neg_inf, neg_inf]), alpha[:-2]])
            a_prev2 = jnp.where(allow_skip, a_prev2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_prev1), a_prev2)
            new = merged + logp_n[t, ext_n]
            new = jnp.where(t < tl, new, alpha)
            return new, None

        alphaT, _ = lax.scan(step, alpha0, jnp.arange(1, T))
        end = 2 * ll
        p1 = alphaT[end]
        p2 = jnp.where(end - 1 >= 0, alphaT[jnp.maximum(end - 1, 0)], neg_inf)
        return -jnp.logaddexp(p1, p2)

    return jax.vmap(per_example)(jnp.transpose(logp, (1, 0, 2)), ext, lab_len, t_len)

"""Pure-JAX operator library (the ``src/operator/`` counterpart).

Importing this package registers all ops into ``registry.OPS``; the
``mx.nd`` namespace is generated from that registry.
"""
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import attention  # noqa: F401
from . import detection  # noqa: F401
from . import quantization  # noqa: F401
from . import vision  # noqa: F401
from . import control_flow  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import subgraph_ops  # noqa: F401
from .registry import OPS, OpDef, register_op, alias_op  # noqa: F401

"""Fused attention ops — the TPU counterpart of the contrib transformer ops.

Reference parity: ``src/operator/contrib/transformer.cc / .cu`` —
``_contrib_interleaved_matmul_selfatt_qk``,
``_contrib_interleaved_matmul_selfatt_valatt``,
``_contrib_interleaved_matmul_encdec_qk``,
``_contrib_interleaved_matmul_encdec_valatt`` — the fused interleaved
multi-head-attention matmuls GluonNLP's BERT uses (SURVEY §2.4, §5.7), plus
``SoftmaxWithLength`` masking (``src/operator/nn/softmax.cc``).

TPU-native design: instead of hand-scheduled cuBLAS strided-batch GEMMs, the
headline primitive is :func:`dot_product_attention` — a single fused
(scores → mask → softmax → context) computation. On TPU backends it lowers to
a blockwise **flash attention** (never materializing the L×L matrix in HBM,
see ``ops/pallas/flash_attention.py``); elsewhere XLA fuses the jnp graph.
The interleaved_* ops are kept with reference semantics (layouts included)
so ported GluonNLP model code runs unchanged.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

__all__ = [
    "dot_product_attention",
    "interleaved_matmul_selfatt_qk",
    "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk",
    "interleaved_matmul_encdec_valatt",
]

_NEG = -1e30


def _mask_bias(mask, dtype):
    """Boolean/0-1 mask -> additive bias (0 keep, -inf drop)."""
    return jnp.where(mask.astype(bool), jnp.zeros((), dtype), jnp.full((), _NEG, dtype))


def _maybe_ring(query, key, value, mask, causal, scale):
    """Lower to ring attention when an active mesh shards sequence over sp.

    Conditions: tracing (inside a compiled step), sp>1, self-attention
    (Lq == Lk, divisible over sp), and a key-padding-style mask (or none).
    Returns None to fall through to the single-shard paths.
    """
    from ..parallel.mesh import current_active_mesh
    mesh = current_active_mesh()
    if mesh is None or mesh.shape.get("sp", 1) <= 1:
        return None
    if not isinstance(query, jax.core.Tracer):
        return None
    if query.ndim != 4 or key.shape != value.shape:
        return None
    B, H, Lq, D = query.shape
    Lk = key.shape[2]
    sp = mesh.shape["sp"]
    if Lq != Lk or Lq % sp:
        return None
    dp = mesh.shape.get("dp", 1)
    tp = mesh.shape.get("tp", 1)
    if B % max(dp, 1) or H % max(tp, 1):
        return None
    key_mask = None
    if mask is not None:
        from .pallas.flash_attention import _as_key_mask
        key_mask = _as_key_mask(mask, B, H, Lq, Lk)
        if key_mask is None:
            return None                     # dense masks stay on XLA path
        if key_mask.shape[1] % sp:
            return None
    from functools import partial
    from ..parallel.collectives import shard_map
    from ..parallel.ring import ring_attention
    from jax.sharding import PartitionSpec as P
    bspec = "dp" if dp > 1 else None
    hspec = "tp" if tp > 1 else None
    spec = P(bspec, hspec, "sp", None)
    if key_mask is None:
        fn = shard_map(
            partial(ring_attention, key_mask=None, axis="sp",
                    causal=causal, scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return fn(query, key, value)
    mspec = P(bspec, "sp")
    fn = shard_map(
        partial(ring_attention, axis="sp", causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec, mspec), out_specs=spec)
    return fn(query, key, value, key_mask)


@register_op()
def dot_product_attention(query, key, value, mask=None, causal=False,
                          scale=None, impl="auto", window=None, **_):
    """Fused scaled-dot-product attention.

    Shapes: ``query (B, H, Lq, D)``, ``key/value (B, H, Lk, D)``,
    ``mask`` broadcastable to ``(B, H, Lq, Lk)`` (1 = attend). Returns
    ``(B, H, Lq, D)``.

    ``impl``: "auto" picks the Pallas flash kernel on TPU when shapes allow,
    else the XLA-fused jnp path; "xla" / "flash" force one (env override:
    MXTPU_ATTN_IMPL).

    ``window`` (with ``causal=True``): causal sliding-window attention over
    the ``window`` most recent keys — O(L·window) on the flash path (dead
    tiles skipped), a banded mask on the XLA path.
    """
    import os
    impl = os.environ.get("MXTPU_ATTN_IMPL", impl)
    scale = (query.shape[-1] ** -0.5) if scale is None else scale
    if window is not None:
        window = int(window)
        if not causal:
            raise ValueError("window= requires causal=True")
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        if impl == "ring":
            raise ValueError(
                "impl='ring' does not support window= (the band does not "
                "decompose over ring hops); use impl='auto'/'flash'")
    # Sequence parallelism: when tracing under a mesh with sp>1 (ShardedTrainer
    # binds it via parallel.mesh.active_mesh), lower to ring attention — K/V
    # shards rotate over the sp axis, the per-hop block attention is the
    # Pallas flash kernel. See parallel/ring.py. (A sliding window stays on
    # the local paths: the band doesn't decompose over ring hops.)
    if impl in ("auto", "ring") and window is None:
        ring_out = _maybe_ring(query, key, value, mask, causal, scale)
        if ring_out is not None:
            return ring_out
    use_flash = False
    if impl in ("auto", "flash"):
        try:
            from .pallas.flash_attention import flash_attention, flash_supported
            use_flash = impl == "flash" or flash_supported(query, key, value, mask)
        except Exception:
            use_flash = False
    if use_flash:
        from .pallas.flash_attention import flash_attention
        return flash_attention(query, key, value, mask=mask, causal=causal,
                               scale=scale, window=window)
    acc = jnp.float32
    s = jnp.einsum("bhqd,bhkd->bhqk", query, key,
                   preferred_element_type=acc) * scale
    if mask is not None:
        s = s + _mask_bias(mask, acc)
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        if window is not None:
            cm = jnp.logical_and(
                cm, jnp.triu(jnp.ones((lq, lk), bool),
                             k=lk - lq - int(window) + 1))
        s = jnp.where(cm, s, jnp.full((), _NEG, acc))
    p = jax.nn.softmax(s, axis=-1).astype(query.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, value,
                      preferred_element_type=acc).astype(query.dtype)


# ---------------------------------------------------------------------------
# Reference-layout interleaved ops. Layout contract (from the reference op
# docs): self-attention input is the fused QKV projection output with shape
# (seq, batch, heads*3*head_dim), interleaved per head as [q, k, v]; the
# qk output is (batch*heads, seq, seq) with q pre-scaled by 1/sqrt(head_dim).
# ---------------------------------------------------------------------------

def _split_selfatt(qkv, heads):
    L, B, C3 = qkv.shape
    d = C3 // (3 * heads)
    x = qkv.reshape(L, B, heads, 3, d)
    # -> (B, heads, L, d)
    q = jnp.transpose(x[:, :, :, 0, :], (1, 2, 0, 3))
    k = jnp.transpose(x[:, :, :, 1, :], (1, 2, 0, 3))
    v = jnp.transpose(x[:, :, :, 2, :], (1, 2, 0, 3))
    return q, k, v, d


@register_op(aliases=("_contrib_interleaved_matmul_selfatt_qk",))
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1, **_):
    q, k, _, d = _split_selfatt(queries_keys_values, heads)
    s = jnp.einsum("bhqd,bhkd->bhqk", q * (d ** -0.5), k,
                   preferred_element_type=jnp.float32)
    B, H, L, _ = q.shape
    return s.astype(queries_keys_values.dtype).reshape(B * H, L, L)


@register_op(aliases=("_contrib_interleaved_matmul_selfatt_valatt",))
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1, **_):
    _, _, v, d = _split_selfatt(queries_keys_values, heads)
    B, H, L, _ = v.shape
    att = attention.reshape(B, H, L, L)
    out = jnp.einsum("bhqk,bhkd->bhqd", att, v,
                     preferred_element_type=jnp.float32)
    # -> (L, B, H*d)
    return jnp.transpose(out, (2, 0, 1, 3)).reshape(L, B, H * d).astype(
        queries_keys_values.dtype)


def _split_kv(kv, heads):
    L, B, C2 = kv.shape
    d = C2 // (2 * heads)
    x = kv.reshape(L, B, heads, 2, d)
    k = jnp.transpose(x[:, :, :, 0, :], (1, 2, 0, 3))
    v = jnp.transpose(x[:, :, :, 1, :], (1, 2, 0, 3))
    return k, v, d


@register_op(aliases=("_contrib_interleaved_matmul_encdec_qk",))
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1, **_):
    Lq, B, C = queries.shape
    d = C // heads
    q = jnp.transpose(queries.reshape(Lq, B, heads, d), (1, 2, 0, 3))
    k, _, _ = _split_kv(keys_values, heads)
    s = jnp.einsum("bhqd,bhkd->bhqk", q * (d ** -0.5), k,
                   preferred_element_type=jnp.float32)
    Lk = k.shape[2]
    return s.astype(queries.dtype).reshape(B * heads, Lq, Lk)


@register_op(aliases=("_contrib_interleaved_matmul_encdec_valatt",))
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1, **_):
    k, v, d = _split_kv(keys_values, heads)
    B, H, Lk, _ = v.shape
    Lq = attention.shape[1]
    att = attention.reshape(B, H, Lq, Lk)
    out = jnp.einsum("bhqk,bhkd->bhqd", att, v,
                     preferred_element_type=jnp.float32)
    return jnp.transpose(out, (2, 0, 1, 3)).reshape(Lq, B, H * d).astype(
        keys_values.dtype)

"""Pallas TPU kernels — the counterpart of the reference's hand-written CUDA
fast paths (``src/operator/contrib/*.cu``, ``src/operator/fusion/``).

Only ops where XLA's automatic fusion leaves profit on the table get a kernel
here (flash attention first); everything else stays jax.numpy/lax and lets
XLA tile onto the MXU.
"""
from . import flash_attention  # noqa: F401

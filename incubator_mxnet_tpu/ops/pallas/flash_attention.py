"""Blockwise (flash) attention as a Pallas TPU kernel, with custom VJP.

Reference counterpart: the fused interleaved-MHA contrib ops
(``src/operator/contrib/transformer.cu``) — which still materialize the
(B·H, L, L) score matrix in HBM. This kernel never does: scores live one
(BQ, BK) tile at a time in VMEM with the online-softmax recurrence, so memory
is O(L·D) instead of O(L²) (SURVEY §5.7 calls this the required
capability-parity-plus deliverable).

Layout: inputs are (B, H, L, D); internally flattened to (B·H, L, D) with the
grid over (batch·head, query-block). K/V for one (b, h) are resident in VMEM
and walked in BK tiles by a ``fori_loop`` — fine up to L ≈ 4k (L·D·2 arrays);
longer sequences go through ring attention over the ``sp`` mesh axis
(``parallel/ring.py``), which calls back into this kernel per shard.

Masking: ``causal`` and/or a key-padding mask of shape (B, Lk) (1 = valid).
The generic (B, H, Lq, Lk) mask case falls back to the XLA path in
``ops/attention.py`` — loading an L² mask would defeat the point.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = ["flash_attention", "flash_supported"]

_NEG = -1e30
_MAX_VMEM_L = 4096


def _platform_of(x) -> Optional[str]:
    """Platform of a concrete jax.Array, or None for tracers."""
    try:
        devs = x.devices()
        return next(iter(devs)).platform
    except Exception:
        return None


def _interpret_for(x) -> bool:
    """Run the kernel in interpreter mode? Concrete arrays: wherever they
    live; tracers: the backend this trace is being compiled for (best
    available signal: the process default backend)."""
    p = _platform_of(x)
    return (jax.default_backend() if p is None else p) != "tpu"


def flash_supported(q, k, v, mask=None) -> bool:
    """Shape/backend gate used by dot_product_attention(impl='auto')."""
    if _interpret_for(q):
        return False
    if q.ndim != 4 or k.shape != v.shape:
        return False
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    if D % 8 or D > 256:
        return False
    if Lq % _bq(Lq) or Lk % _bk(Lk) or Lk > _MAX_VMEM_L:
        return False
    if mask is not None and _as_key_mask(mask, B, H, Lq, Lk) is None:
        return False
    return True


def _bq(lq: int) -> int:
    return min(128, lq)


def _bk(lk: int) -> int:
    return min(128, lk)


def _as_key_mask(mask, B, H, Lq, Lk):
    """Reduce a broadcastable mask to (B, Lk) key-padding form, else None."""
    if mask is None:
        return None
    if mask.ndim == 2 and mask.shape == (B, Lk):
        return mask
    if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1 \
            and mask.shape[0] in (1, B) and mask.shape[3] == Lk:
        m = mask[:, 0, 0, :]
        return jnp.broadcast_to(m, (B, Lk))
    return None


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *,
                scale, causal, bk, n_heads, causal_off=0):
    bq, d = q_ref.shape[1], q_ref.shape[2]
    lk = k_ref.shape[1]
    nk = lk // bk
    iq = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if mask_ref is not None:
            mb = mask_ref[0, 0, pl.ds(j * bk, bk)]
            s = jnp.where(mb[None, :].astype(bool), s, _NEG)
        if causal:
            # bottom-right aligned (tril k = Lk-Lq), matching the XLA path
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + j * bk
            s = jnp.where(cols <= rows + causal_off, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return acc, m_new, l

    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)  # fully-masked rows: output 0, lse finite
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l))[:, 0]


def _fwd(q, k, v, key_mask, causal, scale):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq, bk = _bq(Lq), _bk(Lk)
    BH = B * H
    q3 = q.reshape(BH, Lq, D)
    k3 = k.reshape(BH, Lk, D)
    v3 = v.reshape(BH, Lk, D)
    grid = (BH, Lq // bq)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0), memory_space=_VMEM),
        pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0), memory_space=_VMEM),
        pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0), memory_space=_VMEM),
    ]
    args = [q3, k3, v3]
    if key_mask is not None:
        # (B, 1, Lk): TPU block shapes need the trailing two dims to be
        # tile-divisible or whole, so the mask rides with a singleton row.
        in_specs.append(pl.BlockSpec(
            (1, 1, Lk), lambda b, i: (b // H, 0, 0), memory_space=_VMEM))
        args.append(key_mask.astype(jnp.int32).reshape(key_mask.shape[0], 1, Lk))
    kern = functools.partial(
        _fwd_kernel if key_mask is not None else _fwd_kernel_nomask,
        scale=scale, causal=causal, bk=bk, n_heads=H, causal_off=Lk - Lq)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0), memory_space=_VMEM),
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i), memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, Lq), jnp.float32),
        ],
        interpret=_interpret_for(q3),
    )(*args)
    return o.reshape(B, H, Lq, D), lse.reshape(B, H, Lq)


def _fwd_kernel_nomask(q_ref, k_ref, v_ref, o_ref, lse_ref, **kw):
    _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref, **kw)


# ---------------------------------------------------------------------------
# backward: dkv kernel (grid over key blocks) + dq kernel (grid over q blocks)
# delta = rowsum(do * o) precomputed with plain jnp.
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
                    dk_ref, dv_ref, *, scale, causal, bq, n_heads,
                    causal_off=0):
    bk, d = k_ref.shape[1], k_ref.shape[2]
    lq = q_ref.shape[1]
    nq = lq // bq
    jk = pl.program_id(1)

    kb = k_ref[0].astype(jnp.float32)
    vb = v_ref[0].astype(jnp.float32)
    if mask_ref is not None:
        mb = mask_ref[0, 0].astype(bool)  # (bk,)

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        dob = do_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        lseb = lse_ref[0, 0, pl.ds(i * bq, bq)]
        deltab = delta_ref[0, 0, pl.ds(i * bq, bq)]
        s = jax.lax.dot_general(qb * scale, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if mask_ref is not None:
            s = jnp.where(mb[None, :], s, _NEG)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + i * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + jk * bk
            s = jnp.where(cols <= rows + causal_off, s, _NEG)
        p = jnp.exp(s - lseb[:, None])
        dv = dv + jax.lax.dot_general(p, dob, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltab[:, None]) * scale
        dk = dk + jax.lax.dot_general(ds, qb, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dkv_kernel_nomask(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, **kw):
    _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, None,
                    dk_ref, dv_ref, **kw)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
                   dq_ref, *, scale, causal, bk, n_heads, causal_off=0):
    bq, d = q_ref.shape[1], q_ref.shape[2]
    lk = k_ref.shape[1]
    nk = lk // bk
    iq = pl.program_id(1)

    qb = q_ref[0].astype(jnp.float32)
    dob = do_ref[0].astype(jnp.float32)
    lseb = lse_ref[0, 0]
    deltab = delta_ref[0, 0]

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(qb * scale, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if mask_ref is not None:
            mb = mask_ref[0, 0, pl.ds(j * bk, bk)]
            s = jnp.where(mb[None, :].astype(bool), s, _NEG)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + j * bk
            s = jnp.where(cols <= rows + causal_off, s, _NEG)
        p = jnp.exp(s - lseb[:, None])
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltab[:, None]) * scale
        return dq + jax.lax.dot_general(ds, kb, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dq_kernel_nomask(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dq_ref, **kw):
    _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, None,
                   dq_ref, **kw)


def _bwd(q, k, v, key_mask, causal, scale, o, lse, do):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq, bk = _bq(Lq), _bk(Lk)
    BH = B * H
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    q3, k3, v3 = (x.reshape(BH, -1, D) for x in (q, k, v))
    do3 = do.reshape(BH, Lq, D)
    lse3 = lse.reshape(BH, 1, Lq)
    delta3 = delta.reshape(BH, 1, Lq)

    common = [
        pl.BlockSpec((1, Lq, D), lambda b, j: (b, 0, 0), memory_space=_VMEM),
        pl.BlockSpec((1, Lk, D), lambda b, j: (b, 0, 0), memory_space=_VMEM),
        pl.BlockSpec((1, Lk, D), lambda b, j: (b, 0, 0), memory_space=_VMEM),
        pl.BlockSpec((1, Lq, D), lambda b, j: (b, 0, 0), memory_space=_VMEM),
        pl.BlockSpec((1, 1, Lq), lambda b, j: (b, 0, 0), memory_space=_VMEM),
        pl.BlockSpec((1, 1, Lq), lambda b, j: (b, 0, 0), memory_space=_VMEM),
    ]
    args = [q3, k3, v3, do3, lse3, delta3]
    mask_spec = []
    if key_mask is not None:
        mask_spec = [pl.BlockSpec((1, 1, Lk), lambda b, j: (b // H, 0, 0),
                                  memory_space=_VMEM)]
        args = args + [key_mask.astype(jnp.int32).reshape(-1, 1, Lk)]

    dkv_specs = [
        common[0],
        pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0), memory_space=_VMEM),
        pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0), memory_space=_VMEM),
    ] + common[3:] + ([pl.BlockSpec((1, 1, bk), lambda b, j: (b // H, 0, j),
                                    memory_space=_VMEM)] if key_mask is not None else [])
    dkv_kern = functools.partial(
        _bwd_dkv_kernel if key_mask is not None else _bwd_dkv_kernel_nomask,
        scale=scale, causal=causal, bq=bq, n_heads=H, causal_off=Lk - Lq)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(BH, Lk // bk),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0), memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0), memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Lk, D), v.dtype),
        ],
        interpret=_interpret_for(q3),
    )(*args)

    dq_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0), memory_space=_VMEM),
        common[1], common[2],
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0), memory_space=_VMEM),
        pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i), memory_space=_VMEM),
        pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i), memory_space=_VMEM),
    ] + mask_spec
    dq_kern = functools.partial(
        _bwd_dq_kernel if key_mask is not None else _bwd_dq_kernel_nomask,
        scale=scale, causal=causal, bk=bk, n_heads=H, causal_off=Lk - Lq)
    dq = pl.pallas_call(
        dq_kern,
        grid=(BH, Lq // bq),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
        interpret=_interpret_for(q3),
    )(*args)
    return (dq.reshape(B, H, Lq, D), dk.reshape(B, H, Lk, D),
            dv.reshape(B, H, Lk, D))


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, key_mask, causal, scale):
    o, _ = _fwd(q, k, v, key_mask, causal, scale)
    return o


def _flash_fwd(q, k, v, key_mask, causal, scale):
    o, lse = _fwd(q, k, v, key_mask, causal, scale)
    return o, (q, k, v, key_mask, o, lse)


def _flash_bwd(causal, scale, res, do):
    q, k, v, key_mask, o, lse = res
    dq, dk, dv = _bwd(q, k, v, key_mask, causal, scale, o, lse, do)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, mask=None, causal: bool = False,
                    scale: Optional[float] = None):
    """Blockwise attention, O(L·D) memory. See module docstring for the
    supported mask forms; unsupported ones should be routed to the XLA path
    by the caller (dot_product_attention does this via flash_supported)."""
    scale = (q.shape[-1] ** -0.5) if scale is None else float(scale)
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    if Lq % _bq(Lq) or Lk % _bk(Lk):
        raise ValueError(
            f"flash_attention needs Lq/Lk divisible by the block size "
            f"({_bq(Lq)}/{_bk(Lk)}); got Lq={Lq}, Lk={Lk} — pad the "
            "sequence or use the XLA path (dot_product_attention impl='xla')")
    key_mask = _as_key_mask(mask, B, H, Lq, Lk)
    if mask is not None and key_mask is None:
        raise ValueError("flash_attention supports key-padding masks "
                         "(B, Lk) / (B,1,1,Lk); use the XLA path otherwise")
    return _flash(q, k, v, key_mask, causal, scale)

"""Blockwise (flash) attention as a Pallas TPU kernel, with custom VJP.

Reference counterpart: the fused interleaved-MHA contrib ops
(``src/operator/contrib/transformer.cu``) — which still materialize the
(B·H, L, L) score matrix in HBM. This kernel never does: scores live one
(BQ, BK) tile at a time in VMEM with the online-softmax recurrence, so memory
is O(L·D) instead of O(L²) (SURVEY §5.7 calls this the required
capability-parity-plus deliverable).

TPU mapping (the parts that set the MFU):

- All matmuls run on the MXU in the *input* dtype (bf16 in training) with
  fp32 accumulation (``preferred_element_type``); probabilities are cast
  back to bf16 before the PV dot. fp32 operands would run the MXU at a
  fraction of peak.
- K/V are **streamed from HBM one (BK, D) block per grid step** — the grid's
  innermost "arbitrary" dimension — with softmax state (m, l, acc) carried
  in VMEM scratch across steps. Pallas double-buffers the HBM→VMEM copies
  automatically, so there is no whole-sequence VMEM residency and no cap on
  L (the old design held all of K/V per (b,h) in VMEM and capped L at 4k).
- ``dimension_semantics``: (batch·head, q-block) grid dims are "parallel";
  the k-block dim is "arbitrary" (carries the softmax recurrence).
- Fully-masked causal tiles are skipped with ``pl.when`` (≈2× on causal).

Longer-than-memory sequences go through ring attention over the ``sp`` mesh
axis (``parallel/ring.py``), which calls back into this kernel's ``_fwd``
per K/V hop and merges the per-hop (o, lse) pairs; ``dot_product_attention``
routes there automatically when the active mesh has sp>1.

Masking: ``causal`` and/or a key-padding mask of shape (B, Lk) (1 = valid).
The generic (B, H, Lq, Lk) mask case falls back to the XLA path in
``ops/attention.py`` — loading an L² mask would defeat the point.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = ["flash_attention", "flash_supported"]

_NEG = -1e30


def _platform_of(x) -> Optional[str]:
    """Platform of a concrete jax.Array, or None for tracers."""
    try:
        devs = x.devices()
        return next(iter(devs)).platform
    except Exception:
        return None


def _interpret_for(x) -> bool:
    """Run the kernel in interpreter mode? Concrete arrays: wherever they
    live; tracers: the backend this trace is being compiled for (best
    available signal: the process default backend)."""
    p = _platform_of(x)
    return (jax.default_backend() if p is None else p) != "tpu"


def flash_supported(q, k, v, mask=None) -> bool:
    """Shape/backend gate used by dot_product_attention(impl='auto')."""
    if os.environ.get("MXTPU_FLASH_ATTENTION", "1") == "0":
        return False
    if _interpret_for(q):
        return False
    if q.ndim != 4 or k.shape != v.shape:
        return False
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    if D % 8 or D > 256:
        return False
    if Lq % _bq(Lq) or Lk % _bk(Lk):
        return False
    if mask is not None and _as_key_mask(mask, B, H, Lq, Lk) is None:
        return False
    return True


def _auto_block(length: int) -> int:
    """Default tile rows for one grid dimension: 512 or 256 when they divide
    ``length``, else one whole block for sublane-aligned (length % 8 == 0)
    short sequences (unaligned ones only with MXTPU_FLASH_UNALIGNED=1),
    else 512 (which won't divide — the caller then routes to the XLA path
    via ``flash_supported``).

    Measured on v5e (BERT-base, L=512, D=64): (BQ, BK)=(512, 512) runs the
    step at 40.9ms vs 45.5ms for (256, 512) and a pathological 1066ms for
    (128, 512) — bigger tiles amortize the grid/recurrence overhead and keep
    the MXU busier, and VMEM comfortably holds a 512-row block up to D=256.
    Tiles below 256 rows are never chosen automatically (the 128-row config
    is the measured-pathological regime; env overrides remain available).
    """
    for cand in (512, 256):
        if cand <= length and length % cand == 0:
            return cand
    if length <= 1024 and (
            length % 8 == 0
            or os.environ.get("MXTPU_FLASH_UNALIGNED", "0") == "1"):
        # One whole block; VMEM holds it up to D=256. Sublane-unaligned
        # (length % 8 != 0) block shapes are where Mosaic lowering failures
        # and perf cliffs live, so they stay env-gated until a hardware run
        # validates them (MXTPU_FLASH_UNALIGNED=1).
        return length
    return 512  # not handled: caller falls back to XLA via flash_supported


def _bq(lq: int) -> int:
    env = os.environ.get("MXTPU_FLASH_BQ")
    if env:
        return min(int(env), lq)
    return _auto_block(lq)


def _bk(lk: int) -> int:
    env = os.environ.get("MXTPU_FLASH_BK")
    if env:
        return min(int(env), lk)
    return _auto_block(lk)


def _dimsem(n: int = 2):
    """(parallel, ..., arbitrary) compiler hints; None off-TPU."""
    if pltpu is None:
        return None
    return dict(dimension_semantics=("parallel",) * n + ("arbitrary",))


def _as_key_mask(mask, B, H, Lq, Lk):
    """Reduce a broadcastable mask to (B, Lk) key-padding form, else None."""
    if mask is None:
        return None
    if mask.ndim == 2 and mask.shape == (B, Lk):
        return mask
    if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1 \
            and mask.shape[0] in (1, B) and mask.shape[3] == Lk:
        m = mask[:, 0, 0, :]
        return jnp.broadcast_to(m, (B, Lk))
    return None


def _causal_live(iq, jk, bq, bk, causal_off, window=None):
    """Does q-block iq intersect any unmasked position of k-block jk?
    (bottom-right aligned causal: col <= row + causal_off; with a sliding
    window additionally col > row + causal_off - window). Dead tiles are
    skipped entirely — a window turns the O(L²) tile grid into O(L·W)."""
    first_row = iq * bq
    first_col = jk * bk
    live = first_col <= first_row + (bq - 1) + causal_off
    if window is not None:
        last_col = first_col + bk - 1
        live = jnp.logical_and(
            live, last_col > first_row + causal_off - window)
    return live


def _band(rows, cols, causal_off, window):
    """The in-tile visibility mask for causal (+ optional window)."""
    live = cols <= rows + causal_off
    if window is not None:
        live = jnp.logical_and(live, cols > rows + causal_off - window)
    return live


# ---------------------------------------------------------------------------
# forward: grid (B·H, nq, nk) — K/V streamed block-by-block, state in scratch
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, causal_off,
                window=None):
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _step():
        q = q_ref[0]                       # input dtype (bf16 in training)
        kb = k_ref[0]
        # MXU dot in input dtype, fp32 accumulate; scale applied in fp32
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if mask_ref is not None:
            mb = mask_ref[0, 0]
            s = jnp.where(mb[None, :].astype(bool), s, _NEG)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + jk * bk
            s = jnp.where(_band(rows, cols, causal_off, window), s, _NEG)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    if causal:  # skip tiles fully outside the (banded) diagonal
        pl.when(_causal_live(iq, jk, bq, bk, causal_off, window))(_step)
    else:
        _step()

    @pl.when(jk == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[...], 1e-30)  # fully-masked rows → output 0
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_ref[...] + jnp.log(l))[:, 0]


def _fwd_kernel_nomask(q_ref, k_ref, v_ref, o_ref, lse_ref,
                       acc_ref, m_ref, l_ref, **kw):
    _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, **kw)


def _scratch(bq, d):
    if pltpu is None:
        return None
    return [pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32)]


def _fwd(q, k, v, key_mask, causal, scale, window=None):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq, bk = _bq(Lq), _bk(Lk)
    BH = B * H
    q3 = q.reshape(BH, Lq, D)
    k3 = k.reshape(BH, Lk, D)
    v3 = v.reshape(BH, Lk, D)
    grid = (BH, Lq // bq, Lk // bk)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0), memory_space=_VMEM),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0), memory_space=_VMEM),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0), memory_space=_VMEM),
    ]
    args = [q3, k3, v3]
    if key_mask is not None:
        # (B, 1, Lk): TPU block shapes need the trailing two dims to be
        # tile-divisible or whole, so the mask rides with a singleton row.
        in_specs.append(pl.BlockSpec(
            (1, 1, bk), lambda b, i, j: (b // H, 0, j), memory_space=_VMEM))
        args.append(key_mask.astype(jnp.int32).reshape(key_mask.shape[0], 1, Lk))
    kern = functools.partial(
        _fwd_kernel if key_mask is not None else _fwd_kernel_nomask,
        scale=scale, causal=causal, causal_off=Lk - Lq, window=window)
    interpret = _interpret_for(q3)
    kwargs = {}
    if not interpret and pltpu is not None:
        kwargs["compiler_params"] = pltpu.CompilerParams(**_dimsem(2))
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, Lq), jnp.float32),
        ],
        scratch_shapes=_scratch(bq, D),
        interpret=interpret,
        **kwargs,
    )(*args)
    return o.reshape(B, H, Lq, D), lse.reshape(B, H, Lq)


# ---------------------------------------------------------------------------
# backward: dkv kernel (grid B·H, nk, nq) + dq kernel (grid B·H, nq, nk);
# delta = rowsum(do * o) precomputed with plain jnp.
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    causal_off, window=None):
    bk, d = k_ref.shape[1], k_ref.shape[2]
    bq = q_ref.shape[1]
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _step():
        kb = k_ref[0]
        vb = v_ref[0]
        qb = q_ref[0]
        dob = do_ref[0]
        lseb = lse_ref[0, 0]
        deltab = delta_ref[0, 0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if mask_ref is not None:
            s = jnp.where(mask_ref[0, 0].astype(bool)[None, :], s, _NEG)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + jk * bk
            s = jnp.where(_band(rows, cols, causal_off, window), s, _NEG)
        # masked entries: exp(s - lse) can overflow for fully-masked rows
        # (lse floors at m + log eps); they carry no gradient — zero them.
        p = jnp.where(s > _NEG * 0.5, jnp.exp(s - lseb[:, None]), 0.0)
        pb = p.astype(dob.dtype)
        dv_acc[...] += jax.lax.dot_general(
            pb, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - deltab[:, None]) * scale).astype(qb.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_causal_live(iq, jk, bq, bk, causal_off, window))(_step)
    else:
        _step()

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dkv_kernel_nomask(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc, **kw):
    _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, None,
                    dk_ref, dv_ref, dk_acc, dv_acc, **kw)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
                   dq_ref, dq_acc, *, scale, causal, causal_off, window=None):
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _step():
        qb = q_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        dob = do_ref[0]
        lseb = lse_ref[0, 0]
        deltab = delta_ref[0, 0]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if mask_ref is not None:
            s = jnp.where(mask_ref[0, 0].astype(bool)[None, :], s, _NEG)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + jk * bk
            s = jnp.where(_band(rows, cols, causal_off, window), s, _NEG)
        p = jnp.where(s > _NEG * 0.5, jnp.exp(s - lseb[:, None]), 0.0)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - deltab[:, None]) * scale).astype(kb.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_causal_live(iq, jk, bq, bk, causal_off, window))(_step)
    else:
        _step()

    @pl.when(jk == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dq_kernel_nomask(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dq_ref, dq_acc, **kw):
    _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, None,
                   dq_ref, dq_acc, **kw)


def _bwd(q, k, v, key_mask, causal, scale, o, lse, do, dlse=None,
         window=None):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    bq, bk = _bq(Lq), _bk(Lk)
    BH = B * H
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        # The lse output's cotangent enters the score gradient as
        # ds += p * dlse — algebraically a shift of delta, so the same
        # backward kernels serve the (o, lse) block-attention entry used by
        # ring attention.
        delta = delta - dlse.astype(jnp.float32)
    q3, k3, v3 = (x.reshape(BH, -1, D) for x in (q, k, v))
    do3 = do.reshape(BH, Lq, D)
    lse3 = lse.reshape(BH, 1, Lq)
    delta3 = delta.reshape(BH, 1, Lq)
    interpret = _interpret_for(q3)
    kwargs = {}
    if not interpret and pltpu is not None:
        kwargs["compiler_params"] = pltpu.CompilerParams(**_dimsem(2))

    # ---- dk/dv: fixed k-block (parallel), stream q-blocks (arbitrary)
    dkv_specs = [
        pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0), memory_space=_VMEM),
        pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0), memory_space=_VMEM),
        pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0), memory_space=_VMEM),
        pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0), memory_space=_VMEM),
        pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i), memory_space=_VMEM),
        pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i), memory_space=_VMEM),
    ]
    args = [q3, k3, v3, do3, lse3, delta3]
    if key_mask is not None:
        dkv_specs.append(pl.BlockSpec((1, 1, bk),
                                      lambda b, j, i: (b // H, 0, j),
                                      memory_space=_VMEM))
        args = args + [key_mask.astype(jnp.int32).reshape(-1, 1, Lk)]
    dkv_kern = functools.partial(
        _bwd_dkv_kernel if key_mask is not None else _bwd_dkv_kernel_nomask,
        scale=scale, causal=causal, causal_off=Lk - Lq, window=window)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(BH, Lk // bk, Lq // bq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Lk, D), v.dtype),
        ],
        scratch_shapes=([pltpu.VMEM((bk, D), jnp.float32),
                         pltpu.VMEM((bk, D), jnp.float32)]
                        if pltpu is not None else None),
        interpret=interpret,
        **kwargs,
    )(*args)

    # ---- dq: fixed q-block (parallel), stream k-blocks (arbitrary)
    dq_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0), memory_space=_VMEM),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0), memory_space=_VMEM),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0), memory_space=_VMEM),
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0), memory_space=_VMEM),
        pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i), memory_space=_VMEM),
        pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i), memory_space=_VMEM),
    ]
    if key_mask is not None:
        dq_specs.append(pl.BlockSpec((1, 1, bk),
                                     lambda b, i, j: (b // H, 0, j),
                                     memory_space=_VMEM))
    dq_kern = functools.partial(
        _bwd_dq_kernel if key_mask is not None else _bwd_dq_kernel_nomask,
        scale=scale, causal=causal, causal_off=Lk - Lq, window=window)
    dq = pl.pallas_call(
        dq_kern,
        grid=(BH, Lq // bq, Lk // bk),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
        scratch_shapes=([pltpu.VMEM((bq, D), jnp.float32)]
                        if pltpu is not None else None),
        interpret=interpret,
        **kwargs,
    )(*args)
    return (dq.reshape(B, H, Lq, D), dk.reshape(B, H, Lk, D),
            dv.reshape(B, H, Lk, D))


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, key_mask, causal, scale, window=None):
    o, _ = _fwd(q, k, v, key_mask, causal, scale, window)
    return o


def _flash_fwd(q, k, v, key_mask, causal, scale, window=None):
    o, lse = _fwd(q, k, v, key_mask, causal, scale, window)
    return o, (q, k, v, key_mask, o, lse)


def _flash_bwd(causal, scale, window, res, do):
    q, k, v, key_mask, o, lse = res
    dq, dk, dv = _bwd(q, k, v, key_mask, causal, scale, o, lse, do,
                      window=window)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# block-attention entry for ring attention: returns (o, lse), differentiable
# in both outputs (the lse cotangent folds into delta — see _bwd).
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_block(q, k, v, key_mask, causal, scale):
    """One K/V block's attention returning ``(o, lse)`` — the unit ring
    attention merges per hop. Same mask/shape contract as flash_attention."""
    return _fwd(q, k, v, key_mask, causal, scale)


def _flash_block_fwd(q, k, v, key_mask, causal, scale):
    o, lse = _fwd(q, k, v, key_mask, causal, scale)
    return (o, lse), (q, k, v, key_mask, o, lse)


def _flash_block_bwd(causal, scale, res, cts):
    do, dlse = cts
    q, k, v, key_mask, o, lse = res
    dq, dk, dv = _bwd(q, k, v, key_mask, causal, scale, o, lse,
                      do.astype(q.dtype), dlse)
    return dq, dk, dv, None


flash_block.defvjp(_flash_block_fwd, _flash_block_bwd)


def flash_attention(q, k, v, mask=None, causal: bool = False,
                    scale: Optional[float] = None,
                    window: Optional[int] = None):
    """Blockwise attention, O(L·D) memory. See module docstring for the
    supported mask forms; unsupported ones should be routed to the XLA path
    by the caller (dot_product_attention does this via flash_supported).

    ``window`` (requires ``causal=True``): causal sliding-window attention —
    position i attends to the ``window`` most recent keys only. Tiles fully
    outside the band are skipped, so compute is O(L·window) not O(L²): the
    Mistral-style long-context recipe, native to the tile grid."""
    scale = (q.shape[-1] ** -0.5) if scale is None else float(scale)
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    if window is not None:
        window = int(window)
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        if not causal:
            raise ValueError("window= requires causal=True (the sliding "
                             "window is defined over the causal band)")
    if Lq % _bq(Lq) or Lk % _bk(Lk):
        raise ValueError(
            f"flash_attention needs Lq/Lk divisible by the block size "
            f"({_bq(Lq)}/{_bk(Lk)}); got Lq={Lq}, Lk={Lk} — pad the "
            "sequence or use the XLA path (dot_product_attention impl='xla')")
    key_mask = _as_key_mask(mask, B, H, Lq, Lk)
    if mask is not None and key_mask is None:
        raise ValueError("flash_attention supports key-padding masks "
                         "(B, Lk) / (B,1,1,Lk); use the XLA path otherwise")
    return _flash(q, k, v, key_mask, causal, scale, window)

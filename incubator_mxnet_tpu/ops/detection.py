"""Detection ops — box_nms, MultiBox*, ROIAlign/ROIPooling, box_iou.

Reference parity: ``src/operator/contrib/bounding_box.cc`` (``box_nms``,
``box_iou``, ``bipartite_matching``), ``src/operator/contrib/multibox_*.cc``
(SSD's MultiBoxPrior/Target/Detection) and ``src/operator/contrib/
roi_align.cc`` / ``src/operator/roi_pooling.cc`` — SURVEY §2.4's "padded
top-k NMS" fixed-shape rewrite requirement.

TPU-native design: every op is fixed-shape. NMS keeps all N slots and marks
suppressed entries with -1 (exactly the reference's output convention, which
happens to be TPU-friendly already); the suppression loop is a
``lax.fori_loop`` over a precomputed (N, N) IoU matrix, compiling to one
fused kernel instead of the reference's sort + sequential CUDA kernel chain.
ROIAlign gathers bilinear samples with static sampling grids.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

__all__ = ["box_iou", "box_nms", "bipartite_matching", "multibox_prior",
           "multibox_target", "multibox_detection", "roi_align", "roi_pooling"]


def _corner_iou(a, b):
    """IoU between corner-format boxes a (..., N, 4) and b (..., M, 4)."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)       # (..., N, 1)
    bx1, by1, bx2, by2 = (x.squeeze(-1) for x in jnp.split(b, 4, axis=-1))
    ix1 = jnp.maximum(ax1, bx1[..., None, :])           # (..., N, M)
    iy1 = jnp.maximum(ay1, by1[..., None, :])
    ix2 = jnp.minimum(ax2, bx2[..., None, :])
    iy2 = jnp.minimum(ay2, by2[..., None, :])
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(ax2 - ax1, 0) * jnp.clip(ay2 - ay1, 0)
    area_b = jnp.clip(bx2 - bx1, 0) * jnp.clip(by2 - by1, 0)
    union = area_a + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_to_corner(b):
    x, y, w, h = jnp.split(b, 4, axis=-1)
    return jnp.concatenate([x - w / 2, y - h / 2, x + w / 2, y + h / 2], -1)


def _corner_to_center(b):
    x1, y1, x2, y2 = jnp.split(b, 4, axis=-1)
    return jnp.concatenate([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], -1)


@register_op(aliases=("_contrib_box_iou",))
def box_iou(lhs, rhs, format="corner", **_):
    if format == "center":
        lhs, rhs = _center_to_corner(lhs), _center_to_corner(rhs)
    return _corner_iou(lhs, rhs)


@register_op(aliases=("_contrib_box_nms",))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            background_id=-1, force_suppress=False, in_format="corner",
            out_format="corner", **_):
    """Fixed-shape NMS. data (..., N, K) with K >= coord_start+4; output has
    identical shape with suppressed/invalid rows set to -1 and survivors
    sorted by score (reference output convention)."""
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    *batch, N, K = data.shape
    flat = data.reshape((-1, N, K))

    def one(sample):
        scores = sample[:, score_index]
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= sample[:, id_index] != background_id
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        s = sample[order]
        svalid = valid[order]
        if topk > 0:
            svalid &= jnp.arange(N) < topk
        boxes = s[:, coord_start:coord_start + 4]
        if in_format == "center":
            boxes = _center_to_corner(boxes)
        iou = _corner_iou(boxes, boxes)
        if not force_suppress and id_index >= 0:
            same = s[:, id_index][:, None] == s[:, id_index][None, :]
            iou = jnp.where(same, iou, 0.0)

        def body(i, keep):
            ki = keep[i] & svalid[i]
            sup = (iou[i] > overlap_thresh) & (jnp.arange(N) > i) & ki
            return keep & ~sup

        keep = lax.fori_loop(0, N, body, jnp.ones(N, bool)) & svalid
        if out_format != in_format:
            coords = s[:, coord_start:coord_start + 4]
            conv = (_center_to_corner(coords) if out_format == "corner"
                    else _corner_to_center(coords))
            s = s.at[:, coord_start:coord_start + 4].set(conv)
        out = jnp.where(keep[:, None], s, -jnp.ones_like(s))
        return out

    out = jax.vmap(one)(flat).reshape(data.shape)
    return out[0] if squeeze else out


@register_op(aliases=("_contrib_bipartite_matching",))
def bipartite_matching(data, threshold=0.5, is_ascend=False, topk=-1, **_):
    """Greedy bipartite matching over a (..., N, M) score matrix
    (reference: bounding_box.cc BipartiteMatching). Returns (row_match,
    col_match): for each row the matched col (or -1), and inverse."""
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    B, N, M = data.shape
    sign = 1.0 if is_ascend else -1.0

    def one(mat):
        def body(_, carry):
            row_m, col_m, m = carry
            masked = jnp.where((row_m[:, None] < 0) & (col_m[None, :] < 0),
                               m, sign * jnp.inf)
            # best remaining pair: max score (descend) / min (ascend)
            idx = jnp.argmax(-sign * masked.reshape(-1))
            r, c = idx // M, idx % M
            # threshold the MASKED value: when rows/cols are exhausted the
            # argmax lands on an inf slot, which must never match
            val = masked[r, c]
            ok = (val > threshold) if not is_ascend else (val < threshold)
            row_m = jnp.where(ok, row_m.at[r].set(c), row_m)
            col_m = jnp.where(ok, col_m.at[c].set(r), col_m)
            return row_m, col_m, m

        k = N if topk <= 0 else min(topk, N)
        row0 = -jnp.ones(N, jnp.int32)
        col0 = -jnp.ones(M, jnp.int32)
        row_m, col_m, _ = lax.fori_loop(0, k, body, (row0, col0, mat))
        return row_m.astype(data.dtype), col_m.astype(data.dtype)

    rows, cols = jax.vmap(one)(data)
    if squeeze:
        return rows[0], cols[0]
    return rows, cols


@register_op(aliases=("_contrib_MultiBoxPrior", "MultiBoxPrior"))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5), **_):
    """SSD anchor generation (reference: multibox_prior.cc). data is the
    (B, C, H, W) feature map; returns (1, H*W*(S+R-1), 4) corner anchors."""
    H, W = data.shape[-2], data.shape[-1]
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H) + offsets[0]) * step_y
    cx = (jnp.arange(W) + offsets[1]) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([cx.reshape(-1), cy.reshape(-1)], -1)  # (HW, 2)
    # widths carry the reference's in_h/in_w aspect correction
    # (multibox_prior.cc) so anchors stay square in image space on
    # non-square feature maps.
    ar = H / W
    whs = []
    s0 = sizes[0]
    for s in sizes:
        whs.append((s * ar, s))
    for r in ratios[1:]:
        rr = float(r) ** 0.5
        whs.append((s0 * rr * ar, s0 / rr))
    whs = jnp.asarray(whs)                                       # (A, 2)
    A = whs.shape[0]
    c = jnp.repeat(centers[:, None, :], A, axis=1)               # (HW, A, 2)
    wh = jnp.broadcast_to(whs[None], (centers.shape[0], A, 2))
    boxes = jnp.concatenate([c - wh / 2, c + wh / 2], -1).reshape(1, -1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.astype(data.dtype)


@register_op(aliases=("_contrib_MultiBoxTarget", "MultiBoxTarget"))
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2), **_):
    """SSD training targets (reference: multibox_target.cc).
    anchor (1, N, 4) corner; label (B, M, 5) [cls, x1, y1, x2, y2] with -1
    padding; cls_pred (B, num_cls+1, N). Returns (loc_target (B, N*4),
    loc_mask (B, N*4), cls_target (B, N))."""
    anchors = anchor.reshape(-1, 4)
    N = anchors.shape[0]
    var = jnp.asarray(variances)

    def one(lab, pred):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _corner_iou(anchors, gt_boxes)              # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=1)                 # (N,)
        best_iou = jnp.max(iou, axis=1)
        # force-match: each VALID gt's best anchor is positive. at[].max so a
        # padding gt (argmax lands on anchor 0) can't overwrite a real match.
        best_anchor = jnp.argmax(iou, axis=0)             # (M,)
        forced = jnp.zeros(N, bool).at[best_anchor].max(gt_valid)
        pos = (best_iou >= overlap_threshold) | forced
        matched = gt_boxes[best_gt]                       # (N, 4)
        # encode regression target (center offsets / variances)
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.clip(matched[:, 2] - matched[:, 0], 1e-8)
        gh = jnp.clip(matched[:, 3] - matched[:, 1], 1e-8)
        gcx = (matched[:, 0] + matched[:, 2]) / 2
        gcy = (matched[:, 1] + matched[:, 3]) / 2
        tx = (gcx - acx) / jnp.clip(aw, 1e-8) / var[0]
        ty = (gcy - acy) / jnp.clip(ah, 1e-8) / var[1]
        tw = jnp.log(gw / jnp.clip(aw, 1e-8)) / var[2]
        th = jnp.log(gh / jnp.clip(ah, 1e-8)) / var[3]
        loc_t = jnp.stack([tx, ty, tw, th], -1)           # (N, 4)
        loc_mask = jnp.broadcast_to(pos[:, None], (N, 4)).astype(anchor.dtype)
        pos_cls = lab[best_gt, 0] + 1.0
        if negative_mining_ratio > 0:
            # hard negative mining (multibox_target.cc): keep the
            # ratio*num_pos hardest background anchors (largest background
            # CE under the current predictions); the rest get ignore_label.
            neg_loss = -jax.nn.log_softmax(pred, axis=0)[0]
            num_pos = jnp.sum(pos)
            max_neg = jnp.maximum(num_pos * negative_mining_ratio,
                                  float(minimum_negative_samples))
            cand = jnp.where(pos, -jnp.inf, neg_loss)
            order = jnp.argsort(-cand)
            rank = jnp.zeros(N, jnp.int32).at[order].set(
                jnp.arange(N, dtype=jnp.int32))
            sel_neg = (~pos) & (rank < max_neg)
            cls_t = jnp.where(pos, pos_cls,
                              jnp.where(sel_neg, 0.0, ignore_label))
        else:
            cls_t = jnp.where(pos, pos_cls, 0.0)
        return (loc_t * loc_mask).reshape(-1), loc_mask.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    return loc_t.astype(anchor.dtype), loc_m, cls_t.astype(anchor.dtype)


@register_op(aliases=("_contrib_MultiBoxDetection", "MultiBoxDetection"))
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1, **_):
    """SSD decode + NMS (reference: multibox_detection.cc).
    cls_prob (B, num_cls+1, N), loc_pred (B, N*4), anchor (1, N, 4).
    Returns (B, N, 6) [id, score, x1, y1, x2, y2], -1 for invalid."""
    B = cls_prob.shape[0]
    N = anchor.shape[1]
    var = jnp.asarray(variances)
    anchors = anchor.reshape(N, 4)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def one(prob, loc):
        loc = loc.reshape(N, 4)
        cx = loc[:, 0] * var[0] * aw + acx
        cy = loc[:, 1] * var[1] * ah + acy
        w = jnp.exp(loc[:, 2] * var[2]) * aw
        h = jnp.exp(loc[:, 3] * var[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor; output class ids are
        # 0-based over the non-background classes (reference convention)
        C = prob.shape[0]
        if 0 <= background_id < C:
            masked = prob.at[background_id].set(-jnp.inf)
            raw = jnp.argmax(masked, axis=0)
            cls = jnp.where(raw > background_id, raw - 1, raw)
            score = jnp.max(masked, axis=0)
        else:
            cls = jnp.argmax(prob, axis=0)
            score = jnp.max(prob, axis=0)
        det = jnp.concatenate([cls[:, None].astype(boxes.dtype),
                               score[:, None], boxes], -1)
        return box_nms(det, overlap_thresh=nms_threshold,
                       valid_thresh=threshold, topk=nms_topk,
                       force_suppress=force_suppress, coord_start=2,
                       score_index=1, id_index=0)

    return jax.vmap(one)(cls_prob, loc_pred)


@register_op(aliases=("_contrib_ROIAlign", "ROIAlign"))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, aligned=False, **_):
    """ROIAlign with bilinear sampling (reference: roi_align.cc).
    data (B, C, H, W); rois (R, 5) [batch_idx, x1, y1, x2, y2] in image
    coords. Returns (R, C, PH, PW)."""
    B, C, H, W = data.shape
    PH, PW = pooled_size
    if position_sensitive:
        if C % (PH * PW):
            raise ValueError(
                f"PS-ROIAlign needs channels divisible by PH*PW={PH * PW}, "
                f"got {C}")
    sr = max(1, int(sample_ratio))
    off = 0.5 if aligned else 0.0

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale - off, roi[2] * spatial_scale - off, \
            roi[3] * spatial_scale - off, roi[4] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w, bin_h = rw / PW, rh / PH
        # static (PH*sr, PW*sr) sampling grid
        gy = y1 + (jnp.repeat(jnp.arange(PH), sr)
                   + (jnp.tile(jnp.arange(sr), PH) + 0.5) / sr) * bin_h
        gx = x1 + (jnp.repeat(jnp.arange(PW), sr)
                   + (jnp.tile(jnp.arange(sr), PW) + 0.5) / sr) * bin_w
        img = data[bidx]                                  # (C, H, W)

        def bilinear(y, x):
            y0 = jnp.clip(jnp.floor(y), 0, H - 1)
            x0 = jnp.clip(jnp.floor(x), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(y - y0, 0, 1)
            wx = jnp.clip(x - x0, 0, 1)
            y0i, x0i, y1i, x1i = (v.astype(jnp.int32) for v in (y0, x0, y1_, x1_))
            v00 = img[:, y0i, x0i]
            v01 = img[:, y0i, x1i]
            v10 = img[:, y1i, x0i]
            v11 = img[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                    + v10 * wy * (1 - wx) + v11 * wy * wx)

        yy, xx = jnp.meshgrid(gy, gx, indexing="ij")      # (PH*sr, PW*sr)
        samples = jax.vmap(jax.vmap(bilinear))(yy, xx)    # (PH*sr, PW*sr, C)
        samples = samples.reshape(PH, sr, PW, sr, C)
        pooled = jnp.mean(samples, axis=(1, 3))           # (PH, PW, C)
        if not position_sensitive:
            return pooled.transpose(2, 0, 1)
        # PS-ROIAlign (reference: R-FCN / deformable PS-ROIPooling layout):
        # bin (ph, pw) of output channel o reads input channel
        # o*PH*PW + ph*PW + pw — each spatial bin has its own score map.
        Cout = C // (PH * PW)
        ps = pooled.reshape(PH, PW, Cout, PH * PW)
        bin_idx = (jnp.arange(PH)[:, None] * PW
                   + jnp.arange(PW)[None, :])             # (PH, PW)
        ps = jnp.take_along_axis(
            ps, bin_idx[:, :, None, None].astype(jnp.int32), axis=3)[..., 0]
        return ps.transpose(2, 0, 1)                      # (Cout, PH, PW)

    return jax.vmap(one)(rois).astype(data.dtype)


@register_op(aliases=("ROIPooling",))
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0, **_):
    """Max-pool ROI (reference: roi_pooling.cc) via dense ROIAlign samples."""
    B, C, H, W = data.shape
    PH, PW = pooled_size

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        img = data[bidx]
        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def cell(ph, pw):
            cy1 = y1 + jnp.floor(ph * rh / PH)
            cy2 = y1 + jnp.ceil((ph + 1) * rh / PH)
            cx1 = x1 + jnp.floor(pw * rw / PW)
            cx2 = x1 + jnp.ceil((pw + 1) * rw / PW)
            mask = ((ys[:, None] >= cy1) & (ys[:, None] < cy2)
                    & (xs[None, :] >= cx1) & (xs[None, :] < cx2))
            vals = jnp.where(mask[None], img, -jnp.inf)
            m = jnp.max(vals, axis=(1, 2))
            return jnp.where(jnp.isfinite(m), m, 0.0)

        phs, pws = jnp.meshgrid(jnp.arange(PH), jnp.arange(PW), indexing="ij")
        out = jax.vmap(jax.vmap(cell))(phs, pws)          # (PH, PW, C)
        return out.transpose(2, 0, 1)

    return jax.vmap(one)(rois).astype(data.dtype)


# ---------------------------------------------------------------------------
# Faster-RCNN surface: Proposal / MultiProposal, DeformableConvolution,
# PSROIPooling (reference: src/operator/contrib/{proposal,multi_proposal}.cu,
# nn/deformable_convolution.cu, psroi_pooling.cu — SURVEY §2.4 "padded-topk
# fixed-shape rewrite" requirement for the RPN path).
# ---------------------------------------------------------------------------

def _base_anchors(base_size, scales, ratios):
    """The reference's generate_anchors (rounded width/height enumeration):
    one (A, 4) corner-format anchor set centered on a base_size cell."""
    import numpy as onp
    base = onp.array([0, 0, base_size - 1, base_size - 1], onp.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    xc, yc = base[0] + 0.5 * (w - 1), base[1] + 0.5 * (h - 1)
    out = []
    for r in ratios:
        size_r = (w * h) / r
        ws = onp.round(onp.sqrt(size_r))
        hs = onp.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            out.append([xc - 0.5 * (wss - 1), yc - 0.5 * (hss - 1),
                        xc + 0.5 * (wss - 1), yc + 0.5 * (hss - 1)])
    return onp.array(out, onp.float32)


def _shifted_anchors(H, W, stride, base):
    """All anchors over an (H, W) feature map: (H*W*A, 4), row-major over
    (h, w, a) — matching the reference's enumeration order."""
    import numpy as onp
    sx = onp.arange(W, dtype=onp.float32) * stride
    sy = onp.arange(H, dtype=onp.float32) * stride
    shifts = onp.stack([
        onp.tile(sx, H),
        onp.repeat(sy, W),
        onp.tile(sx, H),
        onp.repeat(sy, W),
    ], axis=1)                                            # (H*W, 4)
    A = base.shape[0]
    all_anchors = (shifts[:, None, :] + base[None, :, :]).reshape(-1, 4)
    return all_anchors                                    # (H*W*A, 4)


def _bbox_pred(anchors, deltas, iou_loss=False):
    """Apply RPN regression deltas (reference: BBoxTransformInv)."""
    ws = anchors[:, 2] - anchors[:, 0] + 1.0
    hs = anchors[:, 3] - anchors[:, 1] + 1.0
    cx = anchors[:, 0] + 0.5 * (ws - 1.0)
    cy = anchors[:, 1] + 0.5 * (hs - 1.0)
    if iou_loss:
        return jnp.stack([anchors[:, 0] + deltas[:, 0],
                          anchors[:, 1] + deltas[:, 1],
                          anchors[:, 2] + deltas[:, 2],
                          anchors[:, 3] + deltas[:, 3]], axis=1)
    pcx = deltas[:, 0] * ws + cx
    pcy = deltas[:, 1] * hs + cy
    pw = jnp.exp(deltas[:, 2]) * ws
    ph = jnp.exp(deltas[:, 3]) * hs
    return jnp.stack([pcx - 0.5 * (pw - 1.0), pcy - 0.5 * (ph - 1.0),
                      pcx + 0.5 * (pw - 1.0), pcy + 0.5 * (ph - 1.0)], axis=1)


def _clip_boxes(boxes, imh, imw):
    """Clamp corner boxes (..., 4) to the image extent (reference:
    BBoxTransformInv's clip step)."""
    return jnp.stack([
        jnp.clip(boxes[..., 0], 0.0, imw - 1.0),
        jnp.clip(boxes[..., 1], 0.0, imh - 1.0),
        jnp.clip(boxes[..., 2], 0.0, imw - 1.0),
        jnp.clip(boxes[..., 3], 0.0, imh - 1.0)], axis=-1)


def _proposal_one(fg, deltas, iminfo, anchors, pre, post, thresh,
                  min_size, iou_loss):
    """One sample's RPN → rois. All shapes static: top-k to ``pre``, greedy
    NMS emitting exactly ``post`` slots (padded with zeros when exhausted).
    """
    imh, imw, imscale = iminfo[0], iminfo[1], iminfo[2]
    boxes = _clip_boxes(_bbox_pred(anchors, deltas, iou_loss), imh, imw)
    ws = boxes[:, 2] - boxes[:, 0] + 1.0
    hs = boxes[:, 3] - boxes[:, 1] + 1.0
    ms = min_size * imscale
    scores = jnp.where((ws >= ms) & (hs >= ms), fg, -jnp.inf)
    k = min(pre, scores.shape[0])
    top_scores, idx = lax.top_k(scores, k)
    top_boxes = boxes[idx]

    def nms_step(carry, _):
        alive, sc = carry
        j = jnp.argmax(jnp.where(alive, sc, -jnp.inf))
        ok = alive[j] & jnp.isfinite(sc[j])
        box = top_boxes[j]
        score = jnp.where(ok, sc[j], 0.0)
        box = jnp.where(ok, box, jnp.zeros(4, box.dtype))
        iou = _corner_iou(box[None, :], top_boxes)[0]
        alive = alive & (iou <= thresh) & (jnp.arange(k) != j)
        return (alive, sc), (box, score)

    (_, _), (sel_boxes, sel_scores) = lax.scan(
        nms_step, (jnp.ones(k, bool), top_scores), None, length=post)
    return sel_boxes, sel_scores


@register_op(aliases=("_contrib_MultiProposal", "MultiProposal"))
def multi_proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                   rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                   scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                   feature_stride=16, output_score=False, iou_loss=False, **_):
    """Batched RPN proposal op (reference: multi_proposal.cu).

    cls_prob (B, 2A, H, W) [bg scores then fg scores], bbox_pred (B, 4A, H,
    W), im_info (B, 3) [h, w, scale]. Returns rois (B*post, 5) with
    [batch_idx, x1, y1, x2, y2]; plus scores (B*post, 1) if output_score.
    TPU rewrite: fixed-shape padded top-k + greedy NMS scan (SURVEY §2.4).
    """
    B, A2, H, W = cls_prob.shape
    A = A2 // 2
    anchors = jnp.asarray(_shifted_anchors(H, W, feature_stride,
                                           _base_anchors(feature_stride,
                                                         scales, ratios)))
    # fg scores: channels A..2A, layout (A, H, W) → (H, W, A) → flat (HWA,)
    fg = jnp.transpose(cls_prob[:, A:, :, :], (0, 2, 3, 1)).reshape(B, -1)
    # deltas: (4A, H, W) = A boxes × 4 coords → (H, W, A, 4) → (HWA, 4)
    dl = bbox_pred.reshape(B, A, 4, H, W)
    dl = jnp.transpose(dl, (0, 3, 4, 1, 2)).reshape(B, -1, 4)
    pre = int(rpn_pre_nms_top_n)
    post = int(rpn_post_nms_top_n)

    def one(fg_b, dl_b, info_b):
        return _proposal_one(fg_b, dl_b, info_b, anchors, pre, post,
                             float(threshold), float(rpn_min_size), iou_loss)

    boxes, scores = jax.vmap(one)(fg, dl, im_info)        # (B, post, 4/1)
    bidx = jnp.repeat(jnp.arange(B, dtype=boxes.dtype), post)[:, None]
    rois = jnp.concatenate([bidx, boxes.reshape(B * post, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(B * post, 1)
    return rois


@register_op(aliases=("_contrib_Proposal", "Proposal"))
def proposal(cls_prob, bbox_pred, im_info, **kwargs):
    """Single-image RPN proposal (reference: proposal.cu) — the B=1 case of
    :func:`multi_proposal`."""
    return multi_proposal(cls_prob, bbox_pred, im_info, **kwargs)


@register_op(aliases=("_contrib_DeformableConvolution",
                      "DeformableConvolution"))
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                           num_filter=0, num_group=1, num_deformable_group=1,
                           no_bias=False, **_):
    """Deformable convolution v1 (reference: nn/deformable_convolution.cu —
    DCN). Each kernel tap samples the input at a learned fractional offset.

    TPU-native formulation: instead of the reference's im2col-with-offsets
    CUDA kernel, the sampled patches are gathered with vectorized bilinear
    interpolation (static shapes) and contracted with the weight in ONE MXU
    einsum — XLA sees gather + matmul, both native.

    data (B, C, H, W); offset (B, 2·ndg·K·K, Ho, Wo) ordered (dg, kk, [y,x]);
    weight (O, C/num_group, Kh, Kw). Returns (B, O, Ho, Wo).
    """
    B, C, H, W = data.shape
    Kh, Kw = kernel
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    Ho = (H + 2 * ph - dh * (Kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (Kw - 1) - 1) // sw + 1
    KK = Kh * Kw
    ndg = num_deformable_group
    off = offset.reshape(B, ndg, KK, 2, Ho, Wo)

    # base sampling grid per output position and tap (no offset yet)
    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    ky = jnp.repeat(jnp.arange(Kh) * dh, Kw)              # (KK,)
    kx = jnp.tile(jnp.arange(Kw) * dw, Kh)
    base_y = oy[None, :, None] + ky[:, None, None]        # (KK, Ho, 1)
    base_x = ox[None, None, :] + kx[:, None, None]        # (KK, 1, Wo)
    sy = base_y + off[:, :, :, 0]                         # (B, ndg, KK, Ho, Wo)
    sx = base_x + off[:, :, :, 1]

    def bilinear(img2d, y, x):
        """img2d (H, W); y/x (...) fractional; zeros outside."""
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)
        wy = y - y0
        wx = x - x0

        def at(yy, xx):
            inside = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            return jnp.where(inside, img2d[yi, xi], 0.0)

        return (at(y0, x0) * (1 - wy) * (1 - wx)
                + at(y0, x0 + 1) * (1 - wy) * wx
                + at(y0 + 1, x0) * wy * (1 - wx)
                + at(y0 + 1, x0 + 1) * wy * wx)

    cpg = C // ndg                                        # channels per dg

    def sample_b(img, sy_b, sx_b):
        # img (C, H, W); sy_b/sx_b (ndg, KK, Ho, Wo)
        def per_dg(imgs_dg, y_dg, x_dg):                  # (cpg, H, W)
            return jax.vmap(lambda im: bilinear(im, y_dg, x_dg))(imgs_dg)

        imgs = img.reshape(ndg, cpg, H, W)
        out = jax.vmap(per_dg)(imgs, sy_b, sx_b)          # (ndg, cpg, KK, Ho, Wo)
        return out.reshape(C, KK, Ho, Wo)

    patches = jax.vmap(sample_b)(data.astype(jnp.float32),
                                 sy.astype(jnp.float32),
                                 sx.astype(jnp.float32))  # (B, C, KK, Ho, Wo)

    O = weight.shape[0]
    cg = C // num_group                                   # in-ch per group
    og = O // num_group
    w = weight.reshape(num_group, og, cg, KK).astype(jnp.float32)
    p = patches.reshape(B, num_group, cg, KK, Ho, Wo)
    out = jnp.einsum("gock,bgckhw->bgohw", w, p,
                     preferred_element_type=jnp.float32)
    out = out.reshape(B, O, Ho, Wo)
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out.astype(data.dtype)


@register_op(aliases=("_contrib_PSROIPooling", "PSROIPooling"))
def psroi_pooling(data, rois, output_dim, pooled_size, spatial_scale=1.0,
                  group_size=None, **_):
    """Position-sensitive ROI pooling (reference: psroi_pooling.cu, R-FCN).
    Average-pools each bin from its own score-map channel group; implemented
    on the ROIAlign sampling machinery with position_sensitive=True."""
    ps = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else tuple(pooled_size)
    if group_size is not None and tuple(
            (group_size, group_size) if isinstance(group_size, int)
            else group_size) != ps:
        raise NotImplementedError(
            "psroi_pooling: group_size != pooled_size is unsupported "
            "(the score-map grid here is the pooled grid)")
    C = data.shape[1]
    if C != output_dim * ps[0] * ps[1]:
        raise ValueError(
            f"psroi_pooling: data needs output_dim*PH*PW = "
            f"{output_dim * ps[0] * ps[1]} channels, got {C}")
    return roi_align(data, rois, pooled_size=ps, spatial_scale=spatial_scale,
                     sample_ratio=2, position_sensitive=True)


def _encode_boxes(ref_boxes, gt):
    """Regression targets that invert :func:`_bbox_pred` exactly (the +1
    pixel convention) — decode of the encode reproduces the matched gt."""
    ws = ref_boxes[:, 2] - ref_boxes[:, 0] + 1.0
    hs = ref_boxes[:, 3] - ref_boxes[:, 1] + 1.0
    cx = ref_boxes[:, 0] + 0.5 * (ws - 1.0)
    cy = ref_boxes[:, 1] + 0.5 * (hs - 1.0)
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = gt[:, 0] + 0.5 * (gw - 1.0)
    gcy = gt[:, 1] + 0.5 * (gh - 1.0)
    ws = jnp.clip(ws, 1.0)
    hs = jnp.clip(hs, 1.0)
    return jnp.stack([(gcx - cx) / ws, (gcy - cy) / hs,
                      jnp.log(jnp.clip(gw, 1.0) / ws),
                      jnp.log(jnp.clip(gh, 1.0) / hs)], axis=-1)


@register_op(aliases=("_contrib_rpn_target", "AnchorTarget"))
def rpn_target(cls_prob, gt_boxes, im_info, feature_stride=16,
               scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
               fg_overlap=0.7, bg_overlap=0.3, **_):
    """RPN anchor targets (reference: the AnchorTarget stage of
    GluonCV faster_rcnn / incubator-mxnet example/rcnn rpn.anchor_target;
    SURVEY §2.9 Faster-RCNN row).

    ``cls_prob (B, 2A, H, W)`` supplies the feature shape (anchors are
    re-derived with the same attrs MultiProposal uses); ``gt_boxes
    (B, M, 5)`` is ``[cls, x1, y1, x2, y2]`` in PIXEL coords with -1
    padding; ``im_info (B, 3)``. Returns ``(labels (B, HWA) in
    {1 fg, 0 bg, -1 ignore}, bbox_targets (B, HWA, 4), bbox_mask
    (B, HWA, 4))`` in the (h, w, a) anchor enumeration MultiProposal
    flattens to. No fg/bg subsampling (the reference's 256-anchor batch
    sampling is a GPU-memory concession; the full fixed-shape loss is
    cheaper on TPU than a gather), so the loss should mean over non-ignored
    anchors."""
    B, A2, H, W = cls_prob.shape
    anchors = jnp.asarray(_shifted_anchors(
        H, W, feature_stride, _base_anchors(feature_stride, scales, ratios)))
    N = anchors.shape[0]

    def one(gt, info):
        valid = gt[:, 0] >= 0
        boxes = gt[:, 1:5]
        iou = _corner_iou(anchors, boxes)                 # (N, M)
        iou = jnp.where(valid[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        inside = (anchors[:, 0] >= 0.0) & (anchors[:, 1] >= 0.0) & \
                 (anchors[:, 2] <= info[1] - 1.0) & \
                 (anchors[:, 3] <= info[0] - 1.0)
        # forced per-gt best anchor over INSIDE anchors only (the reference
        # computes anchor targets on the inside subset): a border gt whose
        # global argmax anchor straddles the image must still force-match
        # its best inside anchor, or it contributes no RPN gradient at all
        iou_in = jnp.where(inside[:, None], iou, -1.0)
        best_anchor = jnp.argmax(iou_in, axis=0)          # (M,)
        has_inside = jnp.max(iou_in, axis=0) > 0.0
        forced = jnp.zeros(N, bool).at[best_anchor].max(valid & has_inside)
        fg = (forced | (best_iou >= fg_overlap)) & inside
        bg = (best_iou < bg_overlap) & inside & ~fg
        labels = jnp.where(fg, 1.0, jnp.where(bg, 0.0, -1.0))
        t = _encode_boxes(anchors, boxes[best_gt])
        mask = jnp.broadcast_to(fg[:, None], (N, 4)).astype(cls_prob.dtype)
        return labels.astype(cls_prob.dtype), t * mask, mask

    lbl, t, m = jax.vmap(one)(gt_boxes, im_info)
    return lbl, t.astype(cls_prob.dtype), m


@register_op(aliases=("_contrib_proposal_target", "ProposalTarget"))
def proposal_target(rois, gt_boxes, num_classes=None, fg_overlap=0.5, **_):
    """ROI head targets (reference: the ProposalTarget stage of GluonCV
    faster_rcnn / example/rcnn rcnn.proposal_target). No roi subsampling —
    the TPU pipeline's roi count is already static and small, so every roi
    gets a target (the reference samples 128 of ~2000 to bound GPU memory).

    ``rois (B*R, 5)`` ``[batch_idx, x1, y1, x2, y2]`` pixels; ``gt_boxes
    (B, M, 5)`` pixels, -1 padded. Returns ``(cls_target (B, R) in
    {0..num_classes}, box_target (B, R, 4*(C+1)), box_mask
    (B, R, 4*(C+1)))`` with class-specific regression slots: only the
    matched class's 4 slots are live; encode inverts _bbox_pred."""
    B = gt_boxes.shape[0]
    R = rois.shape[0] // B
    C1 = int(num_classes) + 1
    roi_boxes = rois.reshape(B, R, 5)[..., 1:5]

    def one(rb, gt):
        valid = gt[:, 0] >= 0
        boxes = gt[:, 1:5]
        iou = _corner_iou(rb, boxes)                      # (R, M)
        iou = jnp.where(valid[None, :], iou, 0.0)
        best = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        fg = best_iou >= fg_overlap
        cls = jnp.where(fg, gt[best, 0] + 1.0, 0.0)
        t4 = _encode_boxes(rb, boxes[best])               # (R, 4)
        onehot = jax.nn.one_hot(cls.astype(jnp.int32), C1)
        mask = (onehot * fg[:, None]).astype(rois.dtype)  # (R, C1)
        t = (onehot[:, :, None] * t4[:, None, :]).reshape(R, 4 * C1)
        mask4 = jnp.repeat(mask, 4, axis=-1).reshape(R, 4 * C1)
        return cls.astype(rois.dtype), t * mask4, mask4

    cls_t, box_t, box_m = jax.vmap(one)(roi_boxes, gt_boxes)
    return cls_t, box_t.astype(rois.dtype), box_m

"""Detection ops — box_nms, MultiBox*, ROIAlign/ROIPooling, box_iou.

Reference parity: ``src/operator/contrib/bounding_box.cc`` (``box_nms``,
``box_iou``, ``bipartite_matching``), ``src/operator/contrib/multibox_*.cc``
(SSD's MultiBoxPrior/Target/Detection) and ``src/operator/contrib/
roi_align.cc`` / ``src/operator/roi_pooling.cc`` — SURVEY §2.4's "padded
top-k NMS" fixed-shape rewrite requirement.

TPU-native design: every op is fixed-shape. NMS keeps all N slots and marks
suppressed entries with -1 (exactly the reference's output convention, which
happens to be TPU-friendly already); the suppression loop is a
``lax.fori_loop`` over a precomputed (N, N) IoU matrix, compiling to one
fused kernel instead of the reference's sort + sequential CUDA kernel chain.
ROIAlign gathers bilinear samples with static sampling grids.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

__all__ = ["box_iou", "box_nms", "bipartite_matching", "multibox_prior",
           "multibox_target", "multibox_detection", "roi_align", "roi_pooling"]


def _corner_iou(a, b):
    """IoU between corner-format boxes a (..., N, 4) and b (..., M, 4)."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)       # (..., N, 1)
    bx1, by1, bx2, by2 = (x.squeeze(-1) for x in jnp.split(b, 4, axis=-1))
    ix1 = jnp.maximum(ax1, bx1[..., None, :])           # (..., N, M)
    iy1 = jnp.maximum(ay1, by1[..., None, :])
    ix2 = jnp.minimum(ax2, bx2[..., None, :])
    iy2 = jnp.minimum(ay2, by2[..., None, :])
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(ax2 - ax1, 0) * jnp.clip(ay2 - ay1, 0)
    area_b = jnp.clip(bx2 - bx1, 0) * jnp.clip(by2 - by1, 0)
    union = area_a + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_to_corner(b):
    x, y, w, h = jnp.split(b, 4, axis=-1)
    return jnp.concatenate([x - w / 2, y - h / 2, x + w / 2, y + h / 2], -1)


def _corner_to_center(b):
    x1, y1, x2, y2 = jnp.split(b, 4, axis=-1)
    return jnp.concatenate([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], -1)


@register_op(aliases=("_contrib_box_iou",))
def box_iou(lhs, rhs, format="corner", **_):
    if format == "center":
        lhs, rhs = _center_to_corner(lhs), _center_to_corner(rhs)
    return _corner_iou(lhs, rhs)


@register_op(aliases=("_contrib_box_nms",))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            background_id=-1, force_suppress=False, in_format="corner",
            out_format="corner", **_):
    """Fixed-shape NMS. data (..., N, K) with K >= coord_start+4; output has
    identical shape with suppressed/invalid rows set to -1 and survivors
    sorted by score (reference output convention)."""
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    *batch, N, K = data.shape
    flat = data.reshape((-1, N, K))

    def one(sample):
        scores = sample[:, score_index]
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= sample[:, id_index] != background_id
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        s = sample[order]
        svalid = valid[order]
        if topk > 0:
            svalid &= jnp.arange(N) < topk
        boxes = s[:, coord_start:coord_start + 4]
        if in_format == "center":
            boxes = _center_to_corner(boxes)
        iou = _corner_iou(boxes, boxes)
        if not force_suppress and id_index >= 0:
            same = s[:, id_index][:, None] == s[:, id_index][None, :]
            iou = jnp.where(same, iou, 0.0)

        def body(i, keep):
            ki = keep[i] & svalid[i]
            sup = (iou[i] > overlap_thresh) & (jnp.arange(N) > i) & ki
            return keep & ~sup

        keep = lax.fori_loop(0, N, body, jnp.ones(N, bool)) & svalid
        if out_format != in_format:
            coords = s[:, coord_start:coord_start + 4]
            conv = (_center_to_corner(coords) if out_format == "corner"
                    else _corner_to_center(coords))
            s = s.at[:, coord_start:coord_start + 4].set(conv)
        out = jnp.where(keep[:, None], s, -jnp.ones_like(s))
        return out

    out = jax.vmap(one)(flat).reshape(data.shape)
    return out[0] if squeeze else out


@register_op(aliases=("_contrib_bipartite_matching",))
def bipartite_matching(data, threshold=0.5, is_ascend=False, topk=-1, **_):
    """Greedy bipartite matching over a (..., N, M) score matrix
    (reference: bounding_box.cc BipartiteMatching). Returns (row_match,
    col_match): for each row the matched col (or -1), and inverse."""
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    B, N, M = data.shape
    sign = 1.0 if is_ascend else -1.0

    def one(mat):
        def body(_, carry):
            row_m, col_m, m = carry
            masked = jnp.where((row_m[:, None] < 0) & (col_m[None, :] < 0),
                               m, sign * jnp.inf)
            # best remaining pair: max score (descend) / min (ascend)
            idx = jnp.argmax(-sign * masked.reshape(-1))
            r, c = idx // M, idx % M
            # threshold the MASKED value: when rows/cols are exhausted the
            # argmax lands on an inf slot, which must never match
            val = masked[r, c]
            ok = (val > threshold) if not is_ascend else (val < threshold)
            row_m = jnp.where(ok, row_m.at[r].set(c), row_m)
            col_m = jnp.where(ok, col_m.at[c].set(r), col_m)
            return row_m, col_m, m

        k = N if topk <= 0 else min(topk, N)
        row0 = -jnp.ones(N, jnp.int32)
        col0 = -jnp.ones(M, jnp.int32)
        row_m, col_m, _ = lax.fori_loop(0, k, body, (row0, col0, mat))
        return row_m.astype(data.dtype), col_m.astype(data.dtype)

    rows, cols = jax.vmap(one)(data)
    if squeeze:
        return rows[0], cols[0]
    return rows, cols


@register_op(aliases=("_contrib_MultiBoxPrior", "MultiBoxPrior"))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5), **_):
    """SSD anchor generation (reference: multibox_prior.cc). data is the
    (B, C, H, W) feature map; returns (1, H*W*(S+R-1), 4) corner anchors."""
    H, W = data.shape[-2], data.shape[-1]
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H) + offsets[0]) * step_y
    cx = (jnp.arange(W) + offsets[1]) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([cx.reshape(-1), cy.reshape(-1)], -1)  # (HW, 2)
    # widths carry the reference's in_h/in_w aspect correction
    # (multibox_prior.cc) so anchors stay square in image space on
    # non-square feature maps.
    ar = H / W
    whs = []
    s0 = sizes[0]
    for s in sizes:
        whs.append((s * ar, s))
    for r in ratios[1:]:
        rr = float(r) ** 0.5
        whs.append((s0 * rr * ar, s0 / rr))
    whs = jnp.asarray(whs)                                       # (A, 2)
    A = whs.shape[0]
    c = jnp.repeat(centers[:, None, :], A, axis=1)               # (HW, A, 2)
    wh = jnp.broadcast_to(whs[None], (centers.shape[0], A, 2))
    boxes = jnp.concatenate([c - wh / 2, c + wh / 2], -1).reshape(1, -1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.astype(data.dtype)


@register_op(aliases=("_contrib_MultiBoxTarget", "MultiBoxTarget"))
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2), **_):
    """SSD training targets (reference: multibox_target.cc).
    anchor (1, N, 4) corner; label (B, M, 5) [cls, x1, y1, x2, y2] with -1
    padding; cls_pred (B, num_cls+1, N). Returns (loc_target (B, N*4),
    loc_mask (B, N*4), cls_target (B, N))."""
    anchors = anchor.reshape(-1, 4)
    N = anchors.shape[0]
    var = jnp.asarray(variances)

    def one(lab, pred):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _corner_iou(anchors, gt_boxes)              # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=1)                 # (N,)
        best_iou = jnp.max(iou, axis=1)
        # force-match: each VALID gt's best anchor is positive. at[].max so a
        # padding gt (argmax lands on anchor 0) can't overwrite a real match.
        best_anchor = jnp.argmax(iou, axis=0)             # (M,)
        forced = jnp.zeros(N, bool).at[best_anchor].max(gt_valid)
        pos = (best_iou >= overlap_threshold) | forced
        matched = gt_boxes[best_gt]                       # (N, 4)
        # encode regression target (center offsets / variances)
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.clip(matched[:, 2] - matched[:, 0], 1e-8)
        gh = jnp.clip(matched[:, 3] - matched[:, 1], 1e-8)
        gcx = (matched[:, 0] + matched[:, 2]) / 2
        gcy = (matched[:, 1] + matched[:, 3]) / 2
        tx = (gcx - acx) / jnp.clip(aw, 1e-8) / var[0]
        ty = (gcy - acy) / jnp.clip(ah, 1e-8) / var[1]
        tw = jnp.log(gw / jnp.clip(aw, 1e-8)) / var[2]
        th = jnp.log(gh / jnp.clip(ah, 1e-8)) / var[3]
        loc_t = jnp.stack([tx, ty, tw, th], -1)           # (N, 4)
        loc_mask = jnp.broadcast_to(pos[:, None], (N, 4)).astype(anchor.dtype)
        pos_cls = lab[best_gt, 0] + 1.0
        if negative_mining_ratio > 0:
            # hard negative mining (multibox_target.cc): keep the
            # ratio*num_pos hardest background anchors (largest background
            # CE under the current predictions); the rest get ignore_label.
            neg_loss = -jax.nn.log_softmax(pred, axis=0)[0]
            num_pos = jnp.sum(pos)
            max_neg = jnp.maximum(num_pos * negative_mining_ratio,
                                  float(minimum_negative_samples))
            cand = jnp.where(pos, -jnp.inf, neg_loss)
            order = jnp.argsort(-cand)
            rank = jnp.zeros(N, jnp.int32).at[order].set(
                jnp.arange(N, dtype=jnp.int32))
            sel_neg = (~pos) & (rank < max_neg)
            cls_t = jnp.where(pos, pos_cls,
                              jnp.where(sel_neg, 0.0, ignore_label))
        else:
            cls_t = jnp.where(pos, pos_cls, 0.0)
        return (loc_t * loc_mask).reshape(-1), loc_mask.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    return loc_t.astype(anchor.dtype), loc_m, cls_t.astype(anchor.dtype)


@register_op(aliases=("_contrib_MultiBoxDetection", "MultiBoxDetection"))
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1, **_):
    """SSD decode + NMS (reference: multibox_detection.cc).
    cls_prob (B, num_cls+1, N), loc_pred (B, N*4), anchor (1, N, 4).
    Returns (B, N, 6) [id, score, x1, y1, x2, y2], -1 for invalid."""
    B = cls_prob.shape[0]
    N = anchor.shape[1]
    var = jnp.asarray(variances)
    anchors = anchor.reshape(N, 4)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def one(prob, loc):
        loc = loc.reshape(N, 4)
        cx = loc[:, 0] * var[0] * aw + acx
        cy = loc[:, 1] * var[1] * ah + acy
        w = jnp.exp(loc[:, 2] * var[2]) * aw
        h = jnp.exp(loc[:, 3] * var[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor; output class ids are
        # 0-based over the non-background classes (reference convention)
        C = prob.shape[0]
        if 0 <= background_id < C:
            masked = prob.at[background_id].set(-jnp.inf)
            raw = jnp.argmax(masked, axis=0)
            cls = jnp.where(raw > background_id, raw - 1, raw)
            score = jnp.max(masked, axis=0)
        else:
            cls = jnp.argmax(prob, axis=0)
            score = jnp.max(prob, axis=0)
        det = jnp.concatenate([cls[:, None].astype(boxes.dtype),
                               score[:, None], boxes], -1)
        return box_nms(det, overlap_thresh=nms_threshold,
                       valid_thresh=threshold, topk=nms_topk,
                       force_suppress=force_suppress, coord_start=2,
                       score_index=1, id_index=0)

    return jax.vmap(one)(cls_prob, loc_pred)


@register_op(aliases=("_contrib_ROIAlign", "ROIAlign"))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, aligned=False, **_):
    """ROIAlign with bilinear sampling (reference: roi_align.cc).
    data (B, C, H, W); rois (R, 5) [batch_idx, x1, y1, x2, y2] in image
    coords. Returns (R, C, PH, PW)."""
    if position_sensitive:
        raise NotImplementedError(
            "position-sensitive ROIAlign (PS-ROIAlign) is not implemented; "
            "use position_sensitive=False")
    B, C, H, W = data.shape
    PH, PW = pooled_size
    sr = max(1, int(sample_ratio))
    off = 0.5 if aligned else 0.0

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale - off, roi[2] * spatial_scale - off, \
            roi[3] * spatial_scale - off, roi[4] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w, bin_h = rw / PW, rh / PH
        # static (PH*sr, PW*sr) sampling grid
        gy = y1 + (jnp.repeat(jnp.arange(PH), sr)
                   + (jnp.tile(jnp.arange(sr), PH) + 0.5) / sr) * bin_h
        gx = x1 + (jnp.repeat(jnp.arange(PW), sr)
                   + (jnp.tile(jnp.arange(sr), PW) + 0.5) / sr) * bin_w
        img = data[bidx]                                  # (C, H, W)

        def bilinear(y, x):
            y0 = jnp.clip(jnp.floor(y), 0, H - 1)
            x0 = jnp.clip(jnp.floor(x), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(y - y0, 0, 1)
            wx = jnp.clip(x - x0, 0, 1)
            y0i, x0i, y1i, x1i = (v.astype(jnp.int32) for v in (y0, x0, y1_, x1_))
            v00 = img[:, y0i, x0i]
            v01 = img[:, y0i, x1i]
            v10 = img[:, y1i, x0i]
            v11 = img[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                    + v10 * wy * (1 - wx) + v11 * wy * wx)

        yy, xx = jnp.meshgrid(gy, gx, indexing="ij")      # (PH*sr, PW*sr)
        samples = jax.vmap(jax.vmap(bilinear))(yy, xx)    # (PH*sr, PW*sr, C)
        samples = samples.reshape(PH, sr, PW, sr, C)
        return jnp.mean(samples, axis=(1, 3)).transpose(2, 0, 1)

    return jax.vmap(one)(rois).astype(data.dtype)


@register_op(aliases=("ROIPooling",))
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0, **_):
    """Max-pool ROI (reference: roi_pooling.cc) via dense ROIAlign samples."""
    B, C, H, W = data.shape
    PH, PW = pooled_size

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        img = data[bidx]
        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def cell(ph, pw):
            cy1 = y1 + jnp.floor(ph * rh / PH)
            cy2 = y1 + jnp.ceil((ph + 1) * rh / PH)
            cx1 = x1 + jnp.floor(pw * rw / PW)
            cx2 = x1 + jnp.ceil((pw + 1) * rw / PW)
            mask = ((ys[:, None] >= cy1) & (ys[:, None] < cy2)
                    & (xs[None, :] >= cx1) & (xs[None, :] < cx2))
            vals = jnp.where(mask[None], img, -jnp.inf)
            m = jnp.max(vals, axis=(1, 2))
            return jnp.where(jnp.isfinite(m), m, 0.0)

        phs, pws = jnp.meshgrid(jnp.arange(PH), jnp.arange(PW), indexing="ij")
        out = jax.vmap(jax.vmap(cell))(phs, pws)          # (PH, PW, C)
        return out.transpose(2, 0, 1)

    return jax.vmap(one)(rois).astype(data.dtype)

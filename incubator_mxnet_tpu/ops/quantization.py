"""INT8 quantization ops — the TPU counterpart of the reference INT8
subsystem (``src/operator/quantization/``: quantize/dequantize/requantize
kernels, ``quantized_conv``/``quantized_fully_connected``/
``quantized_pooling``, SURVEY §2.4).

TPU-native design: TPUs execute int8×int8→int32 matmuls and convolutions
natively on the MXU (``preferred_element_type=jnp.int32``), so the quantized
compute ops are straight XLA dots/convs on int8 operands — no cuDNN-style
hand-packed kernels. The value/range calling convention follows the
reference exactly: every quantized tensor travels as ``(q, min_range,
max_range)``, with the *symmetric signed* int8 scheme the reference uses for
weights and (by default) activations: ``scale = 127 / max(|min|, |max|)``.

Calibration (min/max + KL-entropy) and the graph pass that swaps float
layers for these ops live in ``incubator_mxnet_tpu/quantization/``.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

__all__ = [
    "quantize", "quantize_v2", "dequantize", "requantize",
    "quantized_fully_connected", "quantized_conv", "quantized_pooling",
    "quantized_flatten", "quantized_act",
]

_INT8_RANGE = 127.0
_UINT8_RANGE = 255.0


def _symmetric_scale(min_range, max_range):
    """Real-value scale of the symmetric int8 encoding (reference:
    MaxAbs(min, max) / kInt8Range in quantization_utils.h)."""
    real = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return jnp.maximum(real, 1e-30) / _INT8_RANGE


@register_op(aliases=("_contrib_quantize",))
def quantize(data, min_range, max_range, out_type: str = "int8", **_):
    """Quantize fp32 -> int8 with an explicit calibration range. Returns
    ``(q, min_range, max_range)`` (reference: quantize.cc)."""
    if out_type not in ("int8", "uint8"):
        raise ValueError(f"quantize: unsupported out_type {out_type!r}")
    min_range = jnp.asarray(min_range, jnp.float32)
    max_range = jnp.asarray(max_range, jnp.float32)
    if out_type == "int8":
        scale = _symmetric_scale(min_range, max_range)
        q = jnp.clip(jnp.round(data.astype(jnp.float32) / scale),
                     -_INT8_RANGE, _INT8_RANGE).astype(jnp.int8)
    else:
        # affine uint8 over [min, max] (reference uint8 branch)
        rng = jnp.maximum(max_range - min_range, 1e-30)
        scale = _UINT8_RANGE / rng
        q = jnp.clip(jnp.round((data.astype(jnp.float32) - min_range) * scale),
                     0, _UINT8_RANGE).astype(jnp.uint8)
    return q, min_range, max_range


@register_op(aliases=("_contrib_quantize_v2",))
def quantize_v2(data, min_calib_range: Optional[float] = None,
                max_calib_range: Optional[float] = None,
                out_type: str = "int8", **_):
    """Quantize with ranges from calibration — or computed on the fly when
    absent (reference: quantize_v2.cc online branch)."""
    if min_calib_range is None or max_calib_range is None:
        min_calib_range = jnp.min(data).astype(jnp.float32)
        max_calib_range = jnp.max(data).astype(jnp.float32)
    return quantize(data, min_calib_range, max_calib_range, out_type=out_type)


@register_op(aliases=("_contrib_dequantize",))
def dequantize(data, min_range, max_range, **_):
    """int8/uint8/int32 -> fp32 (reference: dequantize.cc). The range pair
    always describes the REAL values representable at the dtype's full
    integer span (127 for int8, 2³¹-1 for the int32 accumulator)."""
    min_range = jnp.asarray(min_range, jnp.float32)
    max_range = jnp.asarray(max_range, jnp.float32)
    if data.dtype == jnp.uint8:
        rng = jnp.maximum(max_range - min_range, 1e-30)
        return data.astype(jnp.float32) * (rng / _UINT8_RANGE) + min_range
    span = _INT8_RANGE if data.dtype == jnp.int8 else float(2 ** 31 - 1)
    real = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return data.astype(jnp.float32) * (jnp.maximum(real, 1e-30) / span)


@register_op(aliases=("_contrib_requantize",))
def requantize(data, min_range, max_range,
               min_calib_range: Optional[float] = None,
               max_calib_range: Optional[float] = None, **_):
    """int32 accumulator -> int8 with a (calibrated or online) output range
    (reference: requantize.cc)."""
    real = dequantize(data, min_range, max_range)
    if min_calib_range is None or max_calib_range is None:
        min_calib_range = jnp.min(real)
        max_calib_range = jnp.max(real)
    return quantize(real, min_calib_range, max_calib_range, out_type="int8")


@register_op(aliases=("_contrib_quantized_fully_connected",))
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias=None,
                              max_bias=None, num_hidden: int = 0,
                              no_bias: bool = False, flatten: bool = True, **_):
    """int8 FC on the MXU: ``int8 @ int8 -> int32`` via
    ``preferred_element_type`` (reference: quantized_fully_connected.cc).

    data (N, ..., C) int8; weight (num_hidden, C) int8; bias int8 (its own
    range) or None. Returns ``(acc_int32, min_out, max_out)`` where the out
    range is the accumulator's representable real range — feed through
    ``requantize`` (with calibration) or ``dequantize``.
    """
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    acc = lax.dot_general(
        data, weight,
        (((data.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    sa = _symmetric_scale(jnp.asarray(min_data, jnp.float32),
                          jnp.asarray(max_data, jnp.float32))
    sw = _symmetric_scale(jnp.asarray(min_weight, jnp.float32),
                          jnp.asarray(max_weight, jnp.float32))
    if not no_bias and bias is not None:
        # re-encode the int8 bias onto the accumulator scale sa*sw
        sb = _symmetric_scale(jnp.asarray(min_bias, jnp.float32),
                              jnp.asarray(max_bias, jnp.float32))
        b32 = jnp.round(bias.astype(jnp.float32) * (sb / (sa * sw))
                        ).astype(jnp.int32)
        acc = acc + b32
    bound = sa * sw * jnp.float32(2 ** 31 - 1)
    return acc, -bound, bound


@register_op(aliases=("_contrib_quantized_conv",))
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias=None, max_bias=None,
                   kernel=None, stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                   num_filter: int = 0, no_bias: bool = False,
                   layout: str = "NCHW", **_):
    """int8 convolution on the MXU (reference: quantized_conv.cu). NCHW
    data, OIHW weight, int32 accumulator out with its real range."""
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    acc = lax.conv_general_dilated(
        data, weight, window_strides=tuple(stride),
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=tuple(dilate), dimension_numbers=dn,
        preferred_element_type=jnp.int32)
    sa = _symmetric_scale(jnp.asarray(min_data, jnp.float32),
                          jnp.asarray(max_data, jnp.float32))
    sw = _symmetric_scale(jnp.asarray(min_weight, jnp.float32),
                          jnp.asarray(max_weight, jnp.float32))
    if not no_bias and bias is not None:
        sb = _symmetric_scale(jnp.asarray(min_bias, jnp.float32),
                              jnp.asarray(max_bias, jnp.float32))
        b32 = jnp.round(bias.astype(jnp.float32) * (sb / (sa * sw))
                        ).astype(jnp.int32)
        acc = acc + b32.reshape(1, -1, 1, 1)
    bound = sa * sw * jnp.float32(2 ** 31 - 1)
    return acc, -bound, bound


@register_op(aliases=("_contrib_quantized_pooling",))
def quantized_pooling(data, min_data, max_data, kernel=(2, 2),
                      stride=None, pad=(0, 0), pool_type: str = "max", **_):
    """Pooling straight on int8 values — order-preserving, so the range
    passes through (reference: quantized_pooling.cc)."""
    stride = stride or kernel
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1]))
    if pool_type == "max":
        init = jnp.iinfo(jnp.int8).min if data.dtype == jnp.int8 else 0
        out = lax.reduce_window(data, jnp.asarray(init, data.dtype), lax.max,
                                window, strides, padding)
        return out, min_data, max_data
    if pool_type == "avg":
        # average in int32, round back to the input dtype (range preserved)
        s = lax.reduce_window(data.astype(jnp.int32), jnp.int32(0), lax.add,
                              window, strides, padding)
        n = kernel[0] * kernel[1]
        info = jnp.iinfo(data.dtype)
        out = jnp.clip(jnp.round(s / n), info.min, info.max).astype(data.dtype)
        return out, min_data, max_data
    raise ValueError(f"quantized_pooling: unsupported pool_type {pool_type!r}")


@register_op(aliases=("_contrib_quantized_flatten",))
def quantized_flatten(data, min_data, max_data, **_):
    return data.reshape(data.shape[0], -1), min_data, max_data


@register_op(aliases=("_contrib_quantized_act",))
def quantized_act(data, min_data, max_data, act_type: str = "relu", **_):
    """relu on int8 is a clamp at the zero point (symmetric: 0)."""
    if act_type != "relu":
        raise ValueError("only relu is supported on the int8 path "
                         "(reference restriction)")
    return jnp.maximum(data, 0), jnp.zeros_like(
        jnp.asarray(min_data, jnp.float32)), jnp.asarray(max_data, jnp.float32)

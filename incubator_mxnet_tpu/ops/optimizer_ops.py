"""Optimizer update ops + AMP cast ops — the registered op surface.

Reference counterpart: ``src/operator/optimizer_op.cc`` (``sgd_update``,
``sgd_mom_update``, ``mp_sgd_*``, ``adam_update``, ``nag_mom_update``,
``signsgd_update``/``signum_update``, ``ftrl_update``, ``rmsprop_update``,
the ``multi_sgd_*`` multi-tensor family), ``src/operator/contrib/adamw.cc``
(``adamw_update``), the LAMB phases (``src/operator/optimizer_op.cc``
``lamb_update_phase1/2``), and ``src/operator/tensor/amp_cast.cc``
(``amp_cast``/``amp_multicast``).

The trainer path in this framework never calls these by name — the whole
optimizer step is fused into one compiled XLA program
(``parallel/trainer.py``), which is what the reference's multi-tensor ops
exist to approximate kernel-by-kernel. These registered wrappers exist for
*op-surface parity*: user code that drives updates through
``mx.nd.sgd_update(...)`` finds the same names with the same math.

Purity note: the reference mutates ``weight``/state inputs in place; every
op here is pure and RETURNS the updated tensors (weight first, then
states). Use ``out=[weight, state...]`` on the ``mx.nd`` wrapper for
reference-style in-place assignment.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import Field, Schema, register_op

__all__: list = []


def _prep(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


# ---------------------------------------------------------------------------
# single-tensor updates
# ---------------------------------------------------------------------------

@register_op("sgd_update")
def sgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=False, **_):
    """w -= lr * (rescale·clip(grad) + wd·w) (reference:
    optimizer_op.cc SGDUpdate)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register_op("sgd_mom_update")
def sgd_mom_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False,
                   **_):
    """Momentum SGD (reference: optimizer_op.cc SGDMomUpdate). Returns
    (weight, mom)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register_op("mp_sgd_update")
def mp_sgd_update(weight, grad, weight32, lr=None, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=False, **_):
    """Mixed-precision SGD with an fp32 master weight (reference:
    optimizer_op.cc MP_SGDUpdate). Returns (weight, weight32)."""
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@register_op("mp_sgd_mom_update")
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=None, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=False, **_):
    """Returns (weight, mom, weight32)."""
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register_op("adam_update")
def adam_update(weight, grad, mean, var, lr=None, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=False, **_):
    """Adam (reference: optimizer_op.cc AdamUpdate — the raw step without
    bias correction, matching the kernel; clip applies to
    rescale·grad + wd·w as one quantity there). Returns
    (weight, mean, var)."""
    g = _prep(grad * rescale_grad + wd * weight, 1.0, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - lr * m / (jnp.sqrt(v) + epsilon), m, v


@register_op("adamw_update", aliases=("_contrib_adamw_update",))
def adamw_update(weight, grad, mean, var, rescale_grad, lr=None, eta=1.0,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                 clip_gradient=-1.0, **_):
    """AdamW with decoupled weight decay (reference: contrib/adamw.cc;
    rescale_grad arrives as a TENSOR there — kept). Returns
    (weight, mean, var)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight), m, v


@register_op("nag_mom_update")
def nag_mom_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, **_):
    """Nesterov momentum (reference: optimizer_op.cc NAGMomUpdate; clip
    applies to rescale·grad + wd·w as one quantity). Returns
    (weight, mom)."""
    g = _prep(grad * rescale_grad + wd * weight, 1.0, clip_gradient)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register_op("signsgd_update")
def signsgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **_):
    """signSGD (reference: optimizer_op.cc SignSGDUpdate)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register_op("signum_update")
def signum_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, **_):
    """Signum: momentum + sign (reference: optimizer_op.cc SignumUpdate).
    Returns (weight, mom)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight)
    return weight * (1 - lr * wd_lh) + lr * jnp.sign(new_mom), new_mom


@register_op("ftrl_update")
def ftrl_update(weight, grad, z, n, lr=None, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, **_):
    """FTRL-proximal (reference: optimizer_op.cc FTRLUpdate). Returns
    (weight, z, n)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return w, new_z, new_n


@register_op("rmsprop_update")
def rmsprop_update(weight, grad, n, lr=None, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0, **_):
    """RMSProp (reference: optimizer_op.cc RMSPropUpdate; clip applies to
    rescale·grad + wd·w as one quantity). Returns (weight, n)."""
    g = _prep(grad * rescale_grad + wd * weight, 1.0, clip_gradient)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights >= 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


# ---------------------------------------------------------------------------
# LAMB phases (reference: optimizer_op.cc lamb_update_phase1/2 — the
# BERT-large large-batch path; phase1 forms the adaptive direction, the
# caller computes the layer norms, phase2 applies the trust ratio)
# ---------------------------------------------------------------------------

@register_op("lamb_update_phase1")
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, **_):
    """Returns (g_direction, mean, var)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    t = jnp.asarray(t, jnp.float32)
    if bias_correction:
        mhat = m / (1 - beta1 ** t)
        vhat = v / (1 - beta2 ** t)
    else:
        mhat, vhat = m, v
    return mhat / (jnp.sqrt(vhat) + epsilon) + wd * weight, m, v


@register_op("lamb_update_phase2")
def lamb_update_phase2(weight, g, r1, r2, lr=None, lower_bound=-1.0,
                       upper_bound=-1.0, **_):
    """Apply the trust ratio r1/r2 (norms computed by the caller, as the
    reference does with multi_sum_sq): w -= lr·(r1/r2)·g."""
    r1 = jnp.reshape(r1, ())
    r2 = jnp.reshape(r2, ())
    if lower_bound is not None and lower_bound >= 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound >= 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    return weight - lr * ratio * g


@register_op("mp_lamb_update_phase1")
def mp_lamb_update_phase1(weight, grad, mean, var, weight32, **kwargs):
    """fp32-master variant of phase1 (reference: mp_lamb_update_phase1):
    the direction is formed against the fp32 master weight."""
    return lamb_update_phase1(weight32, grad.astype(jnp.float32),
                              mean, var, **kwargs)


@register_op("mp_lamb_update_phase2")
def mp_lamb_update_phase2(weight, g, r1, r2, weight32, lr=None,
                          lower_bound=-1.0, upper_bound=-1.0, **_):
    """Returns (weight, weight32)."""
    w32 = lamb_update_phase2(weight32, g, r1, r2, lr=lr,
                             lower_bound=lower_bound,
                             upper_bound=upper_bound)
    return w32.astype(weight.dtype), w32


# ---------------------------------------------------------------------------
# multi-tensor family (reference: optimizer_op.cc MultiSGDUpdate — one
# kernel launch over many params; XLA fuses per-tensor updates anyway, so
# these are pure API-parity wrappers over the single-tensor math)
# ---------------------------------------------------------------------------

def _csv_floats(name, v, n):
    if v is None:
        raise ValueError(f"multi-tensor update: required parameter "
                         f"'{name}' is missing (one value per weight, "
                         f"e.g. {name}='0.1, 0.1')")
    if isinstance(v, str):
        v = [float(p) for p in v.replace(",", " ").split()]
    elif isinstance(v, (int, float)):
        v = [float(v)] * n
    v = list(v)
    if len(v) != n:
        raise ValueError(f"{name}: expected {n} values, got {len(v)}")
    return v


@register_op("multi_sgd_update")
def multi_sgd_update(*arrays, lrs=None, wds=None, rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=1, **_):
    """Interleaved (w0, g0, w1, g1, ...) — returns the updated weights
    (reference: multi_sgd_update)."""
    n = int(num_weights)
    lrs = _csv_floats("lrs", lrs, n)
    wds = _csv_floats("wds", wds, n)
    outs = []
    for i in range(n):
        w, g = arrays[2 * i], arrays[2 * i + 1]
        outs.append(sgd_update(w, g, lr=lrs[i], wd=wds[i],
                               rescale_grad=rescale_grad,
                               clip_gradient=clip_gradient))
    return tuple(outs) if n > 1 else outs[0]


@register_op("multi_sgd_mom_update")
def multi_sgd_mom_update(*arrays, lrs=None, wds=None, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=1, **_):
    """Interleaved (w0, g0, m0, ...) — returns (w0', m0', w1', m1', ...)."""
    n = int(num_weights)
    lrs = _csv_floats("lrs", lrs, n)
    wds = _csv_floats("wds", wds, n)
    outs = []
    for i in range(n):
        w, g, m = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        nw, nm = sgd_mom_update(w, g, m, lr=lrs[i], momentum=momentum,
                                wd=wds[i], rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient)
        outs.extend([nw, nm])
    return tuple(outs)


@register_op("multi_mp_sgd_update")
def multi_mp_sgd_update(*arrays, lrs=None, wds=None, rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=1, **_):
    """Interleaved (w0, g0, w32_0, ...) — returns (w0', w32_0', ...)."""
    n = int(num_weights)
    lrs = _csv_floats("lrs", lrs, n)
    wds = _csv_floats("wds", wds, n)
    outs = []
    for i in range(n):
        w, g, w32 = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        nw, nw32 = mp_sgd_update(w, g, w32, lr=lrs[i], wd=wds[i],
                                 rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient)
        outs.extend([nw, nw32])
    return tuple(outs)


@register_op("multi_mp_sgd_mom_update")
def multi_mp_sgd_mom_update(*arrays, lrs=None, wds=None, momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=1, **_):
    """Interleaved (w0, g0, m0, w32_0, ...) — returns
    (w0', m0', w32_0', ...)."""
    n = int(num_weights)
    lrs = _csv_floats("lrs", lrs, n)
    wds = _csv_floats("wds", wds, n)
    outs = []
    for i in range(n):
        w, g, m, w32 = arrays[4 * i:4 * i + 4]
        nw, nm, nw32 = mp_sgd_mom_update(
            w, g, m, w32, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        outs.extend([nw, nm, nw32])
    return tuple(outs)


@register_op("multi_sum_sq", aliases=("_contrib_multi_sum_sq",))
def multi_sum_sq(*arrays, num_arrays=1, **_):
    """Per-tensor sum of squares, one scalar each (reference:
    contrib/multi_sum_sq.cc — feeds the LAMB/LARS trust ratios)."""
    n = int(num_arrays)
    outs = tuple(jnp.sum(jnp.square(a.astype(jnp.float32)))
                 for a in arrays[:n])
    return outs if n > 1 else outs[0]


# ---------------------------------------------------------------------------
# AMP cast ops (reference: src/operator/tensor/amp_cast.cc)
# ---------------------------------------------------------------------------

_DTYPES = {"float16": jnp.float16, "bfloat16": jnp.bfloat16,
           "float32": jnp.float32, "float64": jnp.float64}


@register_op("amp_cast", schema=Schema(
    dtype=Field(str, "float32", "Target dtype.",
                choices=tuple(_DTYPES))))
def amp_cast(data, dtype="float32"):
    """Identity-with-cast used by the AMP graph pass (reference:
    amp_cast.cc AMPCast; gradient casts back — here jax.vjp gives that
    for free since the cast is linear). On TPU the low dtype is bfloat16."""
    return data.astype(_DTYPES[dtype])


@register_op("amp_multicast")
def amp_multicast(*data, num_outputs=1, cast_narrow=False, **_):
    """Cast all inputs to their common widest dtype (narrowest with
    ``cast_narrow``) — reference: amp_cast.cc AMPMultiCast."""
    n = int(num_outputs)
    arrs = data[:n]
    widths = {jnp.float16: 16, jnp.bfloat16: 16, jnp.float32: 32,
              jnp.float64: 64}
    key = min if cast_narrow else max
    target = key((a.dtype for a in arrs),
                 key=lambda d: widths.get(jnp.dtype(d).type, 32))
    outs = tuple(a.astype(target) for a in arrs)
    return outs if n > 1 else outs[0]

"""Ops created by the subgraph partitioner (``mx.subgraph``).

Registered eagerly with the rest of the op library so partitioned graphs
load and evaluate in a fresh process (``sym.load`` of a saved partitioned
JSON must not depend on ``mx.subgraph`` having been imported).

Reference: the fused node created by ``SubgraphProperty::CreateSubgraphNode``
(src/operator/subgraph/subgraph_property.h) and the oneDNN FC+eltwise
post-op fusion (src/operator/subgraph/mkldnn/mkldnn_fc_property.h).
"""
from __future__ import annotations

from .registry import register_op


@register_op("_subgraph_exec")
def _subgraph_exec_op(*arrays, sub=None, n_outs=1, prop=None, **_):
    """Evaluate an embedded subgraph spec (``sub`` wire format shared with
    the control-flow ops). Differentiable end-to-end: the body is ordinary
    traced jnp; XLA fuses it into the surrounding computation."""
    from .. import symbol as S
    res = S._eval_graph(S.Group(list(sub["roots"])),
                        list(sub["arg_names"]), list(arrays))
    res = [S._primary(r) for r in res] if isinstance(res, list) else [res]
    return tuple(res) if int(n_outs) > 1 else res[0]


@register_op("_sg_dense_act")
def _sg_dense_act_op(data, weight, bias=None, num_hidden=None, no_bias=False,
                     flatten=True, act_type="relu", **_):
    """Fused Dense+activation (in-tree ``DENSE_ACT`` backend): one op node,
    one jnp composition — XLA emits a single MXU matmul with the activation
    fused into its epilogue."""
    from . import nn as _nn
    y = _nn.fully_connected(data, weight, bias, num_hidden=num_hidden,
                            no_bias=no_bias, flatten=flatten)
    return _nn.activation(y, act_type=act_type)

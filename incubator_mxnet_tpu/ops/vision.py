"""Spatial/vision ops: SpatialTransformer family, Correlation, Crop,
batch_take, MakeLoss.

Reference parity: ``src/operator/spatial_transformer.cc`` +
``grid_generator.cc`` + ``bilinear_sampler.cc`` (STN, Jaderberg et al.),
``src/operator/correlation.cc`` (FlowNet correlation),
``src/operator/crop.cc``, ``src/operator/tensor/indexing_op.cc
(batch_take)``, ``src/operator/make_loss.cc``.

TPU-native design: everything is fixed-shape gather/einsum compositions —
the bilinear sampler is a vectorized 4-tap gather (no per-pixel kernel), the
correlation op materializes the displacement axis as one batched shifted
product (one fused XLA loop over a static displacement grid).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

__all__ = ["grid_generator", "bilinear_sampler", "spatial_transformer",
           "correlation", "crop", "batch_take", "make_loss"]


@register_op("GridGenerator", aliases=("grid_generator",))
def grid_generator(data, transform_type: str = "affine", target_shape=(0, 0),
                   **_):
    """Sampling-grid generation (reference: grid_generator.cc).

    affine: data (N, 6) row-major 2×3 affine θ → grid (N, 2, H, W) of
    (x, y) source coords in [-1, 1] over the target raster.
    warp: data (N, 2, H, W) flow in PIXELS → identity grid + normalized flow.
    """
    if transform_type == "affine":
        N = data.shape[0]
        H, W = int(target_shape[0]), int(target_shape[1])
        theta = data.reshape(N, 2, 3).astype(jnp.float32)
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        src = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], 0)  # (3, HW)
        out = jnp.einsum("nij,jk->nik", theta, src)                 # (N,2,HW)
        return out.reshape(N, 2, H, W).astype(data.dtype)
    if transform_type == "warp":
        N, _, H, W = data.shape
        flow = data.astype(jnp.float32)
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        # pixel flow → normalized displacement (reference convention)
        nx = gx[None] + flow[:, 0] * (2.0 / max(W - 1, 1))
        ny = gy[None] + flow[:, 1] * (2.0 / max(H - 1, 1))
        return jnp.stack([nx, ny], 1).astype(data.dtype)
    raise ValueError(f"GridGenerator: unknown transform_type {transform_type!r}")


@register_op("BilinearSampler", aliases=("bilinear_sampler",))
def bilinear_sampler(data, grid, **_):
    """Bilinear sampling of data (N, C, H, W) at grid (N, 2, Ho, Wo) of
    normalized (x, y) in [-1, 1]; zeros outside (reference:
    bilinear_sampler.cc border handling)."""
    N, C, H, W = data.shape
    gx = (grid[:, 0].astype(jnp.float32) + 1.0) * (W - 1) / 2.0
    gy = (grid[:, 1].astype(jnp.float32) + 1.0) * (H - 1) / 2.0

    def sample_one(img, x, y):
        x0, y0 = jnp.floor(x), jnp.floor(y)
        wx, wy = x - x0, y - y0

        def at(yy, xx):
            inside = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            v = img[:, yi, xi]                           # (C, Ho, Wo)
            return jnp.where(inside[None], v, 0.0)

        return (at(y0, x0) * (1 - wy) * (1 - wx)
                + at(y0, x0 + 1) * (1 - wy) * wx
                + at(y0 + 1, x0) * wy * (1 - wx)
                + at(y0 + 1, x0 + 1) * wy * wx)

    out = jax.vmap(sample_one)(data.astype(jnp.float32), gx, gy)
    return out.astype(data.dtype)


@register_op("SpatialTransformer", aliases=("spatial_transformer",))
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type: str = "affine",
                        sampler_type: str = "bilinear", **_):
    """STN forward: grid from the localization net output + bilinear
    sampling (reference: spatial_transformer.cc)."""
    if sampler_type != "bilinear":
        raise ValueError("SpatialTransformer supports sampler_type='bilinear'")
    grid = grid_generator(loc, transform_type=transform_type,
                          target_shape=target_shape)
    return bilinear_sampler(data, grid)


@register_op("Correlation", aliases=("correlation",))
def correlation(data1, data2, kernel_size: int = 1,
                max_displacement: int = 1, stride1: int = 1,
                stride2: int = 1, pad_size: int = 0,
                is_multiply: bool = True, **_):
    """FlowNet correlation layer (reference: correlation.cc). Output
    channel d = mean over the kernel window and input channels of
    data1 · shift(data2, displacement_d); displacements form a
    (2·⌊max_displacement/stride2⌋ + 1)² grid, and the output raster is the
    reference's border-trimmed geometry: spatial size
    ⌈(W + 2·pad − 2·border)/stride1⌉ with border = max_displacement +
    (kernel_size−1)/2. The displacement axis is ONE ``vmap`` over a static
    offset table (graph size O(1) in the displacement count)."""
    N, C, H, W = data1.shape
    x1 = jnp.pad(data1.astype(jnp.float32),
                 ((0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size)))
    x2 = jnp.pad(data2.astype(jnp.float32),
                 ((0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size)))
    d_max = max_displacement // stride2 * stride2
    offs = jnp.arange(-d_max, d_max + 1, stride2)
    dyx = jnp.stack(jnp.meshgrid(offs, offs, indexing="ij"),
                    -1).reshape(-1, 2)                   # (D², 2) [dy, dx]
    Hp, Wp = x1.shape[2], x1.shape[3]
    ys = jnp.arange(Hp)
    xs = jnp.arange(Wp)

    def one_disp(d):
        dy, dx = d[0], d[1]
        shifted = jnp.roll(x2, shift=(-dy, -dx), axis=(2, 3))
        valid = ((ys + dy >= 0) & (ys + dy < Hp))[:, None] & \
                ((xs + dx >= 0) & (xs + dx < Wp))[None, :]
        prod = x1 * shifted if is_multiply else -jnp.abs(x1 - shifted)
        return prod.mean(axis=1) * valid[None]           # (N, Hp, Wp)

    out = jax.vmap(one_disp)(dyx)                        # (D², N, Hp, Wp)
    out = jnp.transpose(out, (1, 0, 2, 3))
    k = kernel_size
    if k > 1:
        window = (1, 1, k, k)
        out = lax.reduce_window(out, 0.0, lax.add, window, (1, 1, 1, 1),
                                "SAME") / (k * k)
    border = max_displacement + (kernel_size - 1) // 2
    out = out[:, :, border:Hp - border:stride1, border:Wp - border:stride1]
    return out.astype(data1.dtype)


@register_op("Crop", aliases=("crop_like",))
def crop(data, shape_like=None, offset=(0, 0), h_w=(0, 0),
         center_crop: bool = False, **_):
    """Legacy Crop (reference: crop.cc): crop data's trailing two dims to
    ``h_w`` — or to ``shape_like``'s spatial shape when given."""
    H, W = data.shape[-2], data.shape[-1]
    if shape_like is not None:
        th, tw = shape_like.shape[-2], shape_like.shape[-1]
    else:
        th, tw = h_w
        if th == 0 or tw == 0:
            raise ValueError("Crop needs h_w or a shape_like input")
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = offset
    return data[..., oy:oy + th, ox:ox + tw]


@register_op("batch_take")
def batch_take(a, indices, **_):
    """out[i] = a[i, indices[i]] (reference: indexing_op.cc BatchTake)."""
    idx = indices.astype(jnp.int32)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _make_loss(data, valid_count, grad_scale, normalization, dtype):
    return data


def _make_loss_fwd(data, valid_count, grad_scale, normalization, dtype):
    return data, (data.shape, valid_count)


def _make_loss_bwd(grad_scale, normalization, dtype, res, g):
    shape, valid_count = res
    scale = jnp.asarray(grad_scale, jnp.float32)
    if normalization == "batch":
        scale = scale / shape[0]
    elif normalization == "valid":
        scale = scale / jnp.maximum(valid_count, 1.0)
    # the reference ignores the incoming head gradient: MakeLoss IS a head
    return (jnp.full(shape, scale).astype(dtype), None)


_make_loss.defvjp(_make_loss_fwd, _make_loss_bwd)


@register_op("MakeLoss", aliases=("make_loss",))
def make_loss(data, grad_scale: float = 1.0, valid_thresh: float = 0.0,
              normalization: str = "null", **_):
    """Loss-head marker (reference: make_loss.cc): forward is identity,
    backward seeds the gradient with ``grad_scale`` — divided by the batch
    size ('batch') or by the count of elements above ``valid_thresh``
    ('valid') — ignoring any incoming head gradient."""
    if normalization not in ("null", "batch", "valid"):
        raise ValueError(f"MakeLoss: unknown normalization {normalization!r}")
    valid_count = jnp.sum(
        (data > valid_thresh).astype(jnp.float32)) if \
        normalization == "valid" else jnp.asarray(1.0, jnp.float32)
    return _make_loss(data, valid_count, float(grad_scale), normalization,
                      jnp.dtype(data.dtype))

"""Operator registry with a declarative parameter-schema system.

TPU-native counterpart of the NNVM op registry (``NNVM_REGISTER_OP`` +
``FCompute`` attrs — SURVEY §2.4) plus the ``dmlc::Parameter`` /
``DMLC_DECLARE_FIELD`` op-param schema (SURVEY §5.6, e.g.
``src/operator/nn/convolution-inl.h (ConvolutionParam)``). Each op here is a
*pure JAX function* ``fn(*arrays, **params) -> array | tuple`` :

- ``FCompute``        ≙ the function body (jax.numpy/lax, compiled by XLA)
- ``FInferShape/Type``≙ JAX abstract evaluation (free)
- ``FGradient``       ≙ ``jax.vjp`` of the same function (free)
- name + aliases      ≙ the registered op name reflected into ``mx.nd.*``
                        (reference: ``python/mxnet/ndarray/register.py``)
- ``schema=``         ≙ the declarative kwargs spec: typed fields with
                        defaults/choices/ranges, validated + string-coerced on
                        every call (both frontends), reflected into generated
                        docstrings — what ``DMLC_DECLARE_FIELD(...)
                        .set_default(...).describe(...)`` does in the
                        reference, reflected there through
                        ``python/mxnet/ndarray/register.py``.

The ``mx.nd`` namespace wrappers (NDArray-level, autograd-recording) are
generated from this registry in ``ndarray/__init__.py``.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["OpDef", "register_op", "OPS", "alias_op", "Field", "Schema",
           "Shape", "REQUIRED"]


class _Required:
    def __repr__(self):  # pragma: no cover
        return "<required>"


#: Sentinel for fields with no default (dmlc: field without set_default).
REQUIRED = _Required()


class Shape(tuple):
    """Marker type for tuple-of-int params (dmlc ``TShape``). Accepts int,
    sequence, or the string form ``"(3, 3)"`` the reference's frontends emit."""


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return bool(v)
    if isinstance(v, str):
        s = v.strip().lower()
        if s in ("true", "1", "yes"):
            return True
        if s in ("false", "0", "no", ""):
            return False
    raise ValueError(f"cannot interpret {v!r} as bool")


def _parse_shape(v) -> Optional[tuple]:
    if v is None:
        return None
    if isinstance(v, int):
        return (v,)
    if isinstance(v, str):
        s = v.strip().strip("()[]")
        if not s:
            return ()
        return tuple(int(p) for p in s.replace(",", " ").split())
    return tuple(int(p) for p in v)


class Field:
    """One declared op parameter (dmlc ``DMLC_DECLARE_FIELD`` analog).

    ``ftype`` is one of ``int float bool str`` or :class:`Shape`; values are
    coerced (including from the string forms symbolic frontends ship) and
    range/choice-checked. ``default=REQUIRED`` makes the field mandatory.
    """

    __slots__ = ("ftype", "default", "describe", "choices", "ge", "le",
                 "nullable")

    def __init__(self, ftype, default=REQUIRED, describe: str = "",
                 choices: Optional[Sequence] = None, ge=None, le=None,
                 nullable: bool = False):
        self.ftype = ftype
        self.default = default
        self.describe = describe
        self.choices = tuple(choices) if choices is not None else None
        self.ge = ge
        self.le = le
        self.nullable = nullable or default is None

    def coerce(self, opname: str, name: str, v):
        if v is None:
            if self.nullable:
                return None
            raise ValueError(
                f"{opname}: parameter '{name}' must not be None")
        if self.ftype is object:   # passthrough (tensor-valued / any)
            return v
        try:
            if self.ftype is bool:
                v = _parse_bool(v)
            elif self.ftype is Shape:
                v = _parse_shape(v)
            elif self.ftype is int:
                if isinstance(v, bool):
                    v = int(v)
                elif not isinstance(v, int):
                    if isinstance(v, str):
                        v = int(v.strip())
                    else:
                        iv = int(v)
                        if iv != v:  # dmlc rejects non-integral values
                            raise ValueError(f"non-integral value {v!r}")
                        v = iv
            elif self.ftype is float:
                v = float(v)
            elif self.ftype is str:
                v = str(v)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"{opname}: parameter '{name}' expects "
                f"{getattr(self.ftype, '__name__', self.ftype)}, got {v!r} "
                f"({e})") from None
        if self.choices is not None and v not in self.choices:
            raise ValueError(
                f"{opname}: parameter '{name}' must be one of "
                f"{list(self.choices)}, got {v!r}")
        if self.ge is not None and v < self.ge:
            raise ValueError(
                f"{opname}: parameter '{name}' must be >= {self.ge}, got {v!r}")
        if self.le is not None and v > self.le:
            raise ValueError(
                f"{opname}: parameter '{name}' must be <= {self.le}, got {v!r}")
        return v

    def doc_line(self, name: str) -> str:
        tname = getattr(self.ftype, "__name__", str(self.ftype))
        parts = [f"{name} : {tname}"]
        if self.default is REQUIRED:
            parts.append("required")
        else:
            parts.append(f"default={self.default!r}")
        if self.choices is not None:
            parts.append(f"choices={list(self.choices)}")
        head = ", ".join(parts)
        return f"    {head}\n        {self.describe}" if self.describe \
            else f"    {head}"


class Schema:
    """Declared parameter set for one op (dmlc ``Parameter`` struct analog).

    ``ignore`` lists kwargs accepted-and-dropped for reference API parity
    (e.g. cudnn knobs that have no TPU meaning). Unknown kwargs raise with
    the op name and the known-field list.
    """

    __slots__ = ("fields", "ignore")

    def __init__(self, ignore: Sequence[str] = (), **fields: Field):
        self.fields = fields
        self.ignore = frozenset(ignore) | {"name", "ctx"}

    def validate(self, opname: str, kwargs: Dict[str, Any],
                 skip: Sequence[str] = (),
                 input_names: Sequence[str] = ()) -> Dict[str, Any]:
        """Coerce/check ``kwargs``; fill defaults; raise on unknown/missing.

        ``skip`` names params already bound positionally at the call site —
        they are neither defaulted nor required-checked here (their values
        bypass string-coercion, the Python-API convention). ``input_names``
        are the op's tensor slots (fn params that are not schema fields):
        kwargs naming one pass through unvalidated — the standard MXNet
        keyword-input style, e.g. ``FullyConnected(data=x, weight=w)``.
        """
        out = {}
        for k, v in kwargs.items():
            if k in self.fields:
                out[k] = self.fields[k].coerce(opname, k, v)
            elif k in input_names:
                out[k] = v
            elif k not in self.ignore:
                raise TypeError(
                    f"{opname}: unknown parameter '{k}'. Known parameters: "
                    f"{sorted(self.fields)}")
        for k, f in self.fields.items():
            if k not in out and k not in skip:
                if f.default is REQUIRED:
                    raise TypeError(
                        f"{opname}: required parameter '{k}' is missing "
                        f"({f.describe or 'no description'})")
                out[k] = f.default
        return out

    def doc(self) -> str:
        lines = ["", "Parameters (declared schema)", "-" * 28]
        lines += [f.doc_line(n) for n, f in self.fields.items()]
        if self.ignore - {"name", "ctx"}:
            lines.append(
                f"    (accepted for API parity, ignored on TPU: "
                f"{sorted(self.ignore - {'name', 'ctx'})})")
        return "\n".join(lines)


class OpDef:
    __slots__ = ("name", "fn", "aliases", "module", "schema")

    def __init__(self, name: str, fn: Callable, aliases: Tuple[str, ...] = (),
                 schema: Optional[Schema] = None):
        self.name = name
        self.fn = fn
        self.aliases = aliases
        self.module = fn.__module__
        self.schema = schema


OPS: Dict[str, OpDef] = {}


def register_op(name: Optional[str] = None, aliases: Tuple[str, ...] = (),
                schema: Optional[Schema] = None):
    """Register a pure op. Usable as ``@register_op()`` or
    ``@register_op("name", aliases=("alias1",), schema=Schema(...))``.

    With a schema, keyword params are validated/coerced on every call (both
    the ``mx.nd`` and ``mx.sym`` frontends route through the wrapped fn) and
    the schema is appended to the op docstring.
    """

    def _do(fn: Callable) -> Callable:
        opname = name or fn.__name__
        body = fn
        if schema is not None:
            import inspect
            fn_argnames = tuple(inspect.signature(fn).parameters)
            # Tensor slots: fn params that are not schema fields (data,
            # weight, bias, ...) — addressable by keyword without tripping
            # the unknown-parameter check.
            input_names = tuple(n for n in fn_argnames
                                if n not in schema.fields)

            @functools.wraps(fn)
            def body(*args, _fn=fn, _schema=schema, _opname=opname, **kwargs):
                # A schema param bound positionally (e.g. softmax(x, length),
                # activation(x, "relu")) is neither defaulted, required-
                # checked, nor allowed to also arrive as a kwarg.
                bound = fn_argnames[:len(args)]
                for b in bound:
                    if b in kwargs:
                        raise TypeError(f"{_opname}: got multiple values for "
                                        f"parameter '{b}'")
                return _fn(*args, **_schema.validate(_opname, kwargs, bound,
                                                     input_names))
            body.__doc__ = (fn.__doc__ or "") + "\n" + schema.doc()
        opdef = OpDef(opname, body, tuple(aliases), schema=schema)
        OPS[opname] = opdef
        for a in aliases:
            OPS[a] = opdef
        return body

    return _do


def alias_op(existing: str, *names: str) -> None:
    opdef = OPS[existing]
    for n in names:
        OPS[n] = opdef
        # record on the OpDef so generated docs and the registry audit
        # (tests/test_op_schema.py) see alias_op names too
        if n not in opdef.aliases:
            opdef.aliases = opdef.aliases + (n,)

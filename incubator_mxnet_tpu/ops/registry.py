"""Operator registry.

TPU-native counterpart of the NNVM op registry (``NNVM_REGISTER_OP`` +
``FCompute`` attrs — SURVEY §2.4). Each op here is a *pure JAX function*
``fn(*arrays, **params) -> array | tuple`` :

- ``FCompute``        ≙ the function body (jax.numpy/lax, compiled by XLA)
- ``FInferShape/Type``≙ JAX abstract evaluation (free)
- ``FGradient``       ≙ ``jax.vjp`` of the same function (free)
- name + aliases      ≙ the registered op name reflected into ``mx.nd.*``
                        (reference: ``python/mxnet/ndarray/register.py``)

The ``mx.nd`` namespace wrappers (NDArray-level, autograd-recording) are
generated from this registry in ``ndarray/__init__.py``.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["OpDef", "register_op", "OPS", "alias_op"]


class OpDef:
    __slots__ = ("name", "fn", "aliases", "module")

    def __init__(self, name: str, fn: Callable, aliases: Tuple[str, ...] = ()):
        self.name = name
        self.fn = fn
        self.aliases = aliases
        self.module = fn.__module__


OPS: Dict[str, OpDef] = {}


def register_op(name: Optional[str] = None, aliases: Tuple[str, ...] = ()):
    """Register a pure op. Usable as ``@register_op()`` or
    ``@register_op("name", aliases=("alias1",))``."""

    def _do(fn: Callable) -> Callable:
        opname = name or fn.__name__
        opdef = OpDef(opname, fn, tuple(aliases))
        OPS[opname] = opdef
        for a in aliases:
            OPS[a] = opdef
        return fn

    return _do


def alias_op(existing: str, *names: str) -> None:
    opdef = OPS[existing]
    for n in names:
        OPS[n] = opdef

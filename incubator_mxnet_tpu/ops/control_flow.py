"""Control-flow operators: ``foreach`` / ``while_loop`` / ``cond``.

Reference counterpart: ``src/operator/control_flow.cc`` (``_foreach``,
``_while_loop``, ``_cond`` subgraph ops) surfaced through
``python/mxnet/ndarray/contrib.py`` and ``python/mxnet/symbol/contrib.py``
(SURVEY §2.4 contrib subtree) — the backbone of bucketed/dynamic RNN models.

TPU-native design (NOT a port of the reference's subgraph executor):

- ``_foreach``    ≙ ``lax.scan`` — one compiled loop, MXU-friendly, O(1)
  program size in the trip count.
- ``_while_loop`` ≙ a masked ``lax.scan`` over ``max_iterations`` ticks:
  XLA needs static shapes for the stacked per-step outputs, so the traced /
  symbolic form pads output rows beyond the executed steps with zeros
  (the reference's symbolic form also requires ``max_iterations`` for the
  same reason). The imperative NDArray form runs a true Python loop and
  returns exactly the executed steps — the reference's eager semantics.
- ``_cond``       ≙ ``lax.cond`` — both branches traced, one taken at run
  time; gradients flow through the taken branch only.

Imperative-vs-compiled dispatch mirrors the reference split: eager NDArray
calls with concrete inputs use Python control flow (gradients flow through
the tape to everything the body touches, including closed-over arrays);
under a ``hybridize()`` trace or symbolic execution the registered op
compiles to the ``lax`` primitive.

Stochastic bodies: under a traced ``foreach``/``while_loop`` an RNG key is
threaded through the scan carry automatically — each step draws from a
fresh subkey, so per-step dropout inside a compiled loop matches the
reference's eager per-step draws (src/resource.cc kRandom discipline).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as _onp
from jax import lax

from .registry import register_op

onp_asarray = _onp.asarray

__all__ = ["foreach", "while_loop", "cond",
           "sym_foreach", "sym_while_loop", "sym_cond"]


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _as_seq(x) -> Tuple[list, bool]:
    """Normalize NDArray-or-list to (list, was_single)."""
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def _repack(lst, single):
    return lst[0] if single else list(lst)


def _sub_step(sub: Dict[str, Any]):
    """jnp-level step callable from a symbolic subgraph spec:
    ``vals`` (placeholder order = sub['arg_names']) -> list of primaries of
    ``sub['roots']``."""
    from .. import symbol as S
    roots = S.Group(list(sub["roots"]))
    names = list(sub["arg_names"])

    def run(vals):
        return S._eval_graph(roots, names, list(vals))

    return run


def _scalar_bool(x):
    return jnp.reshape(x, ()).astype(bool)


def _loop_rng_key():
    """Per-loop RNG key when a ``trace_rng`` is active (hybridize trace),
    else None. Threading it through the scan carry gives every step a fresh
    subkey — without this, ``next_key()`` inside the body would split once
    at trace time and every step would reuse that one key (stale dropout
    masks; the reference's eager loop draws per step from the device
    stream, src/resource.cc kRandom)."""
    from .. import random as random_mod
    if random_mod._TRACE_RNG.stack:
        return random_mod._TRACE_RNG.stack[-1].split()
    return None


def _step_rng(sub_key):
    """Context manager installing ``sub_key`` as the body's RNG source."""
    from .. import random as random_mod
    return random_mod.trace_rng(sub_key)


# ---------------------------------------------------------------------------
# registered subgraph ops (probe-able in OPS, used by traced/symbolic paths)
# ---------------------------------------------------------------------------

@register_op("_foreach")
def _foreach_op(*arrays, body=None, sub=None, n_data=1, n_states=0,
                n_outs=1, **_):
    """Scan ``body`` over axis 0 of the data arrays (reference:
    src/operator/control_flow.cc ``_foreach``). Inputs are
    ``data x n_data, init_states x n_states, captured...``; outputs are the
    per-step outputs stacked along a new axis 0 followed by the final
    states. Lowered to one ``lax.scan``."""
    n_data, n_states, n_outs = int(n_data), int(n_states), int(n_outs)
    data = tuple(arrays[:n_data])
    states = tuple(arrays[n_data:n_data + n_states])
    capt = tuple(arrays[n_data + n_states:])
    if body is None:
        run = _sub_step(sub)

        def body(xs, st, cp):
            res = run(tuple(xs) + tuple(st) + tuple(cp))
            return tuple(res[:n_outs]), tuple(res[n_outs:])

    k0 = _loop_rng_key()
    if k0 is None:
        def scan_body(st, xs):
            outs, new_st = body(xs, st, capt)
            return tuple(new_st), tuple(outs)

        final, stacked = lax.scan(scan_body, states, data)
    else:
        def scan_body(carry, xs):
            st, key = carry
            key, subkey = jax.random.split(key)
            with _step_rng(subkey):
                outs, new_st = body(xs, st, capt)
            return (tuple(new_st), key), tuple(outs)

        (final, _), stacked = lax.scan(scan_body, (states, k0), data)
    return tuple(stacked) + tuple(final)


@register_op("_while_loop")
def _while_loop_op(*arrays, cond_fn=None, step_fn=None, sub=None,
                   n_states=1, n_outs=1, max_iterations=None, **_):
    """Bounded while loop (reference: control_flow.cc ``_while_loop``).
    Inputs ``loop_vars x n_states, captured...``; outputs are per-step
    outputs stacked over ``max_iterations`` ticks (rows beyond the executed
    steps are zero — XLA static shapes; the reference's symbolic form also
    fixes the output extent to max_iterations) followed by the final loop
    vars. Lowered to one masked ``lax.scan``."""
    if max_iterations is None:
        raise ValueError("_while_loop requires max_iterations")
    n_states, n_outs = int(n_states), int(n_outs)
    states = tuple(arrays[:n_states])
    capt = tuple(arrays[n_states:])
    if step_fn is None:
        n_cond = len(sub["cond_roots"])
        assert n_cond == 1
        run_cond = _sub_step({"roots": sub["cond_roots"],
                              "arg_names": sub["arg_names"]})
        run_step = _sub_step({"roots": sub["roots"],
                              "arg_names": sub["arg_names"]})

        def cond_fn(st, cp):
            return run_cond(tuple(st) + tuple(cp))[0]

        def step_fn(st, cp):
            res = run_step(tuple(st) + tuple(cp))
            return tuple(res[:n_outs]), tuple(res[n_outs:])

    def _masked(st, outs, new_st, ok):
        new_st = tuple(jnp.where(ok, n, o) for n, o in zip(new_st, st))
        outs = tuple(jnp.where(ok, o, jnp.zeros_like(o)) for o in outs)
        return new_st, outs

    k0 = _loop_rng_key()
    if k0 is None:
        def tick(carry, _):
            st, active = carry
            ok = jnp.logical_and(active, _scalar_bool(cond_fn(st, capt)))
            outs, new_st = step_fn(st, capt)
            new_st, outs = _masked(st, outs, new_st, ok)
            return (new_st, ok), tuple(outs)

        (final, _), stacked = lax.scan(
            tick, (states, jnp.asarray(True)), None,
            length=int(max_iterations))
    else:
        def tick(carry, _):
            (st, active), key = carry
            key, subkey = jax.random.split(key)
            with _step_rng(subkey):
                # cond draws under the same per-tick scope as the body
                # (consecutive splits), so stochastic conditions are fresh
                # each tick too
                ok = jnp.logical_and(active, _scalar_bool(cond_fn(st, capt)))
                outs, new_st = step_fn(st, capt)
            new_st, outs = _masked(st, outs, new_st, ok)
            return ((new_st, ok), key), tuple(outs)

        (((final, _), _), stacked) = lax.scan(
            tick, ((states, jnp.asarray(True)), k0), None,
            length=int(max_iterations))
    return tuple(stacked) + tuple(final)


@register_op("_cond")
def _cond_op(pred, *capt, then_branch=None, else_branch=None, sub=None,
             n_outs=1, **_):
    """Two-branch conditional (reference: control_flow.cc ``_cond``).
    ``pred`` is a scalar; both branches are traced, one executes at run time
    (``lax.cond``). Branch outputs must agree in count/shape/dtype."""
    n_outs = int(n_outs)
    p = _scalar_bool(pred)
    if then_branch is None:
        run_t = _sub_step({"roots": sub["then"], "arg_names": sub["arg_names"]})
        run_e = _sub_step({"roots": sub["else"], "arg_names": sub["arg_names"]})

        def then_branch(cp):
            return tuple(run_t(tuple(cp)))

        def else_branch(cp):
            return tuple(run_e(tuple(cp)))

    out = lax.cond(p, lambda c: tuple(then_branch(c)),
                   lambda c: tuple(else_branch(c)), tuple(capt))
    return out if len(out) > 1 else out[0]


# ---------------------------------------------------------------------------
# NDArray frontend (mx.nd.contrib / mx.contrib.nd)
# ---------------------------------------------------------------------------

def _is_traced(ndarrays) -> bool:
    return any(isinstance(a._data, jax.core.Tracer) for a in ndarrays)


def _unwrap_val(a):
    from ..ndarray import NDArray
    return a._data if isinstance(a, NDArray) else jnp.asarray(a)


def _wrap_step(call_body, ctx, n_states, fmt, who):
    """NDArray-level user body -> jnp-level step callable shared by the
    traced foreach/while paths (capt unused in the nd path: Python closures
    carry constants; under a trace, closed-over tracers are scan
    constants). ``call_body(xs_nd, st_nd) -> (out, new_states)``."""
    from ..ndarray import NDArray
    from .. import autograd

    def step(xs_vals, st_vals, _capt):
        xs = [NDArray(v, ctx=ctx) for v in xs_vals]
        st = [NDArray(v, ctx=ctx) for v in st_vals]
        with autograd.pause(train_mode=autograd.is_training()):
            out, new_st = call_body(xs, st)
        out_l, o_single = _as_seq(out if out is not None else [])
        new_l, _ = _as_seq(new_st)
        if len(new_l) != n_states:
            raise ValueError(f"{who}: body must preserve the number of "
                             f"states ({n_states}), got {len(new_l)}")
        fmt["o_single"] = o_single
        fmt["n_outs"] = len(out_l)
        return (tuple(_unwrap_val(a) for a in out_l),
                tuple(_unwrap_val(a) for a in new_l))

    return step


def foreach(body, data, init_states, name: str = "foreach"):
    """``mx.nd.contrib.foreach`` (reference:
    python/mxnet/ndarray/contrib.py foreach): run ``body(data_t, states)``
    over axis 0 of ``data``; returns (stacked outputs, final states).

    Concrete (non-traced) calls — recording or inference — run a Python
    loop, the reference's eager semantics exactly: imperative bodies may
    call ``.asnumpy()`` / branch on values / mutate closures, each step's
    side effects fire once, and gradients reach closed-over arrays through
    the tape. ``hybridize()``-traced calls compile to one ``lax.scan``
    (as does the T == 0 edge, where only a trace can learn the output
    shapes)."""
    from .. import ndarray as ndmod

    data_l, d_single = _as_seq(data)
    states_l, s_single = _as_seq(init_states)
    ctx = data_l[0].context
    traced = _is_traced(data_l + states_l)

    T = data_l[0].shape[0]
    if not traced and T > 0:
        # Python-loop path: reference-imperative semantics (matches the
        # concrete-input while_loop path)
        st = _repack(list(states_l), s_single)
        out_steps: List[list] = []
        o_single = True
        for t in range(T):
            xs = [d[t] for d in data_l]
            out, st = body(_repack(xs, d_single), st)
            if len(_as_seq(st)[0]) != len(states_l):
                raise ValueError(
                    f"foreach: body must preserve the number of states "
                    f"({len(states_l)}), got {len(_as_seq(st)[0])}")
            out_l, o_single = _as_seq(out if out is not None else [])
            out_steps.append(out_l)
        stacked = [ndmod.stack(*[row[i] for row in out_steps], axis=0)
                   for i in range(len(out_steps[0]))] if out_steps[0] else []
        final_l, _ = _as_seq(st)
        return (_repack(stacked, o_single) if stacked else [],
                _repack(list(final_l), s_single))

    return _foreach_scan(body, data_l, d_single, states_l, s_single, ctx)


def _foreach_scan(body, data_l, d_single, states_l, s_single, ctx):
    """The compiled foreach path: one ``lax.scan`` via the ``_foreach`` op."""
    from .. import ndarray as ndmod

    fmt: Dict[str, Any] = {}
    step = _wrap_step(
        lambda xs, st: body(_repack(xs, d_single), _repack(st, s_single)),
        ctx, len(states_l), fmt, "foreach")
    res = ndmod._foreach(*data_l, *states_l, body=step,
                         n_data=len(data_l), n_states=len(states_l))
    res = res if isinstance(res, (list, tuple)) else [res]
    n_outs = fmt["n_outs"]
    outs = list(res[:n_outs])
    states_out = list(res[n_outs:])
    return (_repack(outs, fmt["o_single"]),
            _repack(states_out, s_single))


def while_loop(cond, func, loop_vars, max_iterations: Optional[int] = None,
               name: str = "while_loop"):
    """``mx.nd.contrib.while_loop`` (reference:
    python/mxnet/ndarray/contrib.py while_loop): run
    ``func(*loop_vars) -> (step_output, new_loop_vars)`` while
    ``cond(*loop_vars)`` holds, at most ``max_iterations`` times; returns
    (stacked outputs, final loop vars).

    Eager calls run a Python loop whose stacked outputs have exactly
    ``steps_executed`` rows; traced calls compile to a masked ``lax.scan``
    whose output extent is ``max_iterations`` with zero rows beyond the
    executed steps (XLA static shapes — same constraint as the reference's
    symbolic form)."""
    from .. import ndarray as ndmod
    from ..ndarray import NDArray
    from .. import autograd

    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations")
    vars_l, v_single = _as_seq(loop_vars)
    ctx = vars_l[0].context

    if not _is_traced(vars_l):
        # Python-loop path (eager + recording): exact step count
        st = list(vars_l)
        out_steps: List[list] = []
        o_single = True
        steps = 0
        while steps < max_iterations:
            c = cond(*st)
            c = c.asnumpy() if isinstance(c, NDArray) else onp_asarray(c)
            if not bool(c.reshape(()).item()):
                break
            out, new_st = func(*st)
            out_l, o_single = _as_seq(out if out is not None else [])
            new_l, _ = _as_seq(new_st)
            if len(new_l) != len(st):
                raise ValueError("while_loop: func must preserve the number "
                                 "of loop_vars")
            out_steps.append(out_l)
            st = list(new_l)
            steps += 1
        if out_steps and out_steps[0]:
            stacked = [ndmod.stack(*[row[i] for row in out_steps], axis=0)
                       for i in range(len(out_steps[0]))]
            stacked = _repack(stacked, o_single)
        else:
            stacked = []
        return stacked, _repack(st, v_single)

    # traced: masked scan through the registered op
    fmt: Dict[str, Any] = {}
    wrapped = _wrap_step(lambda xs, st: func(*st), ctx, len(vars_l), fmt,
                         "while_loop")

    def step_fn(st_vals, _capt):
        return wrapped((), st_vals, _capt)

    def cond_fn(st_vals, _capt):
        from .. import autograd as ag
        st = [NDArray(v, ctx=ctx) for v in st_vals]
        with ag.pause(train_mode=ag.is_training()):
            r = cond(*st)
        return _unwrap_val(r)

    res = ndmod._while_loop(*vars_l, cond_fn=cond_fn, step_fn=step_fn,
                            n_states=len(vars_l),
                            max_iterations=int(max_iterations))
    res = res if isinstance(res, (list, tuple)) else [res]
    n_outs = fmt["n_outs"]
    outs = list(res[:n_outs])
    return (_repack(outs, fmt["o_single"]) if n_outs else [],
            _repack(list(res[n_outs:]), v_single))


def cond(pred, then_func, else_func, name: str = "cond"):
    """``mx.nd.contrib.cond`` (reference: python/mxnet/ndarray/contrib.py
    cond): if scalar ``pred`` (NDArray or zero-arg callable) is true run
    ``then_func()`` else ``else_func()``. Concrete predicates take a real
    Python branch (only that branch executes/records); traced predicates
    compile to ``lax.cond`` (both branches traced, one executed)."""
    from ..ndarray import NDArray
    from .. import autograd

    p = pred if isinstance(pred, NDArray) or not callable(pred) else pred()
    if not isinstance(p, NDArray):
        # plain python/numpy scalar: real branch
        return then_func() if bool(p) else else_func()
    if not _is_traced([p]):
        taken = then_func if bool(p.asnumpy().reshape(()).item()) \
            else else_func
        return taken()

    ctx = p.context
    fmt: Dict[str, Any] = {}

    def _branch(fn, tag):
        def run(_capt):
            with autograd.pause(train_mode=autograd.is_training()):
                out = fn()
            out_l, single = _as_seq(out)
            fmt[tag] = (single, len(out_l))
            return tuple(a._data if isinstance(a, NDArray) else jnp.asarray(a)
                         for a in out_l)
        return run

    from .. import ndarray as ndmod
    try:
        res = ndmod._cond(p, then_branch=_branch(then_func, "then"),
                          else_branch=_branch(else_func, "else"))
    except TypeError as e:
        # lax.cond's pytree-structure mismatch, translated (both branches
        # have traced by the time it compares out_trees, so fmt is full)
        if "then" in fmt and "else" in fmt and fmt["then"] != fmt["else"]:
            raise _cond_mismatch_error(fmt) from e
        raise
    if "then" in fmt and "else" in fmt and fmt["then"] != fmt["else"]:
        raise _cond_mismatch_error(fmt)
    res = res if isinstance(res, (list, tuple)) else [res]
    return _repack(list(res), fmt["then"][0])


def _cond_mismatch_error(fmt) -> ValueError:
    return ValueError(
        "cond: then/else branches disagree on output structure "
        f"(then: single={fmt['then'][0]}, n_outs={fmt['then'][1]}; "
        f"else: single={fmt['else'][0]}, n_outs={fmt['else'][1]}); "
        "return the same single-array-vs-list style from both branches")


# ---------------------------------------------------------------------------
# Symbol frontend (mx.sym.contrib / mx.contrib.sym)
# ---------------------------------------------------------------------------

def _free_vars(roots, bound_names):
    """Variable nodes reachable from ``roots`` that are not placeholders —
    the subgraph's captured inputs (reference contrib.py does the same
    free-variable lift when cutting the subgraph)."""
    from .. import symbol as S
    seen, out = set(), []
    for r in roots:
        for node in S._topo(r):
            if node._op is None and node._base is None \
                    and node._name not in bound_names \
                    and id(node) not in seen:
                seen.add(id(node))
                out.append(node)
    return out


def sym_foreach(body, data, init_states, name: str = "foreach"):
    """``mx.sym.contrib.foreach``: build the ``_foreach`` subgraph node.
    ``body(data_t, states) -> (outputs, new_states)`` is called once with
    placeholder Variables to cut the subgraph; its free variables become
    captured node inputs."""
    from .. import symbol as S
    data_l, d_single = _as_seq(data)
    states_l, s_single = _as_seq(init_states)
    data_ph = [S.Variable(f"{name}_data{i}") for i in range(len(data_l))]
    state_ph = [S.Variable(f"{name}_state{i}") for i in range(len(states_l))]
    out, new_st = body(_repack(list(data_ph), d_single),
                       _repack(list(state_ph), s_single))
    out_l, o_single = _as_seq(out if out is not None else [])
    new_l, _ = _as_seq(new_st)
    if len(new_l) != len(states_l):
        raise ValueError("foreach: body must preserve the number of states")
    ph_names = [p.name for p in data_ph] + [p.name for p in state_ph]
    capt = _free_vars(out_l + new_l, set(ph_names))
    sub = {"roots": out_l + new_l,
           "arg_names": ph_names + [c.name for c in capt]}
    node = S.Symbol("_foreach", [*data_l, *states_l, *capt],
                    attrs={"sub": sub, "n_data": len(data_l),
                           "n_states": len(states_l), "n_outs": len(out_l)},
                    name=name, num_outputs=len(out_l) + len(new_l))
    outs = [node[i] for i in range(len(out_l))]
    states_out = [node[len(out_l) + j] for j in range(len(new_l))]
    return (_repack(outs, o_single if out_l else True),
            _repack(states_out, s_single))


def sym_while_loop(cond, func, loop_vars, max_iterations: Optional[int] = None,
                   name: str = "while_loop"):
    """``mx.sym.contrib.while_loop``: build the ``_while_loop`` subgraph
    node. Outputs are stacked over ``max_iterations`` ticks (zero-padded
    beyond the executed steps)."""
    from .. import symbol as S
    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations")
    vars_l, v_single = _as_seq(loop_vars)
    ph = [S.Variable(f"{name}_var{i}") for i in range(len(vars_l))]
    pred = cond(*ph)
    out, new_st = func(*ph)
    out_l, o_single = _as_seq(out if out is not None else [])
    new_l, _ = _as_seq(new_st)
    if len(new_l) != len(vars_l):
        raise ValueError("while_loop: func must preserve the number of "
                         "loop_vars")
    ph_names = [p.name for p in ph]
    capt = _free_vars([pred] + out_l + new_l, set(ph_names))
    sub = {"roots": out_l + new_l, "cond_roots": [pred],
           "arg_names": ph_names + [c.name for c in capt]}
    node = S.Symbol("_while_loop", [*vars_l, *capt],
                    attrs={"sub": sub, "n_states": len(vars_l),
                           "n_outs": len(out_l),
                           "max_iterations": int(max_iterations)},
                    name=name, num_outputs=len(out_l) + len(new_l))
    outs = [node[i] for i in range(len(out_l))]
    states_out = [node[len(out_l) + j] for j in range(len(new_l))]
    return (_repack(outs, o_single if out_l else True),
            _repack(states_out, v_single))


def sym_cond(pred, then_func, else_func, name: str = "cond"):
    """``mx.sym.contrib.cond``: build the ``_cond`` subgraph node. ``pred``
    is a Symbol (or zero-arg callable returning one) evaluated in the outer
    graph; branch subgraphs capture their free variables."""
    from .. import symbol as S
    p = pred if isinstance(pred, S.Symbol) else pred()
    then_l, t_single = _as_seq(then_func())
    else_l, e_single = _as_seq(else_func())
    if len(then_l) != len(else_l):
        raise ValueError("cond: then/else branches must produce the same "
                         "number of outputs")
    capt = _free_vars(then_l + else_l, set())
    sub = {"then": then_l, "else": else_l,
           "arg_names": [c.name for c in capt]}
    node = S.Symbol("_cond", [p, *capt],
                    attrs={"sub": sub, "n_outs": len(then_l)},
                    name=name, num_outputs=len(then_l))
    outs = [node[i] for i in range(len(then_l))]
    return _repack(outs, t_single)

"""Tensor ops: elementwise, broadcast, reduce, matmul, shape, indexing, sort.

TPU-native counterpart of ``src/operator/tensor/`` (SURVEY §2.4:
``elemwise_binary_broadcast_op_basic.cc``, ``dot-inl.h``, ``matrix_op.cc``,
``indexing_op.cc``, ``ordering_op.cc``). Every op is a pure JAX function;
XLA provides the CPU/TPU kernels, fusion, and (via jax.vjp) the gradients
that the reference hand-registers per op.

MXNet semantic details preserved: ``reshape`` magic codes (0,-1,-2,-3,-4),
``dot``'s last-axis·first-axis contraction, ``topk``'s ret_typ modes,
``take``'s clip/wrap modes, 0/1-valued comparison outputs in input dtype.
"""
from __future__ import annotations

import builtins
import functools
import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, alias_op
from .registry import Field as _Field, Schema as _Schema, Shape as _TShape

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _axis_tuple(axis, ndim):
    if axis is None:
        return tuple(range(ndim))
    if isinstance(axis, int):
        return (axis,)
    return tuple(axis)


# ---------------------------------------------------------------------------
# unary elementwise (reference: elemwise_unary_op_basic.cc etc.)
# ---------------------------------------------------------------------------

def _unary(name, f, aliases=()):
    @register_op(name, aliases=aliases)
    def op(data, **_ignored):
        return f(data)
    op.__name__ = name
    return op


_unary("negative", lambda x: -x)
_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("round", jnp.round)
_unary("rint", jnp.rint)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.trunc)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: lax.rsqrt(x))
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("exp", jnp.exp)
_unary("expm1", jnp.expm1)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("reciprocal", lambda x: 1.0 / x)
_unary("erf", jax.scipy.special.erf)
_unary("erfinv", jax.scipy.special.erfinv)
_unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_unary("gammaln", jax.scipy.special.gammaln)
_unary("logical_not", lambda x: (x == 0).astype(x.dtype))
_unary("relu", lambda x: jnp.maximum(x, 0))
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", jax.nn.soft_sign)
_unary("softrelu", jax.nn.softplus, aliases=("softplus",))
_unary("hard_sigmoid", lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0))
_unary("identity", lambda x: x, aliases=("copy", "stop_gradient_identity", "BlockGrad_", ))


@register_op("BlockGrad", aliases=("stop_gradient",))
def block_grad(data):
    return lax.stop_gradient(data)


@register_op("make_loss")
def make_loss(data, grad_scale=1.0, **_):
    return data


@register_op("cast", aliases=("Cast",))
def cast(data, dtype="float32"):
    return data.astype(jnp.dtype(dtype))


@register_op("clip")
def clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


@register_op("isnan")
def isnan(data):
    return jnp.isnan(data)


@register_op("isinf")
def isinf(data):
    return jnp.isinf(data)


@register_op("isfinite")
def isfinite(data):
    return jnp.isfinite(data)


# ---------------------------------------------------------------------------
# binary broadcast (reference: elemwise_binary_broadcast_op_*.cc)
# ---------------------------------------------------------------------------

def _binary(name, f, aliases=()):
    @register_op(name, aliases=aliases)
    def op(lhs, rhs, **_ignored):
        return f(lhs, rhs)
    op.__name__ = name
    return op


_binary("add", jnp.add, aliases=("broadcast_add", "broadcast_plus", "elemwise_add", "__add__"))
_binary("subtract", jnp.subtract, aliases=("broadcast_sub", "broadcast_minus", "elemwise_sub"))
_binary("multiply", jnp.multiply, aliases=("broadcast_mul", "elemwise_mul"))
_binary("divide", jnp.divide, aliases=("broadcast_div", "elemwise_div"))
_binary("floor_divide", jnp.floor_divide)
_binary("mod", jnp.mod, aliases=("broadcast_mod",))
_binary("power", jnp.power, aliases=("broadcast_power", "pow"))
_binary("maximum", jnp.maximum, aliases=("broadcast_maximum",))
_binary("minimum", jnp.minimum, aliases=("broadcast_minimum",))
_binary("hypot", jnp.hypot, aliases=("broadcast_hypot",))
_binary("arctan2", jnp.arctan2)


def _cmp(name, f, aliases=()):
    @register_op(name, aliases=aliases)
    def op(lhs, rhs, **_ignored):
        dt = jnp.result_type(lhs, rhs)
        if dt == jnp.bool_:
            dt = jnp.float32
        return f(lhs, rhs).astype(dt)
    op.__name__ = name
    return op


_cmp("equal", jnp.equal, aliases=("broadcast_equal",))
_cmp("not_equal", jnp.not_equal, aliases=("broadcast_not_equal",))
_cmp("greater", jnp.greater, aliases=("broadcast_greater",))
_cmp("greater_equal", jnp.greater_equal, aliases=("broadcast_greater_equal",))
_cmp("lesser", jnp.less, aliases=("broadcast_lesser", "less"))
_cmp("lesser_equal", jnp.less_equal, aliases=("broadcast_lesser_equal", "less_equal"))
_cmp("logical_and", lambda a, b: (a != 0) & (b != 0), aliases=("broadcast_logical_and",))
_cmp("logical_or", lambda a, b: (a != 0) | (b != 0), aliases=("broadcast_logical_or",))
_cmp("logical_xor", lambda a, b: (a != 0) ^ (b != 0), aliases=("broadcast_logical_xor",))


@register_op("add_n", aliases=("ElementWiseSum", "sum_n"))
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register_op("where")
def where(condition, x, y):
    return jnp.where(condition != 0 if condition.dtype != jnp.bool_ else condition, x, y)


# ---------------------------------------------------------------------------
# reductions (reference: broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------

@register_op("sum", aliases=("sum_axis",))
def sum(data, axis=None, keepdims=False, exclude=False, **_):
    axis = _excl(axis, exclude, data.ndim)
    return jnp.sum(data, axis=axis, keepdims=keepdims)


def _excl(axis, exclude, ndim):
    if not exclude:
        return axis
    ax = set(_axis_tuple(axis, ndim))
    return tuple(i for i in range(ndim) if i not in ax)


@register_op("nansum")
def nansum(data, axis=None, keepdims=False, **_):
    return jnp.nansum(data, axis=axis, keepdims=keepdims)


@register_op("mean")
def mean(data, axis=None, keepdims=False, exclude=False, **_):
    axis = _excl(axis, exclude, data.ndim)
    return jnp.mean(data, axis=axis, keepdims=keepdims)


@register_op("prod")
def prod(data, axis=None, keepdims=False, **_):
    return jnp.prod(data, axis=axis, keepdims=keepdims)


@register_op("nanprod")
def nanprod(data, axis=None, keepdims=False, **_):
    return jnp.nanprod(data, axis=axis, keepdims=keepdims)


@register_op("max", aliases=("max_axis",))
def max(data, axis=None, keepdims=False, **_):
    return jnp.max(data, axis=axis, keepdims=keepdims)


@register_op("min", aliases=("min_axis",))
def min(data, axis=None, keepdims=False, **_):
    return jnp.min(data, axis=axis, keepdims=keepdims)


@register_op("norm")
def norm(data, ord=2, axis=None, keepdims=False, **_):
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims))


@register_op("logsumexp")
def logsumexp(data, axis=None, keepdims=False, **_):
    return jax.scipy.special.logsumexp(data, axis=axis, keepdims=keepdims)


@register_op("argmax")
def argmax(data, axis=None, keepdims=False, **_):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register_op("argmin")
def argmin(data, axis=None, keepdims=False, **_):
    return jnp.argmin(data, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register_op("argmax_channel")
def argmax_channel(data, **_):
    return jnp.argmax(data, axis=1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# matmul family (reference: dot-inl.h — cuBLAS → MXU)
# ---------------------------------------------------------------------------

@register_op("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False, **_):
    """MXNet dot: contract lhs's last axis with rhs's first axis.
    transpose_a/b contract the *first* axis of lhs / *last* of rhs instead."""
    la = 0 if transpose_a else lhs.ndim - 1
    ra = rhs.ndim - 1 if transpose_b else 0
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=(la, ra))


@register_op("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False, **_):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register_op("matmul")
def matmul(a, b, **_):
    return jnp.matmul(a, b)


@register_op("khatri_rao")
def khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(-1, out.shape[-1])
    return out


# ---------------------------------------------------------------------------
# shape manipulation (reference: matrix_op.cc)
# ---------------------------------------------------------------------------

def _mx_reshape_shape(ishape: Tuple[int, ...], shape: Sequence[int]) -> Tuple[int, ...]:
    """MXNet reshape magic: 0 copy-dim, -1 infer, -2 copy-rest, -3 merge-two,
    -4 split (followed by two dims, one may be -1)."""
    out = []
    i = 0  # index into ishape
    j = 0  # index into shape spec
    shape = list(shape)
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(ishape[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(ishape[i:]); i = len(ishape)
        elif s == -3:
            out.append(ishape[i] * ishape[i + 1]); i += 2
        elif s == -4:
            d1, d2 = shape[j + 1], shape[j + 2]
            j += 2
            cur = ishape[i]; i += 1
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2])
        else:
            out.append(s); i += 1
        j += 1
    if out.count(-1) > 1:
        raise ValueError("reshape can infer at most one dimension")
    return tuple(out)


@register_op("reshape", aliases=("Reshape",))
def reshape(data, shape=None, reverse=False, **_):
    newshape = _mx_reshape_shape(data.shape, shape)
    return jnp.reshape(data, newshape)


@register_op("reshape_like")
def reshape_like(lhs, rhs, **_):
    return jnp.reshape(lhs, rhs.shape)


@register_op("transpose")
def transpose(data, axes=None, **_):
    if axes is not None and len(axes) == 0:
        axes = None
    return jnp.transpose(data, axes=axes)


@register_op("swapaxes", aliases=("SwapAxis",))
def swapaxes(data, dim1=0, dim2=0, **_):
    return jnp.swapaxes(data, dim1, dim2)


@register_op("flatten", aliases=("Flatten",))
def flatten(data, **_):
    return jnp.reshape(data, (data.shape[0], -1))


@register_op("expand_dims")
def expand_dims(data, axis=0, **_):
    return jnp.expand_dims(data, axis)


@register_op("squeeze")
def squeeze(data, axis=None, **_):
    return jnp.squeeze(data, axis=axis)


@register_op("broadcast_to")
def broadcast_to(data, shape=None, **_):
    tgt = tuple(d if s == 0 else s for s, d in zip(shape, data.shape)) if len(shape) == data.ndim else tuple(shape)
    return jnp.broadcast_to(data, tgt)


@register_op("broadcast_like")
def broadcast_like(lhs, rhs, **_):
    return jnp.broadcast_to(lhs, rhs.shape)


@register_op("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=(), **_):
    axis = _axis_tuple(axis, data.ndim) if not isinstance(axis, tuple) else axis
    size = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register_op("slice", aliases=("crop",))
def slice(data, begin=None, end=None, step=None, **_):
    idx = []
    step = step or [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins.slice(b, e, s))
    return data[tuple(idx)]


@register_op("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None, **_):
    idx = [builtins.slice(None)] * data.ndim
    if end is not None and end < 0:
        end = data.shape[axis] + end
    idx[axis] = builtins.slice(begin, end)
    return data[tuple(idx)]


@register_op("slice_like")
def slice_like(data, shape_like, axes=(), **_):
    axes = axes or tuple(range(shape_like.ndim))
    idx = [builtins.slice(None)] * data.ndim
    for a in axes:
        idx[a] = builtins.slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register_op("take")
def take(a, indices, axis=0, mode="clip", **_):
    indices = indices.astype(jnp.int32)
    if mode == "wrap":
        indices = jnp.mod(indices, a.shape[axis])
        mode = "clip"
    return jnp.take(a, indices, axis=axis, mode=mode)


@register_op("pick", aliases=("choose_element_0index",))
def pick(data, index, axis=-1, keepdims=False, mode="clip", **_):
    index = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(index, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register_op("gather_nd")
def gather_nd(data, indices, **_):
    indices = indices.astype(jnp.int32)
    idx = tuple(indices[i] for i in range(indices.shape[0]))
    return data[idx]


@register_op("scatter_nd")
def scatter_nd(data, indices, shape=None, **_):
    indices = indices.astype(jnp.int32)
    out = jnp.zeros(tuple(shape), data.dtype)
    idx = tuple(indices[i] for i in range(indices.shape[0]))
    return out.at[idx].set(data)


@register_op("one_hot")
def one_hot(indices, depth=None, on_value=1.0, off_value=0.0, dtype="float32", **_):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register_op("concat", aliases=("Concat",))
def concat(*args, dim=1, **_):
    return jnp.concatenate(args, axis=dim)


@register_op("stack")
def stack(*args, axis=0, **_):
    return jnp.stack(args, axis=axis)


@register_op("split", aliases=("SliceChannel",))
def split(data, num_outputs=1, axis=1, squeeze_axis=False, **_):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register_op("split_v2")
def split_v2(data, indices_or_sections=1, axis=0, squeeze_axis=False, **_):
    parts = jnp.split(data, indices_or_sections, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register_op("tile")
def tile(data, reps=(), **_):
    return jnp.tile(data, reps)


@register_op("repeat")
def repeat(data, repeats=1, axis=None, **_):
    return jnp.repeat(data, repeats, axis=axis)


@register_op("pad", aliases=("Pad",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0, **_):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode=jmode, constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


@register_op("reverse", aliases=("flip",))
def reverse(data, axis=(), **_):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, axis=axis)


@register_op("roll")
def roll(data, shift=0, axis=None, **_):
    return jnp.roll(data, shift, axis=axis)


@register_op("diag")
def diag(data, k=0, **_):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register_op("depth_to_space")
def depth_to_space(data, block_size=1, **_):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register_op("space_to_depth")
def space_to_depth(data, block_size=1, **_):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register_op("ravel_multi_index")
def ravel_multi_index(data, shape=None, **_):
    idx = tuple(data[i].astype(jnp.int32) for i in range(data.shape[0]))
    return jnp.ravel_multi_index(idx, tuple(shape), mode="clip").astype(jnp.float32)


@register_op("unravel_index")
def unravel_index(data, shape=None, **_):
    outs = jnp.unravel_index(data.astype(jnp.int32), tuple(shape))
    return jnp.stack(outs, axis=0).astype(jnp.float32)


@register_op("shape_array")
def shape_array(data, **_):
    return jnp.array(data.shape, dtype=jnp.int32)


@register_op("size_array")
def size_array(data, **_):
    return jnp.array([data.size], dtype=jnp.int32)


# Source nodes behind mx.sym.zeros/ones (0 tensor inputs, shape in attrs).
# Registered so the symbol executor and mx.analysis's graph verifier see
# them as ordinary ops instead of unknown names (MX003).
@register_op("_sym_zeros", schema=_Schema(
    shape=_Field(_TShape, describe="Output shape."),
    dtype=_Field(str, "float32", "Output dtype."),
))
def _sym_zeros(shape, dtype="float32"):
    """Constant zeros source node (``mx.sym.zeros``)."""
    return jnp.zeros(tuple(shape), dtype)


@register_op("_sym_ones", schema=_Schema(
    shape=_Field(_TShape, describe="Output shape."),
    dtype=_Field(str, "float32", "Output dtype."),
))
def _sym_ones(shape, dtype="float32"):
    """Constant ones source node (``mx.sym.ones``)."""
    return jnp.ones(tuple(shape), dtype)


@register_op("zeros_like")
def zeros_like(data, **_):
    return jnp.zeros_like(data)


@register_op("ones_like")
def ones_like(data, **_):
    return jnp.ones_like(data)


@register_op("full_like")
def full_like(data, fill_value=0.0, **_):
    return jnp.full_like(data, fill_value)


# ---------------------------------------------------------------------------
# ordering ops (reference: ordering_op.cc, cub-based — XLA sort/top_k here)
# ---------------------------------------------------------------------------

@register_op("sort")
def sort(data, axis=-1, is_ascend=True, **_):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register_op("argsort")
def argsort(data, axis=-1, is_ascend=True, dtype="float32", **_):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.dtype(dtype))


@register_op("topk")
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32", **_):
    ax = axis if axis >= 0 else data.ndim + axis
    moved = jnp.moveaxis(data, ax, -1)
    src = -moved if is_ascend else moved
    vals, idx = lax.top_k(src, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax)
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx.astype(jnp.dtype(dtype))
    if ret_typ == "both":
        return vals, idx.astype(jnp.dtype(dtype))
    if ret_typ == "mask":
        mask = jnp.zeros(moved.shape, jnp.int32)
        mask = jnp.put_along_axis(mask, idx if not is_ascend else idx, 1, axis=-1, inplace=False) \
            if hasattr(jnp, "put_along_axis") else mask.at[..., :].set(0)
        onehot = jax.nn.one_hot(jnp.moveaxis(idx, ax, -1).astype(jnp.int32), moved.shape[-1], dtype=jnp.int32).sum(-2)
        return jnp.moveaxis(onehot, -1, ax).astype(data.dtype)
    raise ValueError(f"unknown ret_typ {ret_typ}")


# ---------------------------------------------------------------------------
# sequence ops (reference: sequence_*.cc; time-major, axis 0)
# ---------------------------------------------------------------------------

def _seq_mask(data, sequence_length, value, axis):
    # data: (T, B, ...) when axis==0, (B, T, ...) when axis==1
    T = data.shape[axis]
    steps = jnp.arange(T)
    if axis == 0:
        shape = (T, -1) + (1,) * (data.ndim - 2)
        mask = steps[:, None] < sequence_length[None, :].astype(jnp.int32)
        mask = mask.reshape((T,) + (sequence_length.shape[0],) + (1,) * (data.ndim - 2))
    else:
        mask = steps[None, :] < sequence_length[:, None].astype(jnp.int32)
        mask = mask.reshape((sequence_length.shape[0], T) + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register_op("SequenceMask", aliases=("sequence_mask",))
def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0, **_):
    if not use_sequence_length or sequence_length is None:
        return data
    return _seq_mask(data, sequence_length, value, axis)


@register_op("SequenceLast", aliases=("sequence_last",))
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0, **_):
    if not use_sequence_length or sequence_length is None:
        idx = [builtins.slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:
        return jax.vmap(lambda i, col: col[i], in_axes=(0, 1))(last, data)
    return jax.vmap(lambda i, row: row[i], in_axes=(0, 0))(last, data)


@register_op("SequenceReverse", aliases=("sequence_reverse",))
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0, **_):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)

    def rev_col(length, col):
        idx = jnp.where(steps < length, length - 1 - steps, steps)
        return col[idx]

    return jax.vmap(rev_col, in_axes=(0, 1), out_axes=1)(sequence_length.astype(jnp.int32), data)


# ---------------------------------------------------------------------------
# linalg namespace subset (reference: la_op.cc — cuSOLVER → XLA)
# ---------------------------------------------------------------------------

@register_op("linalg_gemm2")
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, **_):
    if transpose_a:
        A = jnp.swapaxes(A, -1, -2)
    if transpose_b:
        B = jnp.swapaxes(B, -1, -2)
    return alpha * jnp.matmul(A, B)


@register_op("linalg_gemm")
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, **_):
    if transpose_a:
        A = jnp.swapaxes(A, -1, -2)
    if transpose_b:
        B = jnp.swapaxes(B, -1, -2)
    return alpha * jnp.matmul(A, B) + beta * C


@register_op("linalg_potrf")
def linalg_potrf(A, **_):
    return jnp.linalg.cholesky(A)


@register_op("linalg_trsm")
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0, **_):
    import jax.scipy.linalg as jsl
    if rightside:
        X = jsl.solve_triangular(A, jnp.swapaxes(alpha * B, -1, -2),
                                 trans="T" if not transpose else "N", lower=lower)
        return jnp.swapaxes(X, -1, -2)
    return jsl.solve_triangular(A, alpha * B, trans="T" if transpose else "N", lower=lower)


@register_op("linalg_sumlogdiag")
def linalg_sumlogdiag(A, **_):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register_op("linalg_syrk")
def linalg_syrk(A, transpose=False, alpha=1.0, **_):
    At = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(At, A) if transpose else jnp.matmul(A, At))


@register_op("linalg_trmm")
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0, **_):
    """Triangular matmul (reference: la_op.cc linalg_trmm): only the
    triangular half of A participates, as in the BLAS trmm contract."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    out = jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B)
    return alpha * out


@register_op("tril")
def tril(data, k=0, **_):
    return jnp.tril(data, k=k)


@register_op("triu")
def triu(data, k=0, **_):
    return jnp.triu(data, k=k)


@register_op("all_finite")
def all_finite(data, init_output=True, **_):
    """1-element 1/0 array (reference: contrib/all_finite.cc — the AMP
    dynamic loss-scaler overflow probe)."""
    return jnp.isfinite(data).all().reshape((1,)).astype(jnp.float32)


@register_op("multi_all_finite")
def multi_all_finite(*arrays, num_arrays=1, init_output=True, **_):
    ok = jnp.array(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return ok.reshape((1,)).astype(jnp.float32)


@register_op("boolean_mask", aliases=("_contrib_boolean_mask",))
def boolean_mask(data, index, axis=0, **_):
    """Dynamic row filter (reference: contrib/boolean_mask.cc). Output shape
    depends on the mask VALUES, so the MASK must be concrete — eager-only
    with respect to `index`; inside jit/XLA (static shapes) use
    ``where``/``sequence_mask`` or pre-filter on host, the same restriction
    the reference documents for TPU-style backends. The concrete mask is
    frozen into static gather indices, so the op stays differentiable in
    `data` (autograd's vjp trace sees a plain take)."""
    if isinstance(index, jax.core.Tracer):
        raise ValueError(
            "boolean_mask has a data-dependent output shape and its mask "
            "cannot be traced/jitted; mask with where()/sequence_mask instead")
    import numpy as _np
    keep = jnp.asarray(_np.nonzero(_np.asarray(index) != 0)[0])
    return jnp.take(data, keep, axis=axis)


# the mask's values determine the output shape: keep it out of the autograd
# tape (trace constant) so the op stays differentiable in `data`
boolean_mask.static_tensor_inputs = ("index",)


@register_op("linalg_extractdiag")
def linalg_extractdiag(A, offset=0, **_):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register_op("linalg_makediag")
def linalg_makediag(A, offset=0, **_):
    return jax.vmap(jnp.diag)(A.reshape(-1, A.shape[-1])).reshape(A.shape[:-1] + (A.shape[-1], A.shape[-1])) if A.ndim > 1 else jnp.diag(A, k=offset)


# ---------------------------------------------------------------------------
# embedding (reference: indexing_op.cc Embedding)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _take_rows_onehot_grad(vocab: int, wdtype: str):
    """take-rows with the weight gradient computed as a one-hot MXU matmul:
    scatter-add serializes on the TPU vector unit, while [N, V]·[N, D] rides
    the MXU (fp32 accumulate). The one-hot operand is N·V bf16 in HBM — for
    BERT-base (N=4096, V=30522) ~250 MB of streaming traffic, well under one
    scatter-limited millisecond."""

    @jax.custom_vjp
    def take_rows(weight, idx):
        return jnp.take(weight, idx, axis=0)

    def fwd(weight, idx):
        return jnp.take(weight, idx, axis=0), idx

    def bwd(idx, g):
        flat_idx = idx.reshape(-1)
        flat_g = g.reshape(-1, g.shape[-1])
        onehot = jax.nn.one_hot(flat_idx, vocab, dtype=flat_g.dtype)
        dw = lax.dot_general(onehot, flat_g, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        return dw.astype(wdtype), None

    take_rows.defvjp(fwd, bwd)
    return take_rows


@register_op("Embedding", aliases=("embedding",))
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32", sparse_grad=False, **_):
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1)
    if os.environ.get("MXTPU_EMBED_ONEHOT_GRAD") == "1":
        return _take_rows_onehot_grad(weight.shape[0],
                                      str(weight.dtype))(weight, idx)
    return jnp.take(weight, idx, axis=0)


# ---------------------------------------------------------------------------
# np-compat additions (reference: tensor/ np ops — cumsum/cumprod/trace/kron/
# bincount/digamma)
# ---------------------------------------------------------------------------

@register_op("cumsum")
def cumsum(a, axis=None, dtype=None, **_):
    out = jnp.cumsum(a, axis=axis)
    return out.astype(dtype) if dtype else out


@register_op("cumprod")
def cumprod(a, axis=None, dtype=None, **_):
    out = jnp.cumprod(a, axis=axis)
    return out.astype(dtype) if dtype else out


@register_op("trace")
def trace(a, offset=0, axis1=0, axis2=1, **_):
    return jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2)


@register_op("kron")
def kron(a, b, **_):
    return jnp.kron(a, b)


@register_op("digamma")
def digamma(a, **_):
    return jax.scipy.special.digamma(a)


@register_op("bincount")
def bincount(a, weights=None, minlength=0, **_):
    """Histogram of non-negative ints. ``minlength`` doubles as the STATIC
    output length under jit (XLA needs static shapes); eager calls without
    it size the output from the data like numpy."""
    x = a.astype(jnp.int32).reshape(-1)
    try:
        # eager: numpy semantics — minlength is a FLOOR, the output grows
        # to hold the largest value (builtins.max: the module-level `max`
        # is the registered reduction op)
        length = builtins.max(int(minlength),
                              (int(jnp.max(x)) + 1) if x.size else 1)
    except jax.errors.ConcretizationTypeError:
        if not minlength:
            raise ValueError(
                "bincount under jit needs minlength= (static output shape)")
        length = int(minlength)  # jit: static cap, out-of-range dropped
    w = None if weights is None else weights.reshape(-1)
    return jnp.bincount(x, weights=w, minlength=length, length=length)


@register_op("index_copy", aliases=("_contrib_index_copy",))
def index_copy(old_tensor, index_vector, new_tensor, **_):
    """Copy rows of ``new_tensor`` into ``old_tensor`` at the positions
    named by ``index_vector`` (reference: contrib index_copy.cc — which
    rejects out-of-range indices; so does this, whenever the indices are
    concrete). Pure functional form: returns the updated array."""
    import numpy as _onp
    idx = index_vector.astype(jnp.int32).reshape(-1)
    n = old_tensor.shape[0]
    k = idx.shape[0]
    want = (k,) + tuple(old_tensor.shape[1:])
    if tuple(new_tensor.shape) != want:
        raise ValueError(
            f"index_copy: new_tensor shape {tuple(new_tensor.shape)} must "
            f"be (len(index),) + old_tensor.shape[1:] = {want}")
    try:
        bad = _onp.asarray((idx < 0) | (idx >= n))
        if bad.any():
            raise ValueError(
                f"index_copy: indices {_onp.asarray(idx)[bad].tolist()} out "
                f"of range for first dim {n}")
    except jax.errors.ConcretizationTypeError:
        pass  # traced: out-of-range rows are dropped (documented)
    # gather-based rebuild: per target row, the LAST matching update wins —
    # the reference's sequential-copy semantics, deterministic on every
    # backend (scatter with duplicate indices is implementation-defined)
    last_pos = jnp.full((n,), -1, jnp.int32).at[idx].max(
        jnp.arange(k, dtype=jnp.int32), mode="drop")
    picked = new_tensor.astype(old_tensor.dtype)[jnp.clip(last_pos, 0)]
    mask = (last_pos >= 0).reshape((n,) + (1,) * (old_tensor.ndim - 1))
    return jnp.where(mask, picked, old_tensor)


@register_op("index_array", aliases=("_contrib_index_array",))
def index_array(data, axes=None, **_):
    """Per-element index coordinates of ``data`` (reference: contrib
    index_array.cc): output shape ``data.shape + (len(axes),)`` holding
    each element's position along the selected ``axes`` (all axes when
    None). Integer dtype is int64 under ``jax_enable_x64``, else int32 —
    the framework-wide index convention."""
    nd_ = data.ndim
    if nd_ == 0:
        raise ValueError("index_array needs at least a 1-d input")
    if axes is None:
        sel = tuple(range(nd_))
    else:
        sel = []
        for a in axes:
            if not -nd_ <= a < nd_:
                raise ValueError(
                    f"index_array: axis {a} out of range for {nd_}-d input")
            sel.append(a + nd_ if a < 0 else a)
        if not sel:
            raise ValueError("index_array: axes must be non-empty")
    # build only the selected axes' coordinate planes (no full meshgrid)
    comps = [jnp.broadcast_to(
        jnp.arange(data.shape[a]).reshape(
            tuple(data.shape[a] if i == a else 1 for i in range(nd_))),
        data.shape) for a in sel]
    dt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return jnp.stack(comps, axis=-1).astype(dt)

"""Exponential-backoff retry policy — the kvstore client's resilience core.

Reference counterpart: ps-lite's van retried connects and resent on
timeout at the transport layer (``van.cc`` resender); the Python surface
never saw it. Here the policy is explicit, env-tunable, and shared by
every host-side networking path:

``MXNET_KVSTORE_RETRIES``      attempts after the first failure (default 5)
``MXNET_KVSTORE_RETRY_DELAY``  base backoff seconds (default 0.05; doubles
                               per attempt, capped at ``max_delay``)
``MXNET_KVSTORE_TIMEOUT``      per-socket-op timeout consumed by the
                               kvstore client itself (``async_ps.py``)

The helper is deliberately synchronous and jitter-free: deterministic
backoff keeps the chaos tests (seeded connection drops) reproducible.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Optional, Tuple, Type

from ..base import MXNetError

__all__ = ["RetryPolicy", "call_with_retry", "RetryExhausted"]


class RetryExhausted(MXNetError):
    """All attempts failed; ``.last`` holds the final exception."""

    def __init__(self, msg: str, last: Optional[BaseException] = None):
        super().__init__(msg)
        self.last = last


class RetryPolicy:
    """``retries`` re-attempts with ``base_delay * 2**k`` backoff."""

    def __init__(self, retries: int = 5, base_delay: float = 0.05,
                 max_delay: float = 2.0,
                 retry_on: Tuple[Type[BaseException], ...] = (
                     ConnectionError, OSError, EOFError, TimeoutError)):
        self.retries = int(retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.retry_on = retry_on

    @classmethod
    def from_env(cls, **overrides) -> "RetryPolicy":
        kw = {"retries": int(os.environ.get("MXNET_KVSTORE_RETRIES", "5")),
              "base_delay": float(os.environ.get(
                  "MXNET_KVSTORE_RETRY_DELAY", "0.05"))}
        kw.update(overrides)
        return cls(**kw)

    def delay(self, attempt: int) -> float:
        return min(self.base_delay * (2 ** attempt), self.max_delay)

    def attempts(self) -> int:
        return self.retries + 1


def call_with_retry(fn: Callable, policy: Optional[RetryPolicy] = None,
                    describe: str = "",
                    on_retry: Optional[Callable[[int, BaseException], None]] = None):
    """Run ``fn()`` under ``policy``; ``on_retry(attempt, exc)`` runs before
    each backoff sleep (the kvstore client reconnects there). Raises
    :class:`RetryExhausted` carrying the final exception."""
    policy = policy or RetryPolicy.from_env()
    last: Optional[BaseException] = None
    for attempt in range(policy.attempts()):
        try:
            return fn()
        except policy.retry_on as e:
            last = e
            if attempt >= policy.retries:
                break
            if on_retry is not None:
                try:
                    on_retry(attempt, e)
                except policy.retry_on:
                    pass  # reconnect itself failed; backoff and loop
            time.sleep(policy.delay(attempt))
    raise RetryExhausted(
        f"{describe or 'operation'} failed after {policy.attempts()} "
        f"attempt(s): {type(last).__name__}: {last}", last=last)

"""Step watchdog — flags steps that blow past a wall-clock deadline.

Reference counterpart: the reference engine's only hang story was
``MXNET_ENGINE_TYPE=NaiveEngine`` bisection after the fact. On TPU the
classic silent stall is a *recompile storm* (every step re-traces because a
static arg churns — seconds per step, no error anywhere), or a collective
waiting on a dead peer. The watchdog is a daemon timer armed around each
step: past ``deadline`` it fires ONCE for that step and dumps a diagnostic
— elapsed time, the block's live jit-compile count and most recent
signatures (from :mod:`..analysis.recompile`'s accounting), i.e. the "last
op" provenance a hung run needs — via ``warnings.warn`` and the
``flags`` list. The step is NOT killed: XLA dispatches cannot be safely
interrupted mid-flight; the watchdog's job is attribution, the recovery
decision stays with the caller (checkpoint + restart).

Usage (``ShardedTrainer(watchdog=Watchdog(deadline=30))`` does this for
you)::

    wd = fault.Watchdog(deadline=30.0)
    with wd.watch(step=trainer.num_update, block=net):
        trainer.step(x, y)
    if wd.flags: ...
"""
from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Callable, List, Optional

from ..lockcheck import make_lock

__all__ = ["Watchdog", "WatchdogFlag"]


class WatchdogFlag:
    """One deadline violation: step index, deadline, elapsed-at-fire, and
    the watched block's compile accounting at fire time."""

    def __init__(self, step: int, deadline: float, elapsed: float,
                 compiles: int, recent_signatures: List[str]):
        self.step = step
        self.deadline = deadline
        self.elapsed = elapsed
        self.compiles = compiles
        self.recent_signatures = recent_signatures

    def __str__(self):
        sig = (f"; {self.compiles} jit compiles, most recent "
               f"{self.recent_signatures[-1]}" if self.compiles else
               "; no compile recorded (likely blocked on data or a "
               "collective peer)")
        return (f"step {self.step} exceeded the {self.deadline:.1f}s "
                f"watchdog deadline ({self.elapsed:.1f}s elapsed{sig})")


class Watchdog:
    """Arms a timer per step; fires at most once per step.

    ``deadline``  seconds a step may take before flagging
    ``on_flag``   optional callback ``(WatchdogFlag)`` — alerting seam;
                  the default also ``warnings.warn``\\ s every flag
    """

    def __init__(self, deadline: float,
                 on_flag: Optional[Callable[[WatchdogFlag], None]] = None):
        self.deadline = float(deadline)
        self.on_flag = on_flag
        self.flags: List[WatchdogFlag] = []
        self._timer: Optional[threading.Timer] = None
        self._lock = make_lock("Watchdog._lock")

    # -- accounting ------------------------------------------------------
    @staticmethod
    def _compile_state(block: Any):
        log = []
        if block is not None:
            for b in Watchdog._blocks(block):
                log.extend(b.__dict__.get("_compile_log") or [])
        return len(log), [repr(s)[:120] for s in log[-3:]]

    @staticmethod
    def _blocks(block):
        yield block
        for child in getattr(block, "_children", {}).values():
            yield from Watchdog._blocks(child)

    def _fire(self, step: int, t0: float, block: Any) -> None:
        compiles, recent = self._compile_state(block)
        flag = WatchdogFlag(step, self.deadline, time.monotonic() - t0,
                            compiles, recent)
        with self._lock:
            self.flags.append(flag)
            del self.flags[:-100]
        # the hang dump goes through the telemetry bus too, so a stalled
        # job's diagnosis is in telemetry.snapshot() / the JSONL stream,
        # not only in a warning nobody captured
        from ..telemetry import events as _tele
        from ..telemetry import metrics as _tmetrics
        _tele.emit("watchdog", severity="warning", step=step,
                   deadline_s=self.deadline,
                   elapsed_s=round(flag.elapsed, 3),
                   compiles=compiles, recent_signatures=recent)
        _tmetrics.counter("mxtpu_watchdog_flags_total",
                          "Step-deadline violations").inc()
        # a tripped watchdog is a primary flight-recorder trigger: the
        # step is wedged and the operator's next move may be kill -9 —
        # capture the rings NOW, while they still exist
        from ..telemetry import flight as _flight
        _flight.dump("watchdog", step=step, deadline_s=self.deadline,
                     elapsed_s=round(flag.elapsed, 3),
                     compiles=compiles, recent_signatures=recent)
        warnings.warn(f"[fault.watchdog] {flag}")
        if self.on_flag is not None:
            self.on_flag(flag)

    # -- arming ----------------------------------------------------------
    class _Watch:
        def __init__(self, wd: "Watchdog", step: int, block: Any):
            self._wd, self._step, self._block = wd, step, block

        def __enter__(self):
            wd = self._wd
            t0 = time.monotonic()
            wd._timer = threading.Timer(
                wd.deadline, wd._fire, args=(self._step, t0, self._block))
            # Timer's ctor takes neither name nor daemon: set both as
            # attributes before start() so hang dumps and the lockcheck
            # timeline can attribute the firing thread
            wd._timer.name = f"mx-fault-watchdog-step{self._step}"
            wd._timer.daemon = True
            wd._timer.start()
            return wd

        def __exit__(self, *exc):
            t = self._wd._timer
            self._wd._timer = None
            if t is not None:
                t.cancel()

    def watch(self, step: int, block: Any = None) -> "Watchdog._Watch":
        """Context manager arming the deadline around one step."""
        return Watchdog._Watch(self, step, block)

    def __repr__(self):
        return (f"Watchdog(deadline={self.deadline}, "
                f"flags={len(self.flags)})")

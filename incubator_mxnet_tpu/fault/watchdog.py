"""Step watchdog — flags steps that blow past a wall-clock deadline.

Reference counterpart: the reference engine's only hang story was
``MXNET_ENGINE_TYPE=NaiveEngine`` bisection after the fact. On TPU the
classic silent stall is a *recompile storm* (every step re-traces because a
static arg churns — seconds per step, no error anywhere), or a collective
waiting on a dead peer. The watchdog is a daemon timer armed around each
step: past the deadline it fires ONCE for that step and dumps a diagnostic
— elapsed time, the block's live jit-compile count and most recent
signatures (from :mod:`..analysis.recompile`'s accounting), i.e. the "last
op" provenance a hung run needs — via ``warnings.warn`` and the
``flags`` list. The step is NOT killed: XLA dispatches cannot be safely
interrupted mid-flight; the watchdog's job is attribution, the recovery
decision stays with the caller (checkpoint + restart).

**Picking the deadline.** A fixed number calibrated for the ~40ms
dispatch-tax era reads as noise today: the compiled whole-step path runs
~0.7ms/step, so a deadline loose enough for the old dispatch overhead is
4-5 orders of magnitude above steady state and only ever catches total
wedges. Default (``deadline=None``) is therefore *adaptive*: each step's
deadline is ``ADAPTIVE_MULT`` (50×) the EMA of recent step wall time,
floored at ``ADAPTIVE_FLOOR_S`` so sub-millisecond steps don't arm a
hair-trigger, and the first steps (compile included) get
``WARMUP_DEADLINE_S`` of headroom. A 0.7ms step tripping means the step
really stalled (a recompile, a dead collective peer), not that the
constant drifted out of date. Pass an explicit ``deadline=`` seconds to
pin the old fixed behavior.

Usage (``ShardedTrainer(watchdog=Watchdog())`` does this for you)::

    wd = fault.Watchdog()                # adaptive deadline
    with wd.watch(step=trainer.num_update, block=net):
        trainer.step(x, y)
    if wd.flags: ...
"""
from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Callable, List, Optional

from ..lockcheck import make_lock

__all__ = ["Watchdog", "WatchdogFlag", "WARMUP_DEADLINE_S",
           "ADAPTIVE_MULT", "ADAPTIVE_FLOOR_S"]

#: adaptive-mode deadline while no steady-state sample exists yet — the
#: first step carries the XLA compile (seconds to minutes for a big step
#: graph), which must not read as a stall
WARMUP_DEADLINE_S = 300.0
#: adaptive-mode multiplier over the step-time EMA: 50× the 0.7ms fused
#: step is 35ms — still instant against a real stall, far above jitter
ADAPTIVE_MULT = 50.0
#: adaptive-mode floor: sub-millisecond steps keep a 2s deadline so GC
#: pauses / data hiccups don't page anyone
ADAPTIVE_FLOOR_S = 2.0
#: EMA smoothing for observed step wall times
_EMA_ALPHA = 0.2


class WatchdogFlag:
    """One deadline violation: step index, deadline, elapsed-at-fire, and
    the watched block's compile accounting at fire time."""

    def __init__(self, step: int, deadline: float, elapsed: float,
                 compiles: int, recent_signatures: List[str]):
        self.step = step
        self.deadline = deadline
        self.elapsed = elapsed
        self.compiles = compiles
        self.recent_signatures = recent_signatures

    def __str__(self):
        sig = (f"; {self.compiles} jit compiles, most recent "
               f"{self.recent_signatures[-1]}" if self.compiles else
               "; no compile recorded (likely blocked on data or a "
               "collective peer)")
        return (f"step {self.step} exceeded the {self.deadline:.1f}s "
                f"watchdog deadline ({self.elapsed:.1f}s elapsed{sig})")


class Watchdog:
    """Arms a timer per step; fires at most once per step.

    ``deadline``  seconds a step may take before flagging; ``None``
                  (default) = adaptive — ``ADAPTIVE_MULT`` × the EMA of
                  observed step time, floored at ``ADAPTIVE_FLOOR_S``,
                  with ``WARMUP_DEADLINE_S`` until the first completed
                  step seeds the EMA (compile headroom)
    ``on_flag``   optional callback ``(WatchdogFlag)`` — alerting seam;
                  the default also ``warnings.warn``\\ s every flag
    """

    def __init__(self, deadline: Optional[float] = None,
                 on_flag: Optional[Callable[[WatchdogFlag], None]] = None):
        self.deadline = None if deadline is None else float(deadline)
        self.on_flag = on_flag
        self.flags: List[WatchdogFlag] = []
        self._timer: Optional[threading.Timer] = None
        self._ema_s: Optional[float] = None
        self._warmup_seen = False    # adaptive: first watched step = compile
        self._lock = make_lock("Watchdog._lock")

    # -- adaptive deadline ----------------------------------------------
    def observe(self, wall_s: float) -> None:
        """Feed one completed step's wall time into the adaptive EMA
        (``watch`` does this automatically for unflagged steps)."""
        with self._lock:
            self._ema_s = (float(wall_s) if self._ema_s is None else
                           (1 - _EMA_ALPHA) * self._ema_s
                           + _EMA_ALPHA * float(wall_s))

    def deadline_for_step(self) -> float:
        """The deadline the next armed step runs under: the fixed value
        when one was given, else the recalibrated adaptive bound."""
        if self.deadline is not None:
            return self.deadline
        with self._lock:
            ema = self._ema_s
        if ema is None:
            return WARMUP_DEADLINE_S
        return max(ADAPTIVE_FLOOR_S, ADAPTIVE_MULT * ema)

    # -- accounting ------------------------------------------------------
    @staticmethod
    def _compile_state(block: Any):
        log = []
        if block is not None:
            for b in Watchdog._blocks(block):
                log.extend(b.__dict__.get("_compile_log") or [])
        return len(log), [repr(s)[:120] for s in log[-3:]]

    @staticmethod
    def _blocks(block):
        yield block
        for child in getattr(block, "_children", {}).values():
            yield from Watchdog._blocks(child)

    def _fire(self, step: int, t0: float, block: Any,
              deadline: float) -> None:
        compiles, recent = self._compile_state(block)
        flag = WatchdogFlag(step, deadline, time.monotonic() - t0,
                            compiles, recent)
        with self._lock:
            self.flags.append(flag)
            del self.flags[:-100]
        # the hang dump goes through the telemetry bus too, so a stalled
        # job's diagnosis is in telemetry.snapshot() / the JSONL stream,
        # not only in a warning nobody captured
        from ..telemetry import events as _tele
        from ..telemetry import metrics as _tmetrics
        _tele.emit("watchdog", severity="warning", step=step,
                   deadline_s=deadline,
                   elapsed_s=round(flag.elapsed, 3),
                   compiles=compiles, recent_signatures=recent)
        _tmetrics.counter("mxtpu_watchdog_flags_total",
                          "Step-deadline violations").inc()
        # a tripped watchdog is a primary flight-recorder trigger: the
        # step is wedged and the operator's next move may be kill -9 —
        # capture the rings NOW, while they still exist
        from ..telemetry import flight as _flight
        # membership rides in the trigger context: "is this hang a dead
        # peer?" is the FIRST multi-host triage question, and the lease
        # table answers it without waiting for the lease watchdog's own
        # bundle
        try:
            from ..parallel import elastic as _elastic
            membership = _elastic.snapshot()
        except Exception:  # noqa: BLE001 — a broken control plane must
            membership = None          # not mask the hang diagnosis
        _flight.dump("watchdog", step=step, deadline_s=deadline,
                     elapsed_s=round(flag.elapsed, 3),
                     compiles=compiles, recent_signatures=recent,
                     membership=membership)
        warnings.warn(f"[fault.watchdog] {flag}")
        if self.on_flag is not None:
            self.on_flag(flag)

    # -- arming ----------------------------------------------------------
    class _Watch:
        def __init__(self, wd: "Watchdog", step: int, block: Any):
            self._wd, self._step, self._block = wd, step, block
            self._t0 = 0.0
            self._deadline = 0.0

        def __enter__(self):
            wd = self._wd
            self._t0 = t0 = time.monotonic()
            self._deadline = wd.deadline_for_step()
            wd._timer = threading.Timer(
                self._deadline, wd._fire,
                args=(self._step, t0, self._block, self._deadline))
            # Timer's ctor takes neither name nor daemon: set both as
            # attributes before start() so hang dumps and the lockcheck
            # timeline can attribute the firing thread
            wd._timer.name = f"mx-fault-watchdog-step{self._step}"
            wd._timer.daemon = True
            wd._timer.start()
            return wd

        def __exit__(self, *exc):
            wd = self._wd
            t = wd._timer
            wd._timer = None
            if t is not None:
                t.cancel()
            elapsed = time.monotonic() - self._t0
            if wd.deadline is None and not wd._warmup_seen:
                # adaptive mode discards its FIRST watched step: that one
                # carries the XLA compile, and seeding the EMA with it
                # would leave deadlines at 50x compile time for dozens of
                # steps — exactly the stall-blindness being recalibrated
                # away
                wd._warmup_seen = True
            elif elapsed < self._deadline:
                # only clean steps recalibrate the adaptive bound — a
                # flagged stall must not stretch the next deadline
                wd.observe(elapsed)

    def watch(self, step: int, block: Any = None) -> "Watchdog._Watch":
        """Context manager arming the deadline around one step."""
        return Watchdog._Watch(self, step, block)

    def __repr__(self):
        dl = ("adaptive" if self.deadline is None
              else f"{self.deadline}")
        return f"Watchdog(deadline={dl}, flags={len(self.flags)})"

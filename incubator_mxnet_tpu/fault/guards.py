"""Step guards — anomaly detection with pluggable recovery policies.

Reference counterpart: the reference's only anomaly handling was AMP's
dynamic loss scaler (skip-update-on-overflow, ``amp/loss_scaler.py``);
everything else — a NaN loss from a bad batch, an exploding gradient —
silently poisoned the weights and the run was lost N steps later when
someone looked at the curves. Here the finite-check is a first-class,
jitted runtime feature: :func:`all_finite` fuses ``isfinite(...).all()``
over a whole pytree into one scalar read, and :class:`StepGuard` turns
that scalar into one of three policies:

``warn``               count + ``warnings.warn``, keep the (bad) update
``skip_and_rollback``  restore the last-good snapshot, drop the step
``halt``               raise :class:`NonFiniteError` with diagnostics

``ShardedTrainer(guard=...)`` owns the snapshot mechanics (device-side
copies every ``snapshot_every`` good steps — rollback must not depend on
the crashed step's donated buffers); the guard itself is trainer-agnostic
state so ``amp.LossScaler`` and custom loops share the same policy object.
"""
from __future__ import annotations

import warnings
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..lockcheck import make_lock

__all__ = ["StepGuard", "NonFiniteError", "all_finite", "POLICIES"]

POLICIES = ("warn", "skip_and_rollback", "halt")


class NonFiniteError(MXNetError):
    """A guarded step produced a non-finite loss/grad under ``halt``."""


@jax.jit
def _tree_finite(tree) -> jax.Array:
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
    ok = jnp.array(True)
    for l in leaves:
        ok = jnp.logical_and(ok, jnp.isfinite(l).all())
    return ok

# a NEW (shape, dtype)-structure through the jitted finite check is an
# extra XLA compile — noted on the process-wide ledger so "how many
# jitted graphs does one training step run" is answerable from the
# ledger alone (ShardedTrainer's fused whole-step capture folds this
# check into the step graph; only the unfused path lands entries here)
_SIG_LOCK = make_lock("guards._SIG_LOCK")
_SEEN_SIGS: set = set()


def all_finite(*trees) -> bool:
    """One fused device reduction over every inexact leaf of the given
    pytrees → a host bool (a single scalar transfer, however many arrays).
    Non-float leaves (int labels, step counters) are ignored. This is a
    SEPARATE jitted call — a training loop that wants the check for free
    uses the fused step's in-graph verdict instead."""
    leaves, treedef = jax.tree_util.tree_flatten(trees)
    sig = (str(treedef), tuple(
        (tuple(getattr(l, "shape", ()) or ()), str(getattr(l, "dtype", "?")))
        for l in leaves))
    with _SIG_LOCK:
        new = sig not in _SEEN_SIGS
        if new:
            _SEEN_SIGS.add(sig)
    if new:
        from ..telemetry import compile_log as _clog
        _clog.note("fault.guards.finite", sig)
    return bool(_tree_finite(trees))


class StepGuard:
    """Policy + counters for one training loop.

    ``policy``         one of :data:`POLICIES`
    ``grad_norm_limit`` optional float: a finite-but-huge global grad norm
                       (``> limit``) trips the guard exactly like a NaN
    ``snapshot_every`` how often (in good steps) the trainer refreshes its
                       rollback snapshot; 1 = every step (exact rollback),
                       larger values amortize the copies and roll back to
                       the most recent multiple
    ``max_consecutive`` under ``warn``/``skip_and_rollback``: after this
                       many consecutive bad steps the guard escalates to
                       :class:`NonFiniteError` anyway — an input pipeline
                       emitting NaNs forever should not spin silently
    ``on_trip``        optional callback ``(guard, info: dict)`` invoked on
                       every tripped step (metrics/logging seam)
    """

    def __init__(self, policy: str = "warn",
                 grad_norm_limit: Optional[float] = None,
                 snapshot_every: int = 1, max_consecutive: int = 25,
                 on_trip: Optional[Callable[["StepGuard", dict], None]] = None):
        if policy not in POLICIES:
            raise MXNetError(f"unknown guard policy {policy!r}; "
                             f"choose from {POLICIES}")
        if snapshot_every < 1:
            raise MXNetError("snapshot_every must be >= 1")
        self.policy = policy
        self.grad_norm_limit = grad_norm_limit
        self.snapshot_every = snapshot_every
        self.max_consecutive = max_consecutive
        self.on_trip = on_trip
        #: steps that tripped the guard (any policy)
        self.tripped = 0
        #: steps rolled back under skip_and_rollback
        self.skipped = 0
        self._consecutive = 0
        #: (step, reason) history, newest last (bounded)
        self.history: List[tuple] = []

    # -- decision -------------------------------------------------------
    def is_bad(self, loss_finite: bool, grad_norm: Optional[float]) -> Optional[str]:
        """Classify one step; returns a reason string or None if clean."""
        if not loss_finite:
            return "non-finite loss/grad"
        if grad_norm is not None and self.grad_norm_limit is not None:
            if not (grad_norm <= self.grad_norm_limit):  # NaN-safe compare
                return (f"global grad norm {grad_norm:.3e} exceeds limit "
                        f"{self.grad_norm_limit:.3e}")
        return None

    def decide(self, step: int, reason: str, detail: str = "") -> str:
        """Record a tripped step and return the action to take
        (``"keep"`` | ``"rollback"``; ``halt``/escalation raises)."""
        self.tripped += 1
        self._consecutive += 1
        self.history.append((step, reason))
        del self.history[:-50]
        info = {"step": step, "reason": reason, "policy": self.policy,
                "consecutive": self._consecutive, "detail": detail}
        if self.on_trip is not None:
            self.on_trip(self, info)
        # guard verdicts are telemetry: the escalation trail (warn →
        # rollback → halt) must be reconstructable after the run
        from ..telemetry import events as _tele
        from ..telemetry import metrics as _tmetrics
        _tele.emit("guard", severity="warning", step=step, reason=reason,
                   policy=self.policy, consecutive=self._consecutive,
                   detail=detail)
        _tmetrics.counter("mxtpu_guard_tripped_total",
                          "Guard-tripped steps", policy=self.policy).inc()
        msg = (f"[fault.guard] step {step}: {reason} "
               f"(policy={self.policy}, consecutive={self._consecutive})"
               + (f" {detail}" if detail else ""))
        if self.policy == "halt":
            self._flight_dump(info)
            raise NonFiniteError(msg)
        if self._consecutive > self.max_consecutive:
            self._flight_dump(info, escalated=True)
            raise NonFiniteError(
                msg + f"; {self._consecutive} consecutive bad steps exceeds "
                f"max_consecutive={self.max_consecutive}, halting anyway")
        warnings.warn(msg)
        if self.policy == "skip_and_rollback":
            self.skipped += 1
            return "rollback"
        return "keep"

    @staticmethod
    def _flight_dump(info: dict, escalated: bool = False) -> None:
        """A halting guard is about to take the process down — the last
        moment the event rings, trace ring, and ledger still exist."""
        from ..telemetry import flight as _flight
        _flight.dump("guard_halt", escalated=escalated, **info)

    def good_step(self) -> None:
        self._consecutive = 0

    def __repr__(self):
        return (f"StepGuard(policy={self.policy!r}, tripped={self.tripped}, "
                f"skipped={self.skipped})")
